"""Ring collective tests on the 8-device mesh: ppermute rings must agree
with XLA's built-in collectives, and the ring exchange path must equal the
auto exchange path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.models import MLP
from distributed_tensorflow_tpu.ops import sgd
from distributed_tensorflow_tpu.ops.collectives import (
    ring_all_gather,
    ring_all_mean,
    ring_all_reduce,
)
from distributed_tensorflow_tpu.parallel import AsyncDataParallel, make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh((8,), ("data",))


def test_ring_all_reduce_matches_psum(mesh):
    x = np.random.default_rng(0).random((8, 4, 128), dtype=np.float32)

    def f(x):
        err = jnp.max(jnp.abs(ring_all_reduce(x, "data") - jax.lax.psum(x, "data")))
        return err[None]

    errs = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    )(x)
    np.testing.assert_allclose(np.asarray(errs), 0.0, atol=1e-5)


def test_ring_all_mean(mesh):
    x = np.random.default_rng(1).random((8, 16), dtype=np.float32)

    def f(x):
        return ring_all_mean(x, "data")

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    )(x)
    want = np.broadcast_to(x.reshape(8, 1, 16).mean(axis=0), (8, 1, 16)).reshape(8, 16)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)


def test_ring_all_gather_matches_all_gather(mesh):
    x = np.random.default_rng(2).random((8, 8), dtype=np.float32)

    def f(x):
        ring = ring_all_gather(x, "data")  # [8, 1, 8]
        ref = jax.lax.all_gather(x, "data")
        err = jnp.max(jnp.abs(ring - ref))
        return err[None]

    errs = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    )(x)
    np.testing.assert_allclose(np.asarray(errs), 0.0, atol=1e-6)


def test_async_ring_exchange_matches_auto():
    mesh = make_mesh()
    strat = AsyncDataParallel(mesh, update_scale=1.0)
    model = MLP(compute_dtype=jnp.float32)
    opt = sgd(0.001)
    from distributed_tensorflow_tpu.ops import cross_entropy

    state = strat.init_state(model, opt, seed=1)
    step = strat.make_train_step(model, cross_entropy, opt)
    rng = np.random.default_rng(0)
    x = rng.random((800, 784), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 800)]
    state, _ = step(state, *strat.prepare_batch(x, y))

    auto = strat.make_exchange_fn("auto")(jax.tree.map(jnp.copy, state))
    ring = strat.make_exchange_fn("ring")(state)
    np.testing.assert_allclose(
        np.asarray(auto.params.w1), np.asarray(ring.params.w1), rtol=1e-5, atol=1e-7
    )
    # All copies identical after either exchange.
    w = np.asarray(ring.params.w1)
    np.testing.assert_allclose(w[0], w[7], rtol=1e-6)
