"""Language-model training + generation — the capability the reference never
had (its one model is the MLP classifier, reference tfsingle.py:23-42).

Run: ``python examples/lm.py [epochs] [max_new]``

Drives the full LM lifecycle through :class:`~train.lm_trainer.LMTrainer`
(the reference loop contract — Step/Cost/AvgTime lines, per-epoch held-out
perplexity, scanned-epoch fast path, optional checkpointing via
``DTF_LM_CKPT=dir``) on the synthetic copy task (sequences ``x · x`` — the
model must attend back and reproduce the first half), then generates from a
held-out prompt with the static-shape KV cache: greedy and sampled.
``DTF_LM_FLASH=1`` switches the causal attention to the Pallas flash
kernel.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.config import TrainConfig
from distributed_tensorflow_tpu.data import copy_corpus
from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.train import LMTrainer


def main(epochs: int = 8, max_new: int = 16) -> None:
    datasets = copy_corpus(num=4096, half_len=8, vocab=61, seed=0)
    model = GPTLM(
        vocab_size=61,
        max_len=48,
        model_dim=64,
        num_heads=4,
        num_layers=2,
        compute_dtype=jnp.float32,
        attention_impl="flash" if os.environ.get("DTF_LM_FLASH") else "xla",
        flash_min_len=0,  # demo corpus is toy-length; keep the knob real
    )
    trainer = LMTrainer(
        model,
        datasets,
        TrainConfig(
            epochs=epochs,
            batch_size=64,
            optimizer="adam",
            learning_rate=3e-3,
            log_frequency=20,
            checkpoint_dir=os.environ.get("DTF_LM_CKPT"),
        ),
    )
    result = trainer.run()
    print(f"held-out perplexity: {result['perplexity']:.2f}")

    params = trainer.state.params
    rng = np.random.default_rng(1)
    half = rng.integers(0, 61, size=(2, 8))
    prompt = jnp.asarray(
        np.concatenate([half, half[:, :2]], axis=1), jnp.int32
    )  # first half + 2 copied tokens: the model should continue the copy
    greedy = model.greedy_decode(params, prompt, max_new)
    sampled = model.sample_decode(
        params, prompt, max_new, jax.random.key(0), temperature=0.7,
        top_k=8, top_p=0.95
    )
    beam = model.beam_decode(params, prompt, max_new, 4)
    ncheck = min(6, max_new)
    copied = np.asarray(greedy[:, 10 : 10 + ncheck])
    want = half[:, 2 : 2 + ncheck]
    print(f"greedy continuation:  {np.asarray(greedy)[0, 10:].tolist()}")
    print(f"sampled continuation: {np.asarray(sampled)[0, 10:].tolist()}")
    print(f"beam-4 continuation:  {np.asarray(beam)[0, 10:].tolist()}")
    print(f"copy-accuracy (greedy): {(copied == want).mean():.2f}")
    print("Done")


if __name__ == "__main__":
    argv = [int(a) for a in sys.argv[1:3]]
    main(*argv)
