"""Language-model training + generation — the capability the reference never
had (its one model is the MLP classifier, reference tfsingle.py:23-42).

Run: ``python examples/lm.py [steps] [max_new]``

Trains a small GPT-style causal LM on a synthetic copy task (sequences of
the form ``x · x`` — the model must learn to attend back and reproduce the
first half), printing the reference-style Step/Cost lines, then generates
from a held-out prompt with the static-shape KV cache: greedy and sampled.
``DTF_LM_FLASH=1`` switches the causal attention to the Pallas flash
kernel.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.models.gpt import GPTLM, make_lm_train_step
from distributed_tensorflow_tpu.ops import optim as optim_lib


def main(steps: int = 300, max_new: int = 16) -> None:
    model = GPTLM(
        vocab_size=61,
        max_len=48,
        model_dim=64,
        num_heads=4,
        num_layers=2,
        compute_dtype=jnp.float32,
        attention_impl="flash" if os.environ.get("DTF_LM_FLASH") else "xla",
    )
    params = model.init(seed=1)
    opt = optim_lib.make("adam", 3e-3)
    opt_state = opt.init(params)
    step = make_lm_train_step(model, opt)
    rng = np.random.default_rng(0)

    def batch():
        half = rng.integers(0, 61, size=(16, 8))
        return jnp.asarray(np.concatenate([half, half], axis=1), jnp.int32)

    t0 = time.time()
    for i in range(1, steps + 1):
        params, opt_state, loss = step(params, opt_state, batch())
        if i % 50 == 0 or i == 1:
            print(f"Step: {i},  Cost: {float(loss):.4f}")
    final = float(loss)  # D2H fetch: the only trustworthy barrier (CLAUDE.md)
    print(f"Total Time: {time.time() - t0:.2f}s  Final Cost: {final:.4f}")

    half = rng.integers(0, 61, size=(2, 8))
    prompt = jnp.asarray(
        np.concatenate([half, half[:, :2]], axis=1), jnp.int32
    )  # first half + 2 copied tokens: the model should continue the copy
    greedy = model.greedy_decode(params, prompt, max_new)
    sampled = model.sample_decode(
        params, prompt, max_new, jax.random.key(0), temperature=0.7, top_k=8
    )
    ncheck = min(6, max_new)
    copied = np.asarray(greedy[:, 10 : 10 + ncheck])
    want = half[:, 2 : 2 + ncheck]
    print(f"greedy continuation:  {np.asarray(greedy)[0, 10:].tolist()}")
    print(f"sampled continuation: {np.asarray(sampled)[0, 10:].tolist()}")
    print(f"copy-accuracy (greedy): {(copied == want).mean():.2f}")
    print("Done")


if __name__ == "__main__":
    argv = [int(a) for a in sys.argv[1:3]]
    main(*argv)
