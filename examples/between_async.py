"""Async data-parallel training — the ``tfdist_between.py`` equivalent
(SURVEY.md §3.3).

Run:  ``python examples/between_async.py --job_name=worker --task_index=0``
      ``python examples/between_async.py --job_name=ps --task_index=0``  (no-op)

The reference's HOGWILD parameter-server updates become per-chip parameter
copies with periodic exchange and update-count-scaled steps
(see parallel/strategy.py docstring). ``settings.py``'s worker list sizes the
multi-host process group; all local chips join the mesh.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import settings  # the reference-compatible cluster module

from distributed_tensorflow_tpu.config import ClusterConfig, TrainConfig
from distributed_tensorflow_tpu.launch import run

if __name__ == "__main__":
    run(
        ClusterConfig.from_settings_module(settings),
        TrainConfig(sync=False, async_avg_every=50),
    )
