"""Text LM — train on deterministic English-like documents, then
generate text. The reference's only dataset is MNIST images
(reference tfsingle.py:13-14); this drives the framework's text story
end to end: tokenizer → pack_documents → LMTrainer lifecycle →
greedy / nucleus / beam generation decoded back to strings.

Byte-level by default; pass a merge count to train a BPE vocabulary on
the corpus first (native incremental trainer, data/text.py) — the same
documents then pack into fewer, higher-entropy tokens, and the learned
vocab is saved alongside any checkpoint the trainer writes.

Run: ``python examples/text_lm.py [epochs] [max_new] [bpe_merges]``
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.config import TrainConfig
from distributed_tensorflow_tpu.data import (
    BPETokenizer,
    ByteTokenizer,
    synthetic_documents,
    text_corpus,
)
from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.train import LMTrainer


def main(epochs: int = 6, max_new: int = 48, bpe_merges: int = 0) -> None:
    if bpe_merges:
        t0 = time.perf_counter()
        tok = BPETokenizer.train(
            synthetic_documents(768, seed=0), num_merges=bpe_merges
        )
        print(
            f"trained {len(tok.merges)}-merge BPE vocab "
            f"({tok.vocab_size} ids) in {time.perf_counter() - t0:.2f}s"
        )
    else:
        tok = ByteTokenizer()
    datasets = text_corpus(
        num_docs=768, seq_len=96, n_val=16, n_test=16, seed=0, tokenizer=tok
    )
    model = GPTLM(
        vocab_size=tok.vocab_size,
        max_len=96 + max_new,
        model_dim=96,
        num_heads=4,
        num_layers=3,
        compute_dtype=jnp.float32,
    )
    trainer = LMTrainer(
        model,
        datasets,
        TrainConfig(
            epochs=epochs, batch_size=32, optimizer="adam",
            learning_rate=3e-3, log_frequency=20,
        ),
        tokenizer=tok,
    )
    result = trainer.run()
    print(f"held-out perplexity: {result['perplexity']:.2f} (uniform = {tok.vocab_size})")

    params = trainer.state.params
    prompt = jnp.asarray(tok.encode("the model ")[None, :], jnp.int32)
    greedy = model.greedy_decode(params, prompt, max_new)
    nucleus = model.sample_decode(
        params, prompt, max_new, jax.random.key(0), temperature=0.8, top_p=0.95
    )
    beam = model.beam_decode(params, prompt, max_new, 4, eos_id=tok.eos_id)
    print(f"greedy:  {tok.decode(np.asarray(greedy)[0])!r}")
    print(f"nucleus: {tok.decode(np.asarray(nucleus)[0])!r}")
    print(f"beam-4:  {tok.decode(np.asarray(beam)[0])!r}")
    print("Done")


if __name__ == "__main__":
    argv = [int(a) for a in sys.argv[1:4]]
    main(*argv)
