"""Single-device training — the ``tfsingle.py`` equivalent (SURVEY.md §3.1).

Run: ``python examples/single.py``

Trains the 784→100→10 sigmoid/softmax MLP with SGD lr=0.001, batch 100, for
100 epochs, printing the reference's Step/Epoch/Batch/Cost/AvgTime lines and
per-epoch Test-Accuracy, and writing cost/accuracy scalars to ./logs.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_tensorflow_tpu.config import TrainConfig
from distributed_tensorflow_tpu.launch import build_trainer, config_from_env

if __name__ == "__main__":
    trainer = build_trainer(config_from_env(TrainConfig()))
    trainer.run()
