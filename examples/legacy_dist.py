"""Legacy variant — the ``tfdist.py`` equivalent (SURVEY.md §3.5).

The reference kept its pre-``settings.py`` iteration in-tree with hardcoded
cluster IPs (reference tfdist.py:8-9) and no session config. Kept here for
launch-surface completeness: edit the two lists below instead of a settings
module. Superseded by ``between_async.py``.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_tensorflow_tpu.config import ClusterConfig, TrainConfig
from distributed_tensorflow_tpu.launch import run

ps_svrs = ["10.88.104.31:2223"]  # accepted, ignored (no PS on TPU)
worker_svrs = ["10.88.104.31:2222", "10.88.102.119:2222"]

if __name__ == "__main__":
    run(
        ClusterConfig.from_lists(worker_svrs, ps_svrs),
        TrainConfig(sync=False, async_avg_every=50),
    )
