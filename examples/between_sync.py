"""Sync data-parallel training — the ``tfdist_between_sync.py`` equivalent
(SURVEY.md §3.4).

Run:  ``python examples/between_sync.py --job_name=worker --task_index=0``

``SyncReplicasOptimizer``'s accumulate-average-apply becomes a compiled
gradient all-reduce over the mesh's ``data`` axis — no queues, no chief
queue-runner, no parameter server.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import settings

from distributed_tensorflow_tpu.config import ClusterConfig, TrainConfig
from distributed_tensorflow_tpu.launch import run

if __name__ == "__main__":
    run(
        ClusterConfig.from_settings_module(settings),
        TrainConfig(sync=True),
    )
