"""Resilient single-device training: durable checkpoints + preemption +
anomaly rollback (train/resilience.py; contracts in docs/resilience.md).

Run: ``python examples/resilient.py``            # train with the full guard
     kill -TERM <pid>                            # graceful stop + final save
     python examples/resilient.py                # resumes from the newest
                                                 # VALID step_N (corrupt or
                                                 # partial saves are skipped)

Every epoch saves ``step_N`` plus a CRC32C manifest sidecar; retention
keeps the newest 3. A NaN/inf or spike epoch (cost > 3x the trailing-
window median) restores the last good checkpoint and retries on the next
data window, up to 2 times, printing a ``Rollback:`` line per event. No
reference analog: the TF1 suite configured no saver at all (SURVEY.md §5).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from distributed_tensorflow_tpu.config import TrainConfig
from distributed_tensorflow_tpu.launch import build_trainer, config_from_env

if __name__ == "__main__":
    config = TrainConfig(
        checkpoint_dir="./checkpoints_resilient",
        keep_last_n=3,          # GC old steps; the last valid one survives
        max_rollbacks=2,        # anomaly guard budget (0 disables)
        spike_threshold=3.0,    # x trailing-window median; NaN always trips
        handle_preemption=True, # SIGTERM/SIGINT -> save at boundary, exit 0
    )
    trainer = build_trainer(config_from_env(config))
    print(f"resuming from step {trainer.start_step}" if trainer.start_step
          else "fresh start")
    trainer.run()
