"""Serve a trained LM checkpoint: text in → text out.

The missing half of examples/text_lm.py — that script trains and decodes
in-process; this one closes the production loop the reference never had
(its only inference was the in-loop eval fetch, reference tfsingle.py:94):

1. train a few epochs with a BPE vocab, checkpointing (the trainer ships
   ``tokenizer.json`` into ``checkpoint_dir``);
2. load the checkpoint into a :class:`~distributed_tensorflow_tpu.serve.
   TextServer` — compiled bucketed prefill + chunked decode with
   continuous batching across 4 request slots;
3. serve a mixed batch of prompts (greedy and seeded nucleus sampling)
   and print the generations.

Run: ``python examples/serve_text.py [epochs] [max_new]``
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax.numpy as jnp

from distributed_tensorflow_tpu.config import TrainConfig
from distributed_tensorflow_tpu.data import (
    BPETokenizer,
    synthetic_documents,
    text_corpus,
)
from distributed_tensorflow_tpu.models.gpt import GPTLM
from distributed_tensorflow_tpu.serve import GenerationConfig, TextServer
from distributed_tensorflow_tpu.train import LMTrainer


def main(epochs: int = 4, max_new: int = 32) -> None:
    tok = BPETokenizer.train(synthetic_documents(512, seed=0), num_merges=64)
    datasets = text_corpus(
        num_docs=512, seq_len=64, n_val=16, n_test=16, seed=0, tokenizer=tok
    )
    model = GPTLM(
        vocab_size=tok.vocab_size,
        max_len=64 + max_new,
        model_dim=64,
        num_heads=4,
        num_layers=2,
        compute_dtype=jnp.float32,
    )
    with tempfile.TemporaryDirectory() as ckpt_dir:
        trainer = LMTrainer(
            model,
            datasets,
            TrainConfig(
                epochs=epochs, batch_size=32, optimizer="adam",
                learning_rate=3e-3, log_frequency=10**9,
                checkpoint_dir=ckpt_dir,
            ),
            tokenizer=tok,
        )
        result = trainer.run()
        print(f"trained: perplexity {result['perplexity']:.2f}")

        # A fresh process would do exactly this — nothing below touches
        # the trainer: params come off disk through the canonical restore
        # layer, the vocab from the shipped tokenizer.json.
        server = TextServer.from_checkpoint(
            model,
            ckpt_dir,
            optimizer=trainer.optimizer,
            slots=4,
            chunk=16,
        )
        prompts = ["the model ", "one step ", "this data ", "a deep ",
                   "the fast ", "new node "]
        greedy = server.serve_text(prompts[:3], max_new=max_new)
        sampled = server.serve_text(
            prompts[3:], max_new=max_new, greedy=False, temperature=0.8,
            top_p=0.95, seed=7,
        )
        for p, g in zip(prompts[:3], greedy):
            print(f"greedy  {p!r} -> {g!r}")
        for p, s in zip(prompts[3:], sampled):
            print(f"nucleus {p!r} -> {s!r}")
    print("Done")


if __name__ == "__main__":
    argv = [int(a) for a in sys.argv[1:3]]
    main(*argv)
