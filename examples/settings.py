"""Cluster topology, drop-in compatible with the reference's settings.py
(reference settings.py:3-4). ``ps_svrs`` is accepted and ignored on TPU —
parameters live on the chips (SURVEY.md §2a). Each worker entry is one host
process in the jax.distributed group; entry 0 is the coordinator/chief."""

ps_svrs = ["localhost:2222"]
worker_svrs = ["localhost:2223"]
