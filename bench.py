"""Benchmark harness: MNIST MLP training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's best single-device number — 550 batches × 100
examples in ~1.3 s/epoch on a GTX 1080 (reference README.md:13-15) ≈ 42k
examples/sec (BASELINE.md). North star: ≥50k examples/sec/chip.

Method: the scanned train path (train/scan.py) — the whole epoch staged in
device memory, one XLA dispatch per epoch, identical update semantics to the
reference loop (SGD lr=0.001, batch 100). Warmup dispatch first (compile),
then the median of several timed epochs. Diagnostics go to stderr; stdout
carries exactly the one JSON line.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from distributed_tensorflow_tpu.data import read_data_sets
from distributed_tensorflow_tpu.models import MLP
from distributed_tensorflow_tpu.ops import cross_entropy, sgd
from distributed_tensorflow_tpu.parallel.strategy import SingleDevice
from distributed_tensorflow_tpu.train.scan import make_scanned_train_fn, stage_epoch

BASELINE_EXAMPLES_PER_SEC = 42_000.0
BATCH_SIZE = 100
LEARNING_RATE = 0.001
TIMED_EPOCHS = 5


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import os

    dev = jax.devices()[0]
    impl = os.environ.get("BENCH_IMPL", "xla")  # xla | pallas
    log(f"device: {dev}  impl: {impl}")
    ds = read_data_sets("MNIST_data", one_hot=True)

    model = MLP()  # bf16 matmuls, f32 accumulation/softmax
    if impl == "pallas":
        # NOTE: the fused kernel computes its matmuls in f32 (not bf16), so
        # an xla-vs-pallas delta includes that dtype difference.
        from distributed_tensorflow_tpu.ops.pallas_mlp import (
            make_fused_scanned_fn,
            to_fused,
        )

        log("pallas impl runs f32 matmuls (xla impl runs bf16)")
        state = to_fused(model.init(seed=1))
        run_epoch = make_fused_scanned_fn(
            batch_size=BATCH_SIZE, learning_rate=LEARNING_RATE
        )
    else:
        opt = sgd(LEARNING_RATE)
        state = SingleDevice().init_state(model, opt, seed=1)
        run_epoch = make_scanned_train_fn(model, cross_entropy, opt)

    rng = np.random.default_rng(0)
    xs_np, ys_np = stage_epoch(ds.train.images, ds.train.labels, BATCH_SIZE, rng=rng)
    steps, batch = xs_np.shape[0], xs_np.shape[1]
    xs = jax.device_put(jnp.asarray(xs_np), dev)
    ys = jax.device_put(jnp.asarray(ys_np), dev)
    log(f"staged epoch: {steps} steps x {batch} examples")

    # Warmup: compile + first run.
    t0 = time.perf_counter()
    state, costs = run_epoch(state, xs, ys)
    jax.block_until_ready(costs)
    log(f"warmup (incl compile): {time.perf_counter() - t0:.2f}s")

    times = []
    for e in range(TIMED_EPOCHS):
        t0 = time.perf_counter()
        state, costs = run_epoch(state, xs, ys)
        jax.block_until_ready(costs)
        dt = time.perf_counter() - t0
        times.append(dt)
        log(
            f"epoch {e + 1}: {dt * 1000:.1f}ms  "
            f"({steps * batch / dt:,.0f} ex/s)  cost={float(costs[-1]):.4f}"
        )

    first, last = float(costs[0]), float(costs[-1])
    if not np.isfinite(last):
        log("FATAL: non-finite cost")
        raise SystemExit(1)

    sec_per_epoch = float(np.median(times))
    examples_per_sec = steps * batch / sec_per_epoch
    print(
        json.dumps(
            {
                "metric": "mnist_mlp_train_examples_per_sec_per_chip",
                "value": round(examples_per_sec, 1),
                "unit": "examples/sec/chip",
                "vs_baseline": round(examples_per_sec / BASELINE_EXAMPLES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
