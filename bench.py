"""Benchmark harness: MNIST MLP training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "impl",
"stream_dtype"}.

Baseline: the reference's best single-device number — 550 batches × 100
examples in ~1.3 s/epoch on a GTX 1080 (reference README.md:13-15) ≈ 42k
examples/sec (BASELINE.md). North star: ≥50k examples/sec/chip.

Method: the scanned train path (train/scan.py) — whole epochs staged in
device memory and walked by one `lax.scan`, identical update semantics to
the reference loop (SGD lr=0.001, batch 100). Each dispatch covers
`BENCH_EPOCHS_PER_DISPATCH` epochs (default 5, each with its own shuffle)
so the per-dispatch host/tunnel round trip is amortised the way any real
multi-epoch run would amortise it. Timing: warmups first (compile +
donation settling), then three TWO-POINT region pairs — each pair times a
5-dispatch and a 20-dispatch region, both synced by *fetching* the final
cost (on the tunneled chip `jax.block_until_ready` returns
optimistically, so a D2H value read that transitively depends on every
enqueued step is the only trustworthy barrier), and per-epoch time is the
pair's DIFFERENCE over the extra epochs (the fetch's ~100 ms roundtrip
cancels — CLAUDE.md TIMING TRAP 2). Median pair is reported.

`BENCH_IMPL=pallas-epoch` (default) runs the whole dispatch as ONE Pallas
kernel launch (ops/pallas_mlp.py `make_fused_epoch_fn`: grid over every
staged step, params VMEM-resident throughout — measured ~30% faster than
scanning the per-step fused kernel). `pallas` scans the per-step fused
kernel; `xla` is the pure-XLA scan. Failures fall back along
pallas-epoch → pallas → xla. Diagnostics go to stderr; stdout carries
exactly the one JSON line.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from distributed_tensorflow_tpu.data import read_data_sets
from distributed_tensorflow_tpu.models import MLP
from distributed_tensorflow_tpu.ops import cross_entropy, sgd
from distributed_tensorflow_tpu.parallel.strategy import SingleDevice
from distributed_tensorflow_tpu.train.scan import make_scanned_train_fn

BASELINE_EXAMPLES_PER_SEC = 42_000.0
BATCH_SIZE = 100
LEARNING_RATE = 0.001
TIMED_DISPATCHES = 5


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main(impl: str) -> None:
    import os

    if impl not in ("pallas-epoch", "pallas", "xla"):
        raise SystemExit(
            f"unknown BENCH_IMPL {impl!r} (expected pallas-epoch|pallas|xla)"
        )
    dev = jax.devices()[0]
    log(f"device: {dev}  impl: {impl}")
    ds = read_data_sets("MNIST_data", one_hot=True)

    model = MLP()  # bf16 matmuls, f32 accumulation/softmax (xla impl)

    # Stage E epochs, each with its own shuffle, as one flattened scan:
    # [E*steps, batch, ...]. The scan body is unchanged, so update semantics
    # are bit-identical to E successive single-epoch dispatches over the
    # same permutations — only the host syncs are fewer.
    epochs_per_dispatch = int(os.environ.get("BENCH_EPOCHS_PER_DISPATCH", "5"))
    # pallas-epoch streams batches half-width from HBM; stage them in that
    # dtype ONCE here (a per-dispatch astype inside the timed region would
    # re-read the full staging each call). BENCH_STREAM_DTYPE=float32 opts
    # back into full-width staging.
    stream = (
        os.environ.get("BENCH_STREAM_DTYPE", "bfloat16")
        if impl == "pallas-epoch"
        else "float32"
    )
    # Stage ON DEVICE: upload the flat dataset once (~86 MB bf16) plus the
    # shuffle indices (~1 MB), then gather/reshape into the [E*steps, B, ...]
    # scan layout in a jitted program. Round 1 shipped the pre-gathered
    # staging (431 MB bf16) through the ~6 MB/s tunnel — that one-time
    # transfer was the mystery "73 s warmup" (it lands in whichever warmup
    # first blocks on execution; see docs/performance.md).
    rng = np.random.default_rng(0)
    n_ex = ds.train.images.shape[0]
    steps = n_ex // BATCH_SIZE
    batch = BATCH_SIZE
    n_used = steps * BATCH_SIZE
    flat_x = jax.device_put(
        jnp.asarray(ds.train.images, dtype=jnp.dtype(stream)), dev
    )
    flat_y = jax.device_put(
        jnp.asarray(ds.train.labels, dtype=jnp.dtype(stream)), dev
    )
    perms = np.concatenate(
        [rng.permutation(n_ex)[:n_used] for _ in range(epochs_per_dispatch)]
    ).astype(np.int32)

    @jax.jit
    def _stage(fx, fy, perm):
        return (
            fx[perm].reshape(-1, BATCH_SIZE, fx.shape[1]),
            fy[perm].reshape(-1, BATCH_SIZE, fy.shape[1]),
        )

    xs, ys = _stage(flat_x, flat_y, jax.device_put(jnp.asarray(perms), dev))
    uploaded_mb = (flat_x.nbytes + flat_y.nbytes + perms.nbytes) / 1e6
    del flat_x, flat_y
    log(
        f"staged {epochs_per_dispatch} epochs x {steps} steps x {batch} "
        f"examples per dispatch ({xs.nbytes / 1e6:.0f} MB {stream} in HBM, "
        f"{uploaded_mb:.0f} MB uploaded)"
    )

    if impl in ("pallas", "pallas-epoch"):
        # NOTE: the fused kernels compute their matmuls in f32 (not bf16),
        # so an xla-vs-pallas delta includes that dtype difference.
        from distributed_tensorflow_tpu.ops.pallas_mlp import (
            make_fused_epoch_fn,
            make_fused_scanned_fn,
            to_fused,
        )

        log("pallas impls run f32 update math (xla impl runs bf16 matmuls)")
        state = to_fused(model.init(seed=1))
        if impl == "pallas-epoch":
            # The whole dispatch (E epochs) is ONE kernel launch: grid over
            # all staged steps, params VMEM-resident throughout. Batches
            # were staged in `stream` dtype above (the astype in run() is
            # then an identity).
            run_epoch = make_fused_epoch_fn(
                steps=steps * epochs_per_dispatch,
                batch_size=BATCH_SIZE,
                learning_rate=LEARNING_RATE,
                stream_dtype=jnp.dtype(stream),
            )
        else:
            run_epoch = make_fused_scanned_fn(
                batch_size=BATCH_SIZE, learning_rate=LEARNING_RATE
            )
    else:
        opt = sgd(LEARNING_RATE)
        state = SingleDevice().init_state(model, opt, seed=1)
        run_epoch = make_scanned_train_fn(model, cross_entropy, opt)

    # Commit the initial state to the device BEFORE the first dispatch:
    # eagerly-built arrays are uncommitted (sharding "unspecified"), while
    # dispatch outputs are committed — without this the second call would
    # miss the jit cache and recompile (the round-1 "warmup 2" recompile;
    # docs/performance.md).
    state = jax.device_put(state, dev)

    # Warmup: dispatch 1 compiles + absorbs the staging upload; dispatch 2
    # must then match dispatch 1's executable (no recompile) and run at
    # steady-state speed.
    for i in range(2):
        t0 = time.perf_counter()
        state, costs = run_epoch(state, xs, ys)
        _ = float(costs[-1])  # D2H fetch = execution barrier (see below)
        log(f"warmup {i + 1}: {time.perf_counter() - t0:.2f}s")

    # Sustained measurement, TWO-POINT (CLAUDE.md TIMING TRAP 2): each
    # region enqueues its dispatches back-to-back and syncs once by
    # *fetching* the final cost (on the tunneled chip `block_until_ready`
    # returns optimistically — a D2H value read that transitively depends
    # on every enqueued step is the only trustworthy barrier), but that
    # one fetch still carries the ~100 ms tunnel roundtrip: at ~5 ms/epoch
    # x 25 epochs the roundtrip was ~40% of the round-3 regions. Per-epoch
    # time is therefore the DIFFERENCE between a 4k-dispatch and a
    # k-dispatch region over the extra epochs, median of 3 pairs.
    from distributed_tensorflow_tpu.utils.sync import two_point_seconds

    region_costs = []
    region_count = [0]

    def region(dispatches):
        nonlocal state
        region_count[0] += 1
        t0 = time.perf_counter()
        for _ in range(dispatches):
            state, costs = run_epoch(state, xs, ys)
        final_cost = float(costs[-1])  # D2H fetch = execution barrier
        total = time.perf_counter() - t0
        epochs = dispatches * epochs_per_dispatch
        region_costs.append(final_cost)
        log(
            f"region {region_count[0]}: {epochs} epochs in "
            f"{total * 1000:.1f}ms ({total / epochs * 1000:.2f}ms/epoch "
            f"raw)  cost={final_cost:.4f}"
        )
        return total

    sec_per_epoch = two_point_seconds(
        lambda: region(TIMED_DISPATCHES),
        lambda: region(4 * TIMED_DISPATCHES),
        3 * TIMED_DISPATCHES * epochs_per_dispatch,
        reps=3,
    )
    log(f"two-point: {sec_per_epoch * 1000:.3f}ms/epoch (median of 3 pairs)")

    # Validity: every region trains MORE epochs (pairs alternate 25- and
    # 100-epoch regions), so the fetched costs must be finite, descend
    # overall by MORE than tol (a flat trajectory means updates were
    # no-ops — e.g. a donation bug returning stale params — and must be
    # refused, not published), and never *increase* between adjacent
    # regions (tolerance: near convergence adjacent regions may plateau to
    # within ulps; the unequal epoch spacing only makes descent easier to
    # observe). Anything else means the barrier did not actually observe
    # execution (or training diverged/stalled) — refuse to publish a
    # number rather than emit a silently-corrupt measurement.
    tol = 1e-3
    if (
        not all(np.isfinite(c) for c in region_costs)
        or region_costs[-1] >= region_costs[0] - tol
        or any(b > a + tol for a, b in zip(region_costs, region_costs[1:]))
    ):
        log(f"FATAL: region costs not finite+descending: {region_costs}")
        raise SystemExit(1)

    examples_per_sec = steps * batch / sec_per_epoch
    print(
        json.dumps(
            {
                "metric": "mnist_mlp_train_examples_per_sec_per_chip",
                "value": round(examples_per_sec, 1),
                "unit": "examples/sec/chip",
                "vs_baseline": round(examples_per_sec / BASELINE_EXAMPLES_PER_SEC, 3),
                "impl": impl,
                "stream_dtype": stream,
            }
        )
    )


if __name__ == "__main__":
    import os as _os

    # Kernel regression (crash OR validity-gate SystemExit, e.g. NaN /
    # non-descending cost) must not zero out the bench: fall back along
    # the chain pallas-epoch → pallas → xla. Each retry runs *outside*
    # the except handler so the failed run's traceback-pinned device
    # buffers (~860 MB staged epochs) are freed before restaging.
    _FALLBACK = {"pallas-epoch": "pallas", "pallas": "xla"}
    _impl = _os.environ.get("BENCH_IMPL", "pallas-epoch")
    while True:
        try:
            main(_impl)
            break
        except (Exception, SystemExit) as e:
            _next = _FALLBACK.get(_impl)
            if _next is None or (isinstance(e, SystemExit) and e.code in (None, 0)):
                raise
            log(f"{_impl} impl failed ({type(e).__name__}: {e}); falling back to {_next}")
            _impl = _next
