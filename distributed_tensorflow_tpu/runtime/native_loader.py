"""Directory-level MNIST loading through the native runtime (C6's host-side
fast path). Raises ImportError/OSError when the native library or the files
are unavailable; ``data/mnist.py`` falls back to its numpy parser."""

from __future__ import annotations

import os

import numpy as np

from distributed_tensorflow_tpu.runtime import native


def load_idx_dir(data_dir: str):
    """Returns (train_x, train_y, test_x, test_y); images float32 [N,784] in
    [0,1], labels int64. Gzip-compressed files are not handled here (pure-C
    parser) — the numpy fallback covers those."""
    paths = {
        "train_x": os.path.join(data_dir, "train-images-idx3-ubyte"),
        "train_y": os.path.join(data_dir, "train-labels-idx1-ubyte"),
        "test_x": os.path.join(data_dir, "t10k-images-idx3-ubyte"),
        "test_y": os.path.join(data_dir, "t10k-labels-idx1-ubyte"),
    }
    for p in paths.values():
        if not os.path.exists(p):
            raise OSError(f"missing IDX file: {p}")
    train_x = native.load_idx_images(paths["train_x"])
    train_y = native.load_idx_labels(paths["train_y"])
    test_x = native.load_idx_images(paths["test_x"])
    test_y = native.load_idx_labels(paths["test_y"])
    return train_x, train_y, np.asarray(test_x), np.asarray(test_y)
