"""Native (C++) runtime helpers: data pipeline + failure detection.

See ``csrc/dtf_runtime.cc``. Loaded lazily via ctypes; everything in the
framework that uses this package degrades gracefully to pure Python/numpy
when the shared library is absent or the toolchain can't build it.
"""

from distributed_tensorflow_tpu.runtime.native import (  # noqa: F401
    HeartbeatCoordinator,
    HeartbeatWorker,
    available,
    load_library,
)
