// Native runtime helpers for distributed_tensorflow_tpu.
//
// The reference leaned on TF 1.2.1's C++ runtime for data feeding and
// cluster liveness (SURVEY.md §2a): the tutorial loader's numpy pipeline fed
// sess.run, and worker liveness was implicit in gRPC channel state
// (tf.train.Server, reference tfdist_between.py:17). This translation unit
// provides the TPU-native framework's equivalents as a small C library:
//
//   1. IDX (MNIST) file parsing + normalized decode to float32 — the host
//      side of the input pipeline, off the Python interpreter.
//   2. Shuffled-permutation + batch-gather kernels — next_batch's hot work.
//   3. A UDP heartbeat coordinator/worker pair — explicit failure detection
//      for multi-host jobs (SURVEY.md §5 "Failure detection": the reference
//      had none beyond gRPC blocking; this is the deliberate upgrade).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

uint32_t read_be32(const unsigned char* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// 1. IDX parsing
// ---------------------------------------------------------------------------

// Reads an IDX3 image file; writes n*rows*cols floats in [0,1] into `out`
// (caller allocates; pass out=nullptr to query the count). Returns the
// number of images, or -1 on open/parse failure.
long dtf_load_idx_images(const char* path, float* out, long out_capacity) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char header[16];
  if (std::fread(header, 1, 16, f) != 16 || read_be32(header) != 2051) {
    std::fclose(f);
    return -1;
  }
  long n = read_be32(header + 4);
  long rows = read_be32(header + 8);
  long cols = read_be32(header + 12);
  long total = n * rows * cols;
  if (!out) {
    std::fclose(f);
    return n;
  }
  if (out_capacity < total) {
    std::fclose(f);
    return -1;
  }
  std::vector<unsigned char> buf(total);
  if ((long)std::fread(buf.data(), 1, total, f) != total) {
    std::fclose(f);
    return -1;
  }
  std::fclose(f);
  constexpr float kInv255 = 1.0f / 255.0f;
  for (long i = 0; i < total; ++i) out[i] = buf[i] * kInv255;
  return n;
}

// Reads an IDX1 label file into int64 `out`. Same conventions as above.
long dtf_load_idx_labels(const char* path, long* out, long out_capacity) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char header[8];
  if (std::fread(header, 1, 8, f) != 8 || read_be32(header) != 2049) {
    std::fclose(f);
    return -1;
  }
  long n = read_be32(header + 4);
  if (!out) {
    std::fclose(f);
    return n;
  }
  if (out_capacity < n) {
    std::fclose(f);
    return -1;
  }
  std::vector<unsigned char> buf(n);
  if ((long)std::fread(buf.data(), 1, n, f) != n) {
    std::fclose(f);
    return -1;
  }
  std::fclose(f);
  for (long i = 0; i < n; ++i) out[i] = buf[i];
  return n;
}

// ---------------------------------------------------------------------------
// 2. Shuffle + batch gather
// ---------------------------------------------------------------------------

// Fisher-Yates permutation of [0, n) using splitmix64, deterministic in seed.
void dtf_shuffle_perm(long* perm, long n, uint64_t seed) {
  for (long i = 0; i < n; ++i) perm[i] = i;
  uint64_t s = seed + 0x9E3779B97F4A7C15ull;
  auto next = [&s]() {
    uint64_t z = (s += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  };
  for (long i = n - 1; i > 0; --i) {
    long j = (long)(next() % (uint64_t)(i + 1));
    long t = perm[i];
    perm[i] = perm[j];
    perm[j] = t;
  }
}

// Gathers rows `idx[0..batch)` of `src` (row_len floats each) into `out`.
void dtf_gather_rows(const float* src, const long* idx, long batch,
                     long row_len, float* out) {
  for (long b = 0; b < batch; ++b) {
    std::memcpy(out + b * row_len, src + idx[b] * row_len,
                row_len * sizeof(float));
  }
}

// ---------------------------------------------------------------------------
// 3. Heartbeat failure detection (UDP)
// ---------------------------------------------------------------------------

struct Coordinator {
  int fd = -1;
  int expected = 0;
  int timeout_ms = 0;
  int grace_ms = 0;     // never-seen workers count failed after this
  int64_t start_ms = 0;  // coordinator start time (grace reference point)
  std::thread thread;
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::vector<int64_t> last_seen;  // 0 = never
  // Progress-aware health (elastic layer, train/elastic.py): the payload's
  // monotonic counter distinguishes DEAD (beats stopped) from LIVE-BUT-
  // STALLED (the native sender thread keeps beating while the main thread
  // hangs in a collective — the silence timeout alone can never see that).
  std::vector<long> progress;          // last reported value; -1 = never
  std::vector<int64_t> progress_ms;    // when it last CHANGED; 0 = never

  void loop() {
    char buf[64];
    while (!stop.load()) {
      struct timeval tv = {0, 100 * 1000};  // 100ms poll
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      ssize_t r = recv(fd, buf, sizeof(buf) - 1, 0);
      if (r > 0) {
        buf[r] = 0;
        int id = -1;
        long p = 0;
        // "HB <id> <progress>" (round 7) or the bare "HB <id>" payload
        // older senders emit — both keep counting as beats.
        int n = std::sscanf(buf, "HB %d %ld", &id, &p);
        if (n >= 1 && id >= 0 && id < expected) {
          std::lock_guard<std::mutex> lock(mu);
          last_seen[(size_t)id] = now_ms();
          if (n == 2 && p != progress[(size_t)id]) {
            progress[(size_t)id] = p;
            progress_ms[(size_t)id] = now_ms();
          }
        }
      }
    }
  }
};

// Starts a coordinator listening on udp://0.0.0.0:port for "HB <id>"
// datagrams from `expected_workers` workers. A worker that has reported at
// least once and then stays silent for `timeout_ms` counts as failed; a
// worker that NEVER reports counts as failed once `grace_ms` has elapsed
// since coordinator start (round-1 gap: a worker dead at t=0 was never
// "failed", so a job could wait forever with failed_count()==0 — the
// reference analog blocked in prepare_or_wait_for_session, reference
// tfdist_between.py:83, with no timeout either; this is the upgrade).
void* dtf_coord_start2(int port, int expected_workers, int timeout_ms,
                       int grace_ms) {
  auto* c = new Coordinator();
  c->expected = expected_workers;
  c->timeout_ms = timeout_ms;
  c->grace_ms = grace_ms;
  c->start_ms = now_ms();
  c->last_seen.assign((size_t)expected_workers, 0);
  c->progress.assign((size_t)expected_workers, -1);
  c->progress_ms.assign((size_t)expected_workers, 0);
  c->fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (c->fd < 0) {
    delete c;
    return nullptr;
  }
  int one = 1;
  setsockopt(c->fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (bind(c->fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(c->fd);
    delete c;
    return nullptr;
  }
  c->thread = std::thread([c] { c->loop(); });
  return c;
}

// Back-compat entry: grace defaults to 5x the silence timeout.
void* dtf_coord_start(int port, int expected_workers, int timeout_ms) {
  return dtf_coord_start2(port, expected_workers, timeout_ms, 5 * timeout_ms);
}

int dtf_coord_alive_count(void* h) {
  auto* c = (Coordinator*)h;
  int64_t now = now_ms();
  std::lock_guard<std::mutex> lock(c->mu);
  int alive = 0;
  for (int64_t t : c->last_seen)
    if (t != 0 && now - t <= c->timeout_ms) ++alive;
  return alive;
}

int dtf_coord_failed_count(void* h) {
  auto* c = (Coordinator*)h;
  int64_t now = now_ms();
  std::lock_guard<std::mutex> lock(c->mu);
  int failed = 0;
  for (int64_t t : c->last_seen) {
    if (t == 0) {
      if (now - c->start_ms > c->grace_ms) ++failed;  // never came up
    } else if (now - t > c->timeout_ms) {
      ++failed;  // reported, then went silent
    }
  }
  return failed;
}

// Milliseconds since worker `id` was last heard from; -1 if never.
long dtf_coord_ms_since_seen(void* h, int id) {
  auto* c = (Coordinator*)h;
  std::lock_guard<std::mutex> lock(c->mu);
  if (id < 0 || id >= c->expected || c->last_seen[(size_t)id] == 0) return -1;
  return (long)(now_ms() - c->last_seen[(size_t)id]);
}

// Last progress value reported by worker `id`; -1 if it never reported one
// (dead, not yet up, or a pre-progress sender).
long dtf_coord_progress(void* h, int id) {
  auto* c = (Coordinator*)h;
  std::lock_guard<std::mutex> lock(c->mu);
  if (id < 0 || id >= c->expected) return -1;
  return c->progress[(size_t)id];
}

// Milliseconds since worker `id`'s progress counter last CHANGED (the first
// report counts as a change); -1 if it never reported progress.
long dtf_coord_ms_since_progress(void* h, int id) {
  auto* c = (Coordinator*)h;
  std::lock_guard<std::mutex> lock(c->mu);
  if (id < 0 || id >= c->expected || c->progress_ms[(size_t)id] == 0) return -1;
  return (long)(now_ms() - c->progress_ms[(size_t)id]);
}

// Workers that are ALIVE (beating within timeout_ms) but whose progress
// counter has not moved for more than `stall_ms` — the live-but-stalled
// class the elastic agent recovers from (a rank hung in a collective keeps
// its sender thread beating forever; without this the job hangs). Workers
// that never reported progress are not counted: a pre-progress sender must
// not read as stalled, and startup (import + compile) is covered by sizing
// stall_ms above the worst-case first-epoch latency.
int dtf_coord_stalled_count(void* h, long stall_ms) {
  auto* c = (Coordinator*)h;
  int64_t now = now_ms();
  std::lock_guard<std::mutex> lock(c->mu);
  int stalled = 0;
  for (size_t i = 0; i < c->last_seen.size(); ++i) {
    bool alive = c->last_seen[i] != 0 && now - c->last_seen[i] <= c->timeout_ms;
    if (alive && c->progress_ms[i] != 0 && now - c->progress_ms[i] > stall_ms)
      ++stalled;
  }
  return stalled;
}

void dtf_coord_stop(void* h) {
  auto* c = (Coordinator*)h;
  c->stop.store(true);
  if (c->thread.joinable()) c->thread.join();
  close(c->fd);
  delete c;
}

struct Worker {
  int fd = -1;
  sockaddr_in addr{};
  int id = 0;
  int interval_ms = 0;
  std::thread thread;
  std::atomic<bool> stop{false};
  // Monotonic progress counter included in beats once set from Python
  // (epoch boundaries, train/supervisor.py::report_progress). Read by the
  // sender thread — atomic, never locked, so a hung interpreter cannot
  // block the beat (which is the whole point: beats survive a stall).
  // Starts at the -1 sentinel: until the first set_progress the payload
  // stays the bare "HB <id>", so the coordinator's never-reported-progress
  // carve-out really does cover startup (import + first compile) — a
  // counter sent as 0 from beat one would start the stall clock at
  // bootstrap and verdict every slow-compiling incarnation "stalled".
  std::atomic<long> progress{-1};

  void loop() {
    char msg[48];
    while (!stop.load()) {
      long p = progress.load(std::memory_order_relaxed);
      int len = p < 0 ? std::snprintf(msg, sizeof(msg), "HB %d", id)
                      : std::snprintf(msg, sizeof(msg), "HB %d %ld", id, p);
      sendto(fd, msg, (size_t)len, 0, (sockaddr*)&addr, sizeof(addr));
      std::this_thread::sleep_for(std::chrono::milliseconds(interval_ms));
    }
  }
};

// Starts a worker-side heartbeat thread sending "HB <id>" to host:port
// every interval_ms.
void* dtf_worker_start(const char* host, int port, int worker_id,
                       int interval_ms) {
  auto* w = new Worker();
  w->id = worker_id;
  w->interval_ms = interval_ms;
  w->fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (w->fd < 0) {
    delete w;
    return nullptr;
  }
  w->addr.sin_family = AF_INET;
  w->addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &w->addr.sin_addr) != 1) {
    close(w->fd);
    delete w;
    return nullptr;
  }
  w->thread = std::thread([w] { w->loop(); });
  return w;
}

// Advance the monotonic progress counter carried by this worker's beats.
void dtf_worker_set_progress(void* h, long p) {
  auto* w = (Worker*)h;
  w->progress.store(p, std::memory_order_relaxed);
}

void dtf_worker_stop(void* h) {
  auto* w = (Worker*)h;
  w->stop.store(true);
  if (w->thread.joinable()) w->thread.join();
  close(w->fd);
  delete w;
}

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli) + TFRecord masking — the checksum the TFRecord/tfevents
// format requires (the reference's FileWriter computed it inside TF's C++
// core). Table-driven; the Python writer (utils/summary.py) calls this and
// falls back to its pure-Python table when the library is unavailable.

struct Crc32cTable {
  uint32_t t[256];
  Crc32cTable() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k)
        crc = (crc & 1) ? (crc >> 1) ^ 0x82F63B78u : crc >> 1;
      t[i] = crc;
    }
  }
};

uint32_t dtf_crc32c(const uint8_t* data, size_t n) {
  // Meyers singleton: thread-safe one-time init (ctypes calls drop the
  // GIL, so first-use can race across threads).
  static const Crc32cTable table;
  const uint32_t* crc32c_table = table.t;
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i)
    crc = crc32c_table[(crc ^ data[i]) & 0xFFu] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// TFRecord "masked" crc: rotate right 15 + magic constant.
uint32_t dtf_crc32c_masked(const uint8_t* data, size_t n) {
  uint32_t crc = dtf_crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// ---------------------------------------------------------------------------
// Byte-level BPE (data/text.py's fast path). Semantics are pinned by the
// pure-Python fallback and tests/test_text.py: pick the most frequent
// adjacent pair (ties → numerically smallest pair), merge every
// non-overlapping occurrence left to right, never across document
// boundaries; encode applies merges in rank order, occurrences left to
// right. Incremental pair-count maintenance over a linked-list corpus —
// O(corpus + merge-site updates) total — so thousands of merges over a
// multi-megabyte corpus finish in seconds.

// Trains `num_merges` merges over `n_docs` UTF-8 documents concatenated in
// `bytes` (document i occupies doc_lens[i] bytes). Writes (a,b) pairs into
// out_pairs[2k],out_pairs[2k+1]; returns the number of merges learned
// (< num_merges iff the corpus ran out of pairs).
long dtf_bpe_train(const uint8_t* bytes, const long* doc_lens, long n_docs,
                   long num_merges, int32_t* out_pairs) {
  long total = 0;
  for (long d = 0; d < n_docs; ++d) total += doc_lens[d];
  // Node positions are int32 (cache footprint matters at this scale);
  // refuse corpora that would wrap rather than corrupt merges silently.
  if (total > 0x7FFFFFF0L) return -1;
  std::vector<int32_t> ids(total);
  std::vector<int32_t> nxt(total, -1), prv(total, -1);
  long off = 0;
  for (long d = 0; d < n_docs; ++d) {
    long n = doc_lens[d];
    for (long k = 0; k < n; ++k) {
      ids[off + k] = bytes[off + k];
      if (k + 1 < n) nxt[off + k] = int32_t(off + k + 1);
      if (k > 0) prv[off + k] = int32_t(off + k - 1);
    }
    off += n;
  }
  auto key = [](int64_t a, int64_t b) {
    return (uint64_t(a) << 32) | uint64_t(b);
  };
  std::unordered_map<uint64_t, int64_t> counts;
  std::unordered_map<uint64_t, std::vector<int32_t>> occ;
  counts.reserve(1 << 16);
  occ.reserve(1 << 16);
  for (long i = 0; i < total; ++i) {
    if (nxt[i] >= 0) {
      uint64_t k = key(ids[i], ids[nxt[i]]);
      ++counts[k];
      occ[k].push_back(int32_t(i));  // ascending by construction
    }
  }
  // Max-heap popping (max count, then smallest pair). Entries are lazy:
  // validate against `counts` at pop time. Count deltas are accumulated
  // per merge ROUND and applied once per distinct changed pair — one heap
  // push per (round, pair), not per occurrence, which keeps the heap
  // millions of entries smaller (per-occurrence pushes made heap pops 86%
  // of the runtime on a repetitive corpus).
  struct Entry {
    int64_t count;
    uint64_t pair;
    bool operator<(const Entry& o) const {
      if (count != o.count) return count < o.count;
      return pair > o.pair;
    }
  };
  std::priority_queue<Entry> heap;
  for (const auto& kv : counts) heap.push({kv.second, kv.first});
  std::unordered_map<uint64_t, int64_t> delta;
  delta.reserve(1 << 10);
  long n_merges = 0;
  while (n_merges < num_merges && !heap.empty()) {
    Entry top = heap.top();
    heap.pop();
    auto it = counts.find(top.pair);
    if (it == counts.end() || it->second != top.count) continue;  // stale
    int32_t a = int32_t(top.pair >> 32), b = int32_t(top.pair & 0xFFFFFFFF);
    int32_t new_id = int32_t(257 + n_merges);
    out_pairs[2 * n_merges] = a;
    out_pairs[2 * n_merges + 1] = b;
    ++n_merges;
    std::vector<int32_t> positions;
    auto oit = occ.find(top.pair);
    if (oit != occ.end()) {
      positions = std::move(oit->second);
      occ.erase(oit);
    }
    std::sort(positions.begin(), positions.end());
    delta.clear();
    for (int32_t i : positions) {
      if (ids[i] != a) continue;  // stale occurrence
      int32_t j = nxt[i];
      if (j < 0 || ids[j] != b) continue;
      int32_t p = prv[i], q = nxt[j];
      if (p >= 0) --delta[key(ids[p], a)];
      if (q >= 0) --delta[key(b, ids[q])];
      ids[i] = new_id;
      ids[j] = -2;  // dead node
      nxt[i] = q;
      if (q >= 0) {
        prv[q] = i;
        ++delta[key(new_id, ids[q])];
        occ[key(new_id, ids[q])].push_back(i);
      }
      if (p >= 0) {
        ++delta[key(ids[p], new_id)];
        occ[key(ids[p], new_id)].push_back(p);
      }
    }
    for (const auto& kv : delta) {
      if (kv.first == top.pair || kv.second == 0) continue;
      auto cit = counts.find(kv.first);
      int64_t c = (cit == counts.end() ? 0 : cit->second) + kv.second;
      if (c <= 0) {
        if (cit != counts.end()) counts.erase(cit);
      } else {
        counts[kv.first] = c;
        heap.push({c, kv.first});
      }
    }
    counts.erase(top.pair);
  }
  return n_merges;
}

namespace {

uint64_t bpe_key(int64_t a, int64_t b) {
  return (uint64_t(a) << 32) | uint64_t(b);
}

// Single-document heap-pass encode against a prebuilt ranks map; writes ids
// into `out`, returns encoded length.
long bpe_encode_one(const std::unordered_map<uint64_t, int32_t>& ranks,
                    const uint8_t* bytes, long n, int32_t* out);

}  // namespace

// Encodes `n` UTF-8 bytes with `n_merges` learned merges (pairs laid out as
// in dtf_bpe_train's output). Writes ids into `out` (capacity >= n);
// returns the encoded length. Single heap pass popping (rank, position):
// equivalent to rank-order application because a pair created by a rank-r
// merge always ranks > r.
long dtf_bpe_encode(const int32_t* merges, long n_merges, const uint8_t* bytes,
                    long n, int32_t* out) {
  std::unordered_map<uint64_t, int32_t> ranks;
  ranks.reserve(size_t(n_merges) * 2);
  for (long r = 0; r < n_merges; ++r)
    ranks.emplace(bpe_key(merges[2 * r], merges[2 * r + 1]), int32_t(r));
  return bpe_encode_one(ranks, bytes, n, out);
}

// Batch encode: builds the ranks map ONCE and encodes `n_docs` documents
// concatenated in `bytes` (document i occupies doc_lens[i] bytes). Writes
// the concatenated ids into `out` (capacity >= total bytes) and each
// document's encoded length into out_lens; returns the total id count.
long dtf_bpe_encode_batch(const int32_t* merges, long n_merges,
                          const uint8_t* bytes, const long* doc_lens,
                          long n_docs, int32_t* out, long* out_lens) {
  std::unordered_map<uint64_t, int32_t> ranks;
  ranks.reserve(size_t(n_merges) * 2);
  for (long r = 0; r < n_merges; ++r)
    ranks.emplace(bpe_key(merges[2 * r], merges[2 * r + 1]), int32_t(r));
  long in_off = 0, out_off = 0;
  for (long d = 0; d < n_docs; ++d) {
    long m = bpe_encode_one(ranks, bytes + in_off, doc_lens[d], out + out_off);
    out_lens[d] = m;
    in_off += doc_lens[d];
    out_off += m;
  }
  return out_off;
}

namespace {

long bpe_encode_one(const std::unordered_map<uint64_t, int32_t>& ranks,
                    const uint8_t* bytes, long n, int32_t* out) {
  if (n <= 1 || ranks.empty()) {
    for (long i = 0; i < n; ++i) out[i] = bytes[i];
    return n;
  }
  auto key = bpe_key;
  std::vector<int32_t> ids(n);
  std::vector<int64_t> nxt(n), prv(n);
  for (long i = 0; i < n; ++i) {
    ids[i] = bytes[i];
    nxt[i] = (i + 1 < n) ? i + 1 : -1;
    prv[i] = i - 1;
  }
  // Min-heap on (rank, position).
  using RP = std::pair<int32_t, int64_t>;
  std::priority_queue<RP, std::vector<RP>, std::greater<RP>> heap;
  for (long i = 0; i + 1 < n; ++i) {
    auto it = ranks.find(key(ids[i], ids[i + 1]));
    if (it != ranks.end()) heap.push({it->second, i});
  }
  while (!heap.empty()) {
    auto [r, i] = heap.top();
    heap.pop();
    if (ids[i] < 0) continue;
    int64_t j = nxt[i];
    if (j < 0) continue;
    auto it = ranks.find(key(ids[i], ids[j]));
    if (it == ranks.end() || it->second != r) continue;  // stale
    ids[i] = 257 + r;
    ids[j] = -1;
    int64_t q = nxt[j];
    nxt[i] = q;
    if (q >= 0) {
      prv[q] = i;
      auto it2 = ranks.find(key(ids[i], ids[q]));
      if (it2 != ranks.end()) heap.push({it2->second, i});
    }
    int64_t p = prv[i];
    if (p >= 0) {
      auto it2 = ranks.find(key(ids[p], ids[i]));
      if (it2 != ranks.end()) heap.push({it2->second, p});
    }
  }
  long m = 0;
  for (long i = 0; i < n; ++i)
    if (ids[i] >= 0) out[m++] = ids[i];
  return m;
}

}  // namespace

}  // extern "C"
