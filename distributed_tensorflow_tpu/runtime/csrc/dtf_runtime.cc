// Native runtime helpers for distributed_tensorflow_tpu.
//
// The reference leaned on TF 1.2.1's C++ runtime for data feeding and
// cluster liveness (SURVEY.md §2a): the tutorial loader's numpy pipeline fed
// sess.run, and worker liveness was implicit in gRPC channel state
// (tf.train.Server, reference tfdist_between.py:17). This translation unit
// provides the TPU-native framework's equivalents as a small C library:
//
//   1. IDX (MNIST) file parsing + normalized decode to float32 — the host
//      side of the input pipeline, off the Python interpreter.
//   2. Shuffled-permutation + batch-gather kernels — next_batch's hot work.
//   3. A UDP heartbeat coordinator/worker pair — explicit failure detection
//      for multi-host jobs (SURVEY.md §5 "Failure detection": the reference
//      had none beyond gRPC blocking; this is the deliberate upgrade).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

uint32_t read_be32(const unsigned char* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// 1. IDX parsing
// ---------------------------------------------------------------------------

// Reads an IDX3 image file; writes n*rows*cols floats in [0,1] into `out`
// (caller allocates; pass out=nullptr to query the count). Returns the
// number of images, or -1 on open/parse failure.
long dtf_load_idx_images(const char* path, float* out, long out_capacity) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char header[16];
  if (std::fread(header, 1, 16, f) != 16 || read_be32(header) != 2051) {
    std::fclose(f);
    return -1;
  }
  long n = read_be32(header + 4);
  long rows = read_be32(header + 8);
  long cols = read_be32(header + 12);
  long total = n * rows * cols;
  if (!out) {
    std::fclose(f);
    return n;
  }
  if (out_capacity < total) {
    std::fclose(f);
    return -1;
  }
  std::vector<unsigned char> buf(total);
  if ((long)std::fread(buf.data(), 1, total, f) != total) {
    std::fclose(f);
    return -1;
  }
  std::fclose(f);
  constexpr float kInv255 = 1.0f / 255.0f;
  for (long i = 0; i < total; ++i) out[i] = buf[i] * kInv255;
  return n;
}

// Reads an IDX1 label file into int64 `out`. Same conventions as above.
long dtf_load_idx_labels(const char* path, long* out, long out_capacity) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char header[8];
  if (std::fread(header, 1, 8, f) != 8 || read_be32(header) != 2049) {
    std::fclose(f);
    return -1;
  }
  long n = read_be32(header + 4);
  if (!out) {
    std::fclose(f);
    return n;
  }
  if (out_capacity < n) {
    std::fclose(f);
    return -1;
  }
  std::vector<unsigned char> buf(n);
  if ((long)std::fread(buf.data(), 1, n, f) != n) {
    std::fclose(f);
    return -1;
  }
  std::fclose(f);
  for (long i = 0; i < n; ++i) out[i] = buf[i];
  return n;
}

// ---------------------------------------------------------------------------
// 2. Shuffle + batch gather
// ---------------------------------------------------------------------------

// Fisher-Yates permutation of [0, n) using splitmix64, deterministic in seed.
void dtf_shuffle_perm(long* perm, long n, uint64_t seed) {
  for (long i = 0; i < n; ++i) perm[i] = i;
  uint64_t s = seed + 0x9E3779B97F4A7C15ull;
  auto next = [&s]() {
    uint64_t z = (s += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  };
  for (long i = n - 1; i > 0; --i) {
    long j = (long)(next() % (uint64_t)(i + 1));
    long t = perm[i];
    perm[i] = perm[j];
    perm[j] = t;
  }
}

// Gathers rows `idx[0..batch)` of `src` (row_len floats each) into `out`.
void dtf_gather_rows(const float* src, const long* idx, long batch,
                     long row_len, float* out) {
  for (long b = 0; b < batch; ++b) {
    std::memcpy(out + b * row_len, src + idx[b] * row_len,
                row_len * sizeof(float));
  }
}

// ---------------------------------------------------------------------------
// 3. Heartbeat failure detection (UDP)
// ---------------------------------------------------------------------------

struct Coordinator {
  int fd = -1;
  int expected = 0;
  int timeout_ms = 0;
  int grace_ms = 0;     // never-seen workers count failed after this
  int64_t start_ms = 0;  // coordinator start time (grace reference point)
  std::thread thread;
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::vector<int64_t> last_seen;  // 0 = never

  void loop() {
    char buf[64];
    while (!stop.load()) {
      struct timeval tv = {0, 100 * 1000};  // 100ms poll
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      ssize_t r = recv(fd, buf, sizeof(buf) - 1, 0);
      if (r > 0) {
        buf[r] = 0;
        int id = -1;
        if (std::sscanf(buf, "HB %d", &id) == 1 && id >= 0 && id < expected) {
          std::lock_guard<std::mutex> lock(mu);
          last_seen[(size_t)id] = now_ms();
        }
      }
    }
  }
};

// Starts a coordinator listening on udp://0.0.0.0:port for "HB <id>"
// datagrams from `expected_workers` workers. A worker that has reported at
// least once and then stays silent for `timeout_ms` counts as failed; a
// worker that NEVER reports counts as failed once `grace_ms` has elapsed
// since coordinator start (round-1 gap: a worker dead at t=0 was never
// "failed", so a job could wait forever with failed_count()==0 — the
// reference analog blocked in prepare_or_wait_for_session, reference
// tfdist_between.py:83, with no timeout either; this is the upgrade).
void* dtf_coord_start2(int port, int expected_workers, int timeout_ms,
                       int grace_ms) {
  auto* c = new Coordinator();
  c->expected = expected_workers;
  c->timeout_ms = timeout_ms;
  c->grace_ms = grace_ms;
  c->start_ms = now_ms();
  c->last_seen.assign((size_t)expected_workers, 0);
  c->fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (c->fd < 0) {
    delete c;
    return nullptr;
  }
  int one = 1;
  setsockopt(c->fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (bind(c->fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(c->fd);
    delete c;
    return nullptr;
  }
  c->thread = std::thread([c] { c->loop(); });
  return c;
}

// Back-compat entry: grace defaults to 5x the silence timeout.
void* dtf_coord_start(int port, int expected_workers, int timeout_ms) {
  return dtf_coord_start2(port, expected_workers, timeout_ms, 5 * timeout_ms);
}

int dtf_coord_alive_count(void* h) {
  auto* c = (Coordinator*)h;
  int64_t now = now_ms();
  std::lock_guard<std::mutex> lock(c->mu);
  int alive = 0;
  for (int64_t t : c->last_seen)
    if (t != 0 && now - t <= c->timeout_ms) ++alive;
  return alive;
}

int dtf_coord_failed_count(void* h) {
  auto* c = (Coordinator*)h;
  int64_t now = now_ms();
  std::lock_guard<std::mutex> lock(c->mu);
  int failed = 0;
  for (int64_t t : c->last_seen) {
    if (t == 0) {
      if (now - c->start_ms > c->grace_ms) ++failed;  // never came up
    } else if (now - t > c->timeout_ms) {
      ++failed;  // reported, then went silent
    }
  }
  return failed;
}

// Milliseconds since worker `id` was last heard from; -1 if never.
long dtf_coord_ms_since_seen(void* h, int id) {
  auto* c = (Coordinator*)h;
  std::lock_guard<std::mutex> lock(c->mu);
  if (id < 0 || id >= c->expected || c->last_seen[(size_t)id] == 0) return -1;
  return (long)(now_ms() - c->last_seen[(size_t)id]);
}

void dtf_coord_stop(void* h) {
  auto* c = (Coordinator*)h;
  c->stop.store(true);
  if (c->thread.joinable()) c->thread.join();
  close(c->fd);
  delete c;
}

struct Worker {
  int fd = -1;
  sockaddr_in addr{};
  int id = 0;
  int interval_ms = 0;
  std::thread thread;
  std::atomic<bool> stop{false};

  void loop() {
    char msg[32];
    int len = std::snprintf(msg, sizeof(msg), "HB %d", id);
    while (!stop.load()) {
      sendto(fd, msg, (size_t)len, 0, (sockaddr*)&addr, sizeof(addr));
      std::this_thread::sleep_for(std::chrono::milliseconds(interval_ms));
    }
  }
};

// Starts a worker-side heartbeat thread sending "HB <id>" to host:port
// every interval_ms.
void* dtf_worker_start(const char* host, int port, int worker_id,
                       int interval_ms) {
  auto* w = new Worker();
  w->id = worker_id;
  w->interval_ms = interval_ms;
  w->fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (w->fd < 0) {
    delete w;
    return nullptr;
  }
  w->addr.sin_family = AF_INET;
  w->addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &w->addr.sin_addr) != 1) {
    close(w->fd);
    delete w;
    return nullptr;
  }
  w->thread = std::thread([w] { w->loop(); });
  return w;
}

void dtf_worker_stop(void* h) {
  auto* w = (Worker*)h;
  w->stop.store(true);
  if (w->thread.joinable()) w->thread.join();
  close(w->fd);
  delete w;
}

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli) + TFRecord masking — the checksum the TFRecord/tfevents
// format requires (the reference's FileWriter computed it inside TF's C++
// core). Table-driven; the Python writer (utils/summary.py) calls this and
// falls back to its pure-Python table when the library is unavailable.

struct Crc32cTable {
  uint32_t t[256];
  Crc32cTable() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k)
        crc = (crc & 1) ? (crc >> 1) ^ 0x82F63B78u : crc >> 1;
      t[i] = crc;
    }
  }
};

uint32_t dtf_crc32c(const uint8_t* data, size_t n) {
  // Meyers singleton: thread-safe one-time init (ctypes calls drop the
  // GIL, so first-use can race across threads).
  static const Crc32cTable table;
  const uint32_t* crc32c_table = table.t;
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i)
    crc = crc32c_table[(crc ^ data[i]) & 0xFFu] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// TFRecord "masked" crc: rotate right 15 + magic constant.
uint32_t dtf_crc32c_masked(const uint8_t* data, size_t n) {
  uint32_t crc = dtf_crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

}  // extern "C"
