"""ctypes bindings for the native runtime (csrc/dtf_runtime.cc).

No pybind11 in this environment — the library exposes a plain C ABI and
this module wraps it. The library is built on demand with ``make`` the
first time it is requested (set ``DTF_NO_NATIVE=1`` to disable entirely).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libdtf_runtime.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_SO)
    except (OSError, subprocess.SubprocessError):
        return False


def load_library() -> ctypes.CDLL:
    """Load (building if needed) the native library; raises ImportError if
    unavailable so callers can fall back to pure Python. A stale ``.so``
    built from older sources (missing newer symbols) is rebuilt once; if
    symbols are still missing the failure surfaces as ImportError so the
    pure-Python fallbacks engage rather than AttributeError escaping."""
    global _lib, _tried
    with _lock:
        if _lib is not None:
            return _lib
        if os.environ.get("DTF_NO_NATIVE"):
            raise ImportError("native runtime disabled via DTF_NO_NATIVE")
        if not os.path.exists(_SO):
            if _tried or not _build():
                _tried = True
                raise ImportError("libdtf_runtime.so unavailable (build failed)")
        _tried = True
        lib = ctypes.CDLL(_SO)
        try:
            _bind(lib)
        except AttributeError as exc:
            # dlopen caches by pathname: close the stale mapping or the
            # post-rebuild CDLL call would hand back the old library.
            import _ctypes

            _ctypes.dlclose(lib._handle)
            try:
                os.remove(_SO)
            except OSError:
                pass
            if not _build():
                raise ImportError(
                    f"stale libdtf_runtime.so and rebuild failed: {exc}"
                ) from exc
            lib = ctypes.CDLL(_SO)
            try:
                _bind(lib)
            except AttributeError as exc2:
                raise ImportError(
                    f"libdtf_runtime.so missing symbol after rebuild: {exc2}"
                ) from exc2
        _lib = lib
        return lib


def _bind(lib: ctypes.CDLL) -> None:
    """Declare C ABI signatures; raises AttributeError on a missing symbol."""
    lib.dtf_load_idx_images.restype = ctypes.c_long
    lib.dtf_load_idx_images.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_long,
    ]
    lib.dtf_load_idx_labels.restype = ctypes.c_long
    lib.dtf_load_idx_labels.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_long),
        ctypes.c_long,
    ]
    lib.dtf_shuffle_perm.restype = None
    lib.dtf_shuffle_perm.argtypes = [
        ctypes.POINTER(ctypes.c_long),
        ctypes.c_long,
        ctypes.c_uint64,
    ]
    lib.dtf_gather_rows.restype = None
    lib.dtf_gather_rows.argtypes = [
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_long),
        ctypes.c_long,
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_float),
    ]
    lib.dtf_coord_start.restype = ctypes.c_void_p
    lib.dtf_coord_start.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.dtf_coord_start2.restype = ctypes.c_void_p
    lib.dtf_coord_start2.argtypes = [
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.dtf_coord_alive_count.restype = ctypes.c_int
    lib.dtf_coord_alive_count.argtypes = [ctypes.c_void_p]
    lib.dtf_coord_failed_count.restype = ctypes.c_int
    lib.dtf_coord_failed_count.argtypes = [ctypes.c_void_p]
    lib.dtf_coord_ms_since_seen.restype = ctypes.c_long
    lib.dtf_coord_ms_since_seen.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dtf_coord_progress.restype = ctypes.c_long
    lib.dtf_coord_progress.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dtf_coord_ms_since_progress.restype = ctypes.c_long
    lib.dtf_coord_ms_since_progress.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dtf_coord_stalled_count.restype = ctypes.c_int
    lib.dtf_coord_stalled_count.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.dtf_coord_stop.restype = None
    lib.dtf_coord_stop.argtypes = [ctypes.c_void_p]
    lib.dtf_worker_start.restype = ctypes.c_void_p
    lib.dtf_worker_start.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.dtf_worker_set_progress.restype = None
    lib.dtf_worker_set_progress.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.dtf_worker_stop.restype = None
    lib.dtf_worker_stop.argtypes = [ctypes.c_void_p]
    lib.dtf_crc32c.restype = ctypes.c_uint32
    lib.dtf_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.dtf_crc32c_masked.restype = ctypes.c_uint32
    lib.dtf_crc32c_masked.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.dtf_bpe_train.restype = ctypes.c_long
    lib.dtf_bpe_train.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_long),
        ctypes.c_long,
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.dtf_bpe_encode.restype = ctypes.c_long
    lib.dtf_bpe_encode.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.dtf_bpe_encode_batch.restype = ctypes.c_long
    lib.dtf_bpe_encode_batch.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_long),
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_long),
    ]


def available() -> bool:
    try:
        load_library()
        return True
    except ImportError:
        return False


# ---------------------------------------------------------------------------
# Data pipeline bindings
# ---------------------------------------------------------------------------


def load_idx_images(path: str) -> np.ndarray:
    lib = load_library()
    n = lib.dtf_load_idx_images(path.encode(), None, 0)
    if n < 0:
        raise OSError(f"failed to parse IDX images: {path}")
    # IDX MNIST rows*cols is always 784; query again with a buffer.
    out = np.empty(n * 784, dtype=np.float32)
    got = lib.dtf_load_idx_images(
        path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size
    )
    if got != n:
        raise OSError(f"short read from IDX images: {path}")
    return out.reshape(n, 784)


def load_idx_labels(path: str) -> np.ndarray:
    lib = load_library()
    n = lib.dtf_load_idx_labels(path.encode(), None, 0)
    if n < 0:
        raise OSError(f"failed to parse IDX labels: {path}")
    out = np.empty(n, dtype=np.int64)
    got = lib.dtf_load_idx_labels(
        path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_long)), out.size
    )
    if got != n:
        raise OSError(f"short read from IDX labels: {path}")
    return out


def shuffle_perm(n: int, seed: int) -> np.ndarray:
    lib = load_library()
    out = np.empty(n, dtype=np.int64)
    lib.dtf_shuffle_perm(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_long)), n, seed & (2**64 - 1)
    )
    return out


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    lib = load_library()
    src = np.ascontiguousarray(src, dtype=np.float32)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    out = np.empty((idx.shape[0], src.shape[1]), dtype=np.float32)
    lib.dtf_gather_rows(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        idx.shape[0],
        src.shape[1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out


# ---------------------------------------------------------------------------
# BPE bindings (data/text.py's fast path)
# ---------------------------------------------------------------------------


def bpe_train(docs: list[str], num_merges: int) -> list[tuple[int, int]]:
    """Train byte-level BPE merges natively; bit-identical to
    data/text.py's ``_bpe_train_py`` (pinned by tests/test_text.py).
    Raises ImportError (→ the caller's pure-Python fallback) for corpora
    beyond the native path's int32 position indexing (~2 GiB)."""
    lib = load_library()
    blobs = [d.encode("utf-8") for d in docs]
    lens = np.asarray([len(b) for b in blobs], np.int64)
    if int(lens.sum()) > 0x7FFFFFF0:
        raise ImportError("corpus exceeds native BPE int32 indexing")
    data = np.frombuffer(b"".join(blobs), np.uint8)
    data = np.ascontiguousarray(data)
    out = np.empty(2 * max(num_merges, 1), np.int32)
    got = lib.dtf_bpe_train(
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        len(blobs),
        num_merges,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if got < 0:
        raise ImportError("native BPE train refused the corpus")
    return [(int(out[2 * k]), int(out[2 * k + 1])) for k in range(got)]


def bpe_encode(merges, data: bytes) -> np.ndarray:
    """Encode UTF-8 bytes with learned merges (list of pairs, or the
    pre-flattened [2K] int32 array BPETokenizer caches); bit-identical to
    data/text.py's ``_bpe_encode_py``."""
    lib = load_library()
    pairs = np.ascontiguousarray(np.asarray(merges, np.int32).reshape(-1))
    buf = np.frombuffer(data, np.uint8)
    buf = np.ascontiguousarray(buf)
    out = np.empty(max(len(buf), 1), np.int32)
    got = lib.dtf_bpe_encode(
        pairs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(pairs) // 2,
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(buf),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out[:got].copy()


def bpe_encode_batch(merges, docs: list[bytes]) -> list[np.ndarray]:
    """Encode many documents in one native call (ranks map built once) —
    the fast path under data/text.py's ``pack_documents``."""
    lib = load_library()
    pairs = np.ascontiguousarray(np.asarray(merges, np.int32).reshape(-1))
    lens = np.asarray([len(b) for b in docs], np.int64)
    data = np.ascontiguousarray(np.frombuffer(b"".join(docs), np.uint8))
    out = np.empty(max(len(data), 1), np.int32)
    out_lens = np.empty(max(len(docs), 1), np.int64)
    lib.dtf_bpe_encode_batch(
        pairs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(pairs) // 2,
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        len(docs),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
    )
    pieces, off = [], 0
    for n in out_lens[: len(docs)]:
        pieces.append(out[off : off + int(n)].copy())
        off += int(n)
    return pieces


# ---------------------------------------------------------------------------
# Failure detection bindings (SURVEY.md §5 upgrade)
# ---------------------------------------------------------------------------


class HeartbeatCoordinator:
    """Chief-side liveness tracker: workers that reported once and then went
    silent past ``timeout_ms`` count as failed, and workers that NEVER report
    count as failed once ``grace_ms`` (default 5x timeout) has elapsed since
    start — so a worker dead at t=0 is detected rather than waited on forever
    (the reference's chief blocked indefinitely in
    ``prepare_or_wait_for_session``, reference tfdist_between.py:83)."""

    def __init__(
        self,
        port: int,
        expected_workers: int,
        timeout_ms: int = 5000,
        grace_ms: int | None = None,
    ):
        self._lib = load_library()
        if grace_ms is None:
            grace_ms = 5 * timeout_ms
        self._h = self._lib.dtf_coord_start2(
            port, expected_workers, timeout_ms, grace_ms
        )
        if not self._h:
            raise OSError(f"failed to bind heartbeat coordinator on :{port}")

    def alive_count(self) -> int:
        return self._lib.dtf_coord_alive_count(self._h)

    def failed_count(self) -> int:
        return self._lib.dtf_coord_failed_count(self._h)

    def ms_since_seen(self, worker_id: int) -> int:
        return self._lib.dtf_coord_ms_since_seen(self._h, worker_id)

    def progress(self, worker_id: int) -> int:
        """Last progress-counter value in ``worker_id``'s beats; -1 if it
        never reported one (round 7: the payload is ``HB <id> <progress>``,
        bumped by trainers at epoch boundaries)."""
        return self._lib.dtf_coord_progress(self._h, worker_id)

    def ms_since_progress(self, worker_id: int) -> int:
        """Milliseconds since ``worker_id``'s progress counter last changed
        (first report counts); -1 if it never reported progress."""
        return self._lib.dtf_coord_ms_since_progress(self._h, worker_id)

    def stalled_count(self, stall_timeout_ms: int) -> int:
        """Workers ALIVE (beating within timeout) whose progress counter has
        not moved for more than ``stall_timeout_ms`` — the live-but-stalled
        class (a rank hung in a collective keeps beating; only the progress
        payload can expose it). Never-progressed workers are not counted."""
        return self._lib.dtf_coord_stalled_count(self._h, stall_timeout_ms)

    def stop(self) -> None:
        if self._h:
            self._lib.dtf_coord_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class HeartbeatWorker:
    """Worker-side heartbeat sender. Every beat carries the monotonic
    progress counter last handed to :meth:`set_progress` — the sender runs
    on a native thread, so beats (and the frozen counter) keep flowing even
    while the Python main thread hangs in a collective, which is exactly
    what lets the coordinator tell *stalled* from *dead*."""

    def __init__(self, host: str, port: int, worker_id: int, interval_ms: int = 1000):
        self._lib = load_library()
        self._h = self._lib.dtf_worker_start(host.encode(), port, worker_id, interval_ms)
        if not self._h:
            raise OSError(f"failed to start heartbeat worker to {host}:{port}")

    def set_progress(self, progress: int) -> None:
        """Advance the monotonic progress counter carried by each beat
        (trainers call this at epoch boundaries with the global step).
        Until the first call, beats carry NO counter — the detector's
        never-reported-progress carve-out covers startup import/compile."""
        if self._h:
            self._lib.dtf_worker_set_progress(self._h, max(0, int(progress)))

    def stop(self) -> None:
        if self._h:
            self._lib.dtf_worker_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


# ---------------------------------------------------------------------------
# TFRecord checksum bindings (utils/summary.py's hot path)
# ---------------------------------------------------------------------------


def crc32c(data: bytes) -> int:
    return int(load_library().dtf_crc32c(data, len(data)))


def crc32c_buffer(a: np.ndarray) -> int:
    """CRC32C over an ndarray's buffer without the ``tobytes`` copy — the
    checkpoint-manifest writer (train/resilience.py) checksums every state
    leaf per save, so large parameter tables go through the C kernel
    directly. Same value as ``crc32c(a.tobytes())``."""
    a = np.ascontiguousarray(a)
    return int(
        load_library().dtf_crc32c(
            a.ctypes.data_as(ctypes.c_char_p), a.nbytes
        )
    )


def crc32c_masked(data: bytes) -> int:
    """TFRecord-masked CRC32C (rotate-right-15 + magic), computed natively."""
    return int(load_library().dtf_crc32c_masked(data, len(data)))
