"""Host-side KV block pool: allocator, prefix radix, n-gram drafts.

The paged serving engine (serve.py ``paged=True``) splits the KV cache
into fixed-size BLOCKS drawn from one shared pool (vLLM's PagedAttention
layout): a request holds ``ceil((prompt+max_new)/block_size)`` blocks
instead of a whole ``max_len`` slab, so concurrent occupancy scales with
*actual* request footprints — the serving-side analog of the reference's
async-over-sync thesis (throughput comes from packing independent work,
not reserving for the worst case; reference tfdist_between.py:64-66
async workers applying updates as they land vs the lock-stepped sync
mode — PARITY.md C10, the 0.8156-vs-0.618 oracle). Three host-side
pieces, all
jax-free (the lean-import convention — the device half lives in
``ops/paged_attention.py`` + ``GPTLM.{extend_paged,decode_paged}``):

- :class:`BlockAllocator` — refcounted free-list over the pool. A block
  is FREE (on the list), or held by one or more owners (a live slot,
  the prefix cache, or both); ``release`` returns it to the free list
  only at refcount zero — the copy-on-write discipline that lets two
  requests map the same physical prompt block.
- :class:`PrefixCache` — hash-consed radix over FULL prompt blocks:
  node key = (parent block id, that block's token content), so a chain
  lookup is exact-prefix matching by construction (a block's K/V depends
  only on the tokens at and before it — causal attention — so content-
  chain identity implies K/V identity). A shared system prompt prefills
  once; later requests map the cached physical blocks (refcount +1 each)
  and prefill only their suffix. Only IMMUTABLE blocks enter the radix:
  full blocks of the prompt region, which no live slot ever rewrites
  (generation writes start past the prompt), so sharing never needs an
  actual copy. Eviction is LRU over leaf blocks held by the cache alone.
- :func:`lookup_draft` — prompt-lookup speculative drafts (n-gram
  continuation from the request's own context; no draft model), verified
  by one batched target pass in the engine's greedy-exact verify graph.
- :class:`FleetPrefixIndex` (round 23) — the ROUTER's view of the same
  radix identity: prompt-block chains → which replica holds that prefix
  warm, so the disaggregated fleet can choose the prefill leg by warmth
  (docs/serving.md §disaggregation).
"""

from __future__ import annotations

from collections import deque

import numpy as np


class QueueFull(RuntimeError):
    """``submit()`` refused: the admission queue is at ``queue_limit``.
    The loud alternative to unbounded growth — a caller (or the fleet
    router, serve_fleet.py) is expected to retry later or route the
    request to a less-saturated replica (``/healthz`` surfaces
    ``queue_saturation`` exactly for that decision). Lives here (not in
    serve.py) so the jax-free router shares ONE exception surface with
    the engine; serve.py re-exports it."""


class RequestCancelled(RuntimeError):
    """``result()`` for a request cancelled at a chunk boundary (deadline
    expiry): the slot/blocks were freed and no tokens are returned.
    Raised by TextServer.result AND ReplicaRouter.result — one typed
    contract for both surfaces (re-exported from serve.py)."""


class RequestShed(RuntimeError):
    """``result()`` for a request the scheduler dropped WITHOUT spending a
    dispatch on it: it arrived past its deadline, its deadline expired (or
    became provably unreachable) while queued, or it was the
    lowest-priority victim of saturation shedding. Distinct from
    :class:`RequestCancelled` — a cancel interrupts work already started
    (a resident past its deadline); a shed refuses work before any
    prefill. Raised by TextServer.result AND ReplicaRouter.result
    (re-exported from serve.py)."""


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` positions."""
    if tokens < 0:
        raise ValueError(f"tokens must be >= 0, got {tokens}")
    return -(-tokens // block_size)


def kv_position_bytes(
    num_layers: int,
    kv_heads: int,
    head_dim: int,
    elem_bytes: int,
    scale_bytes: int = 0,
) -> int:
    """HBM bytes ONE cached position occupies across the whole stack:
    K + V payload rows plus (quantized caches, round 15) the per-row
    scale side tensors — ``scale_bytes`` per KV head per tensor per
    layer (4 for the f32 scales ``ops/quantized.quantize_kv`` emits,
    0 for the bf16 identity layout). This is the element-size-aware
    accounting the quantized pool's capacity claim rests on: admission
    is gated on blocks, so blocks-per-budget MUST derive from what a
    block actually costs, scales included — counting payload alone
    would overstate int8 capacity by ~``head_dim·elem/4`` percent."""
    if min(num_layers, kv_heads, head_dim, elem_bytes) < 1:
        raise ValueError(
            "num_layers/kv_heads/head_dim/elem_bytes must all be >= 1"
        )
    if scale_bytes < 0:
        raise ValueError(f"scale_bytes must be >= 0, got {scale_bytes}")
    return 2 * num_layers * kv_heads * (head_dim * elem_bytes + scale_bytes)


def kv_block_bytes(
    block_size: int,
    *,
    num_layers: int,
    kv_heads: int,
    head_dim: int,
    elem_bytes: int,
    scale_bytes: int = 0,
) -> int:
    """HBM bytes one pool block occupies (payload + scales)."""
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    return block_size * kv_position_bytes(
        num_layers, kv_heads, head_dim, elem_bytes, scale_bytes
    )


def blocks_for_hbm_bytes(
    budget_bytes: int,
    block_size: int,
    *,
    num_layers: int,
    kv_heads: int,
    head_dim: int,
    elem_bytes: int,
    scale_bytes: int = 0,
) -> int:
    """Pool blocks a byte budget holds at the given element size — the
    knob that turns "int8 halves the bytes" into "the pool admits ~2×
    the positions": the SAME ``kv_hbm_bytes`` passed to two servers
    yields ~``elem_ratio`` × the blocks for the smaller dtype (minus the
    scale overhead, which this accounting charges honestly)."""
    bb = kv_block_bytes(
        block_size,
        num_layers=num_layers,
        kv_heads=kv_heads,
        head_dim=head_dim,
        elem_bytes=elem_bytes,
        scale_bytes=scale_bytes,
    )
    n = int(budget_bytes) // bb
    if n < 1:
        raise ValueError(
            f"HBM budget {budget_bytes} B holds no {bb} B block; raise the "
            "budget or shrink block_size"
        )
    return n


class BlockAllocator:
    """Refcounted free-list allocator over ``num_blocks`` physical KV
    blocks. Invariants (pinned by the randomized schedule in
    tests/test_serve.py): a block is on the free list iff its refcount
    is 0; ``alloc`` never hands out a live block; free + live counts
    always partition the pool."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = num_blocks
        self._free: deque[int] = deque(range(num_blocks))
        self._ref = [0] * num_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    def can_alloc(self, n: int) -> bool:
        return len(self._free) >= n

    def alloc(self, n: int) -> list[int]:
        """Take ``n`` fresh blocks at refcount 1. Raises ``MemoryError``
        when the free list is short — the caller (admission control)
        checks ``can_alloc``/evicts first, so hitting this is a bug."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        if n > len(self._free):
            raise MemoryError(
                f"pool exhausted: want {n} blocks, {len(self._free)} free"
            )
        out = [self._free.popleft() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def retain(self, bid: int) -> None:
        """One more owner for a LIVE block (prefix-cache hit sharing)."""
        if self._ref[bid] <= 0:
            raise ValueError(f"retain of free block {bid}")
        self._ref[bid] += 1

    def release(self, bid: int) -> bool:
        """Drop one owner; returns True when the block went back to the
        free list (refcount hit zero)."""
        if self._ref[bid] <= 0:
            raise ValueError(f"release of free block {bid}")
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)
            return True
        return False

    def refcount(self, bid: int) -> int:
        return self._ref[bid]

    def reset(self) -> None:
        """Everything back to free (server teardown)."""
        self._free = deque(range(self.num_blocks))
        self._ref = [0] * self.num_blocks


class PrefixCache:
    """Hash-consed radix of full prompt blocks over a
    :class:`BlockAllocator`. The cache holds ONE reference on every
    registered block (so completed requests can release theirs and the
    K/V stays resident for future hits); eviction releases that
    reference, leaf-first, LRU, and only for blocks nobody else holds."""

    def __init__(
        self, allocator: BlockAllocator, block_size: int, *, journal=None
    ):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.allocator = allocator
        self.block_size = block_size
        # Optional event journal (round 12): eviction-under-pressure is
        # the one pool decision invisible from the admission events —
        # a warm radix shrinking changes future hit rates, so each
        # evict() that freed anything lands as a prefix_evict event.
        # Duck-typed (anything with .emit) to keep this module jax- and
        # observability-import-free for its unit tests.
        self.journal = journal
        self._map: dict = {}  # (parent bid | -1, block tokens) -> bid
        self._key_of: dict = {}  # bid -> its radix key
        self._children: dict = {}  # bid -> registered child count
        self._lru: dict = {}  # bid -> last-touch tick
        self._tick = 0

    def __len__(self) -> int:
        return len(self._map)

    def matchable_blocks(self, prompt_len: int) -> int:
        """Full blocks of an ``prompt_len``-token prompt eligible for
        matching: capped one token short of the prompt, because the
        engine always needs >= 1 suffix token to prefill (the request's
        first generated token comes from the prefill logits)."""
        return max(prompt_len - 1, 0) // self.block_size

    def match(self, tokens) -> list[int]:
        """Longest cached chain of full prompt blocks (block ids, root
        first). Pure lookup: no refcounts move — the caller retains each
        returned block if (and only if) it actually admits the request
        (the engine also owns hit/miss counting there, so a request
        re-planned across failed admission rounds counts once)."""
        bs = self.block_size
        out: list[int] = []
        parent = -1
        nmax = self.matchable_blocks(len(tokens))
        self._tick += 1
        for i in range(nmax):
            key = (parent, tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            bid = self._map.get(key)
            if bid is None:
                break
            out.append(bid)
            self._lru[bid] = self._tick
            parent = bid
        return out

    def insert(self, tokens, block_ids: list[int], n_full: int) -> int:
        """Register the first ``n_full`` blocks of a freshly prefilled
        prompt (``block_ids`` = the slot's block table). Already-cached
        links are skipped (idempotent — the chain keeps following the
        CACHED block, so concurrent same-prefix admissions converge on
        one physical chain); each newly registered block gains the
        cache's reference. Returns how many blocks were newly added."""
        bs = self.block_size
        parent = -1
        added = 0
        self._tick += 1
        for i in range(n_full):
            key = (parent, tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            bid = self._map.get(key)
            if bid is None:
                bid = block_ids[i]
                self._map[key] = bid
                self._key_of[bid] = key
                self.allocator.retain(bid)
                if parent != -1:
                    self._children[parent] = self._children.get(parent, 0) + 1
                added += 1
            self._lru[bid] = self._tick
            parent = bid
        return added

    def evictable_blocks(self) -> int:
        """Blocks :meth:`evict` could EVENTUALLY free: radix entries whose
        only owner is the cache (refcount 1). A live request always
        retains its matched chain from the root, so a refcount-1 block's
        registered descendants are refcount-1 too — the leaf-first
        cascade in :meth:`evict` reaches every block counted here."""
        return sum(
            1 for bid in self._key_of if self.allocator.refcount(bid) == 1
        )

    def evict(self, want_free: int) -> int:
        """Release cached blocks until ``want_free`` more blocks are on
        the allocator's free list (or no candidate remains). Candidates:
        radix LEAVES (no registered children) whose only owner is the
        cache (refcount 1) — blocks a live request still maps are never
        touched. LRU order; evicting a leaf can expose its parent, so
        the scan repeats. Returns the number of blocks actually freed."""
        freed = 0
        while freed < want_free:
            candidates = [
                bid
                for bid in self._key_of
                if self._children.get(bid, 0) == 0
                and self.allocator.refcount(bid) == 1
            ]
            if not candidates:
                break
            bid = min(candidates, key=lambda b: self._lru.get(b, 0))
            self._drop(bid)
            freed += 1
        if freed and self.journal is not None:
            self.journal.emit(
                "prefix_evict",
                freed_blocks=freed,
                want_free=int(want_free),
                cached_blocks=len(self._map),
            )
        return freed

    def _drop(self, bid: int) -> None:
        key = self._key_of.pop(bid)
        del self._map[key]
        self._lru.pop(bid, None)
        self._children.pop(bid, None)
        parent = key[0]
        if parent != -1:
            self._children[parent] -= 1
        self.allocator.release(bid)


class FleetPrefixIndex:
    """Fleet-wide radix over prompt prefixes → the replica believed to
    hold that prefix WARM (round 23, the disaggregated-fleet routing
    half of :class:`PrefixCache`): same hash-consed node identity
    (parent node, that block's token content), but the payload per node
    is a {replica: last-touch tick} map instead of a physical block id —
    the router holds no blocks, it holds BELIEFS about where prefixes
    live. This promotes the round-16 sticky ``affinity_tokens`` map
    (exact fixed-length key, single owner) into true longest-prefix
    matching with per-replica recency.

    Fed from two sides: optimistically at ROUTE time (the routed
    prefill replica is about to register the prompt in its own radix)
    and authoritatively from replica journal events
    (``admission``/``prefix_evict``/``weight_swap`` — see
    ``ReplicaRouter._ingest_prefix_events``). Beliefs can go stale
    either way; the router treats a lookup as a HINT (a miss on the
    replica costs one re-prefill, never correctness), which is why this
    stays jax-free and lock-free. ``drop_replica`` forgets everything a
    dead/relaunched/swapped replica was believed to hold — its radix is
    gone (relaunch) or flushed (weight swap), so the belief is provably
    wrong."""

    def __init__(self, block_size: int = 16, cap: int = 4096):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if cap < 1:
            raise ValueError(f"cap must be >= 1, got {cap}")
        self.block_size = block_size
        self.cap = cap
        self._nodes: dict = {}  # (parent key | None, block tokens) -> {replica: tick}
        self._tick = 0

    def __len__(self) -> int:
        return len(self._nodes)

    def _keys(self, tokens, nmax: int | None = None):
        bs = self.block_size
        n = len(tokens) // bs
        if nmax is not None:
            n = min(n, nmax)
        parent = None
        for i in range(n):
            key = (parent, tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            yield key
            parent = key

    def insert(self, tokens, replica: str) -> int:
        """Register every full block of ``tokens`` as warm on
        ``replica``; returns the chain depth registered. LRU-capped on
        total node count — oldest nodes fall off first (a belief cache,
        not an allocator: forgetting is always safe)."""
        self._tick += 1
        depth = 0
        for key in self._keys(tokens):
            self._nodes.setdefault(key, {})[replica] = self._tick
            depth += 1
        while len(self._nodes) > self.cap:
            oldest = min(
                self._nodes, key=lambda k: max(self._nodes[k].values())
            )
            del self._nodes[oldest]
        return depth

    def lookup(self, tokens) -> tuple[str | None, int]:
        """The replica believed to hold the LONGEST warm prefix of
        ``tokens`` (full blocks only) and its depth in blocks. A replica
        counts at depth d only if it is present on EVERY node of the
        chain up to d (a warm prefix is a chain, not a set); ties break
        to the most recently touched belief. ``(None, 0)`` = no belief."""
        alive: dict = {}  # replica -> (depth, freshest tick)
        on_chain: set | None = None
        for depth, key in enumerate(self._keys(tokens), start=1):
            node = self._nodes.get(key)
            if not node:
                break
            here = set(node) if on_chain is None else on_chain & set(node)
            if not here:
                break
            on_chain = here
            for r in here:
                alive[r] = (depth, node[r])
        if not alive:
            return None, 0
        best = max(alive.items(), key=lambda kv: kv[1])
        return best[0], best[1][0]

    def drop_replica(self, replica: str) -> int:
        """Forget every belief about ``replica`` (death, relaunch, or
        weight swap — its radix no longer holds what we thought).
        Returns the number of nodes the replica was dropped from."""
        dropped = 0
        empty = []
        for key, node in self._nodes.items():
            if replica in node:
                del node[replica]
                dropped += 1
                if not node:
                    empty.append(key)
        for key in empty:
            del self._nodes[key]
        return dropped


def lookup_draft(context, max_draft: int, ngram: int = 2):
    """Prompt-lookup decoding drafts (Saxena 2023-style, the no-model
    drafter): find the most recent PRIOR occurrence of the context's
    final ``ngram`` tokens and propose the tokens that followed it.
    Returns a list of at most ``max_draft`` ints (possibly empty — no
    match, or context shorter than the n-gram). Greedy-exact
    verification makes a bad draft cost only wasted compute, never a
    changed token, so the proposer is free to guess."""
    ctx = np.asarray(context, np.int64)
    n = ctx.size
    if max_draft < 1 or n <= ngram:
        return []
    # Prefer the newest match with a FULL max_draft continuation (recent
    # repetition is the common case: generated cycles, repeated
    # boilerplate — but the very newest match of a cyclic tail sits near
    # the context's end, where the continuation truncates to a token or
    # two; a period-length-earlier match drafts the whole cycle ahead).
    # One vectorized pass: matching every start against the tail is a
    # single [n-ngram, ngram] comparison, not O(n) Python list builds
    # per verify tick.
    tail = ctx[n - ngram:]
    windows = np.lib.stride_tricks.sliding_window_view(ctx, ngram)
    hits = np.flatnonzero((windows[: n - ngram] == tail).all(axis=1))
    if hits.size == 0:
        return []
    full = hits[hits + ngram + max_draft <= n]
    start = int(full[-1]) if full.size else int(hits[-1])
    return [int(t) for t in ctx[start + ngram : start + ngram + max_draft]]
