from distributed_tensorflow_tpu.ops.losses import (  # noqa: F401
    accuracy,
    cross_entropy,
    stable_cross_entropy,
)
from distributed_tensorflow_tpu.ops.optim import sgd  # noqa: F401
