"""Ring collectives over the device mesh.

The reference's cross-device communication was a gRPC parameter-server star
(SURVEY.md §5 "Distributed communication backend") — every gradient hop
traversed host NICs. Here the framework-level collectives are XLA's
(``psum``/``pmean`` over ICI, used by the sync strategies), and this module
additionally provides *explicit* ring algorithms built from
``lax.ppermute`` — the neighbor-exchange pattern ICI topologies are built
for. They serve two purposes:

1. load-bearing: the async strategy's periodic parameter exchange can run as
   a ring all-reduce (``AsyncDataParallel.make_exchange_fn(collective="ring")``);
2. infrastructure: the same ppermute ring is the building block for
   sequence-parallel/ring-attention workloads on a future ``seq`` mesh axis
   (SURVEY.md §5 "Long-context": absent in the reference workload; the
   machinery is first-class here).

All functions are collective-inside-``shard_map`` primitives: call them from
a function mapped over the named axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _ring_perm(n: int) -> list[tuple[int, int]]:
    return [(j, (j + 1) % n) for j in range(n)]


def _vma_of(a):
    """The varying manual axes of ``a``'s abstract value, or the empty set
    on JAX versions without ``jax.typeof`` / vma typing (the same vintage
    the ``lax.pvary`` fallback below targets — there every axis is
    cast-able and double-casting is accepted)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return frozenset(getattr(typeof(a), "vma", ()))


def to_varying(a, axis_name):
    """Cast a value to varying over ``axis_name`` (vma typing under
    ``shard_map``; accepts one axis or a tuple). Idempotent: axes the
    value ALREADY varies over are skipped — ``pcast(to='varying')``
    rejects them, and callers like the ring-attention carry inits derive
    their zeros from inputs whose vma depends on the enclosing mesh (1-D
    sp vs 2-D dp×sp). ``pcast`` is the current API; ``pvary`` its
    predecessor — routing every varying-cast through this one helper
    keeps the whole framework working on JAX versions that only have one
    of them."""
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    have = _vma_of(a)
    axes = tuple(ax for ax in axes if ax not in have)
    if not axes:
        return a
    if hasattr(lax, "pcast"):
        return lax.pcast(a, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(a, axes)
    # Pre-vma vintage (no pcast, no pvary): every value is implicitly
    # varying under shard_map and there is no rep/vma checker to satisfy —
    # the cast is an identity.
    return a


def ring_all_reduce(x: jax.Array, axis_name: str) -> jax.Array:
    """Sum ``x`` across the named axis with N-1 neighbor exchanges (each
    step moves one chunk over one ICI hop), no tree/star topology."""
    n = lax.axis_size(axis_name)
    perm = _ring_perm(n)

    def body(_, carry):
        acc, cur = carry
        cur = lax.ppermute(cur, axis_name, perm)
        return acc + cur, cur

    acc, _ = lax.fori_loop(0, n - 1, body, (x, x))
    return acc


def ring_all_mean(x: jax.Array, axis_name: str) -> jax.Array:
    n = lax.axis_size(axis_name)
    return ring_all_reduce(x, axis_name) / n


def ring_all_gather(x: jax.Array, axis_name: str) -> jax.Array:
    """Gather every device's ``x`` into a new leading axis (shape [N, ...]),
    rotating chunks around the ring. After k hops a device holds the chunk
    that originated k positions behind it."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = _ring_perm(n)
    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = lax.dynamic_update_slice(out, x[None], (idx,) + (0,) * x.ndim)

    def body(k, carry):
        out, cur = carry
        cur = lax.ppermute(cur, axis_name, perm)
        src = (idx - k - 1) % n
        out = lax.dynamic_update_slice(out, cur[None], (src,) + (0,) * x.ndim)
        return out, cur

    out, _ = lax.fori_loop(0, n - 1, body, (out, x))
    return out
