"""Ring attention: sequence-parallel attention over a mesh axis.

The reference workload has no attention and no sequence dimension
(SURVEY.md §2b "Sequence/context parallel: ABSENT — model is a fixed-784-
feature MLP"), but long-context capability is first-class in this framework:
the mesh design reserves a sequence axis and this module provides the
canonical long-context primitive — blockwise attention with the KV blocks
rotating around the device ring (one ``lax.ppermute`` hop per step), online-
softmax accumulation, O(L_local) memory per device.

Mechanics (flash-attention-style streaming):

- each device holds local blocks q, k, v of shape [B, L/n, H, D] for an
  L-token sequence sharded over the ``seq`` axis of n devices;
- n ring steps: attend local q against the currently-held KV block while a
  ``ppermute`` forwards the block to the ring neighbor; a running
  (max, sum, accumulator) triple makes the streamed softmax exact;
- causal masking uses global positions reconstructed from the ring step and
  the device's axis index, so the sharded result equals dense causal
  attention on the unsharded sequence.

Also here: ``all_to_all_seq_to_heads`` / ``heads_to_seq`` — the
Ulysses-style alternative that reshards sequence↔heads around attention so
each device computes full-sequence attention for a head subset — and
``ring_flash_attention``, the same KV ring with each hop's local attend
running the Pallas flash kernel (``ops/pallas_attention``) and hops
combined by per-row logsumexp, making memory O(block) end to end.

Call these inside ``jax.shard_map`` over the sequence axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_tpu.ops.collectives import _ring_perm, to_varying

_NEG_INF = -1e30


def _block_scores(q, k, *, scale, mask=None):
    """Pre-softmax scores for one block: q [B,Lq,H,D] x k [B,Lk,H,D] →
    [B,H,Lq,Lk] (f32), with optional mask applied as -inf."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG_INF)
    return scores


def _rotate_unless_last(kv, step, n, *, axis_name, perm):
    """Forward the KV pair one ring hop — except on the final step, whose
    rotated result the loop would discard (XLA cannot DCE inside a while
    loop, so an unconditional permute would pay one dead cross-device hop
    per attention call). The predicate is device-invariant, so all devices
    agree on whether the collective runs."""
    return lax.cond(
        step < n - 1,
        lambda kv: jax.tree.map(
            lambda x: lax.ppermute(x, axis_name, perm), kv
        ),
        lambda kv: kv,
        kv,
    )


def _window_hops(window: int | None, l_loc: int, n: int) -> int:
    """Ring steps actually needed under a sliding window: local queries
    span [my·L, (my+1)·L); the farthest-back key any of them sees is
    my·L − W + 1, i.e. ceil((W−1)/L) blocks behind — plus the diagonal.
    Hops beyond that hold KV wholly outside every band and never happen:
    THIS is sliding-window SP's traffic win (for W ≪ global L most of the
    ring is skipped), not just masked-out compute."""
    if window is None:
        return n
    return min(n, -(-(window - 1) // l_loc) + 1)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    window: int | None = None,
    kv_lens: jax.Array | None = None,
) -> jax.Array:
    """Exact attention over a sequence sharded on ``axis_name``.

    q is a local block [B, L_local, Hq, D]; k/v are local blocks with
    ``Hkv ≤ Hq`` heads (grouped-query attention: ONLY the KV heads ride the
    ring — the group factor is reclaimed as cross-device bandwidth, the one
    place GQA's saving matters most; the repeat to Hq happens locally after
    each receive). Returns the local output block [B, L_local, Hq, D] —
    equivalent to ``dense_attention`` (optionally causal / windowed) over
    the full gathered sequence.

    ``window=W`` (requires ``causal``) restricts each query to its last W
    keys; the ring then runs only ``ceil((W−1)/L_local)+1`` hops (see
    :func:`_window_hops`). ``kv_lens`` [B] int32 is the key-padding mask in
    right-padded form, in GLOBAL positions (replicated across the seq
    axis): keys at global position ≥ kv_lens[b] are masked — exactly
    ``dense_attention(kv_lens=...)`` on the gathered sequence.
    """
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, l_loc, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.array(d, jnp.float32))
    perm = _ring_perm(n)
    hops = _window_hops(window, l_loc, n)

    q32 = q.astype(jnp.float32)
    # pvary: the zero-init carries are device-invariant but the loop body
    # makes them device-varying; shard_map's vma typing requires the carry
    # types to match up front. Derived from q (q*0, not fresh constants)
    # so they also inherit any OTHER varying axes — under a 2-D dp×sp
    # shard_map the batch is varying over 'data' and the carries must be
    # too (same pattern as parallel/pipeline.py).
    pvary = partial(to_varying, axis_name=(axis_name,))
    # stop_gradient keeps the init off the AD path (a q*0 cotangent route
    # would put pcast's psum transpose on paths check_vma=False can't
    # type) while preserving q's vma on the zeros.
    zeros = jnp.moveaxis(lax.stop_gradient(q32) * 0, 1, 2)  # [b,h,l_loc,d]
    m = pvary(zeros[..., :1] + _NEG_INF)
    s = pvary(zeros[..., :1])
    o = pvary(zeros)

    q_pos = my * l_loc + jnp.arange(l_loc)  # global positions of local q rows

    def body(step, carry):
        m, s, o, kv = carry
        k_blk, v_blk = kv

        def attend(m, s, o):
            # The block held at `step` originated `step` positions behind us.
            src = (my - step) % n
            # GQA: the block circulated at Hkv heads; repeat locally (a
            # transient — never on the wire).
            k_rep, v_rep = repeat_kv(k_blk, v_blk, h)
            mask = None
            k_pos = src * l_loc + jnp.arange(l_loc)
            if causal:
                diff = q_pos[:, None] - k_pos[None, :]  # [Lq, Lk]
                mask = diff >= 0
                if window is not None:
                    mask &= diff < window
                mask = mask[None, None]  # broadcast over B, H
            if kv_lens is not None:
                valid_k = k_pos[None, :] < kv_lens[:, None]  # [B, Lk]
                valid_k = valid_k[:, None, None, :]  # over H, Lq
                mask = valid_k if mask is None else mask & valid_k
            scores = _block_scores(
                q32, k_rep.astype(jnp.float32), scale=scale, mask=mask
            )
            blk_max = jnp.max(scores, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, blk_max)
            # Guard fully-masked rows (every score -inf): exp(-inf - -inf).
            m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
            corr = jnp.exp(m - m_safe)
            p = jnp.exp(scores - m_safe)
            if mask is not None:
                p = jnp.where(mask, p, 0.0)
            s_new = s * corr + jnp.sum(p, axis=-1, keepdims=True)
            pv = jnp.einsum(
                "bhqk,bkhd->bhqd", p, v_rep.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            return m_new, s_new, o * corr + pv

        if causal:
            # Blocks strictly ahead of every local q row are fully masked:
            # skip their einsums entirely (devices early in the ring would
            # otherwise burn ~half the attention FLOPs on zeroed work).
            src = (my - step) % n
            m, s, o = lax.cond(src > my, lambda m, s, o: (m, s, o), attend, m, s, o)
        else:
            m, s, o = attend(m, s, o)
        kv = _rotate_unless_last(
            (k_blk, v_blk), step, hops, axis_name=axis_name, perm=perm
        )
        return m, s, o, kv

    m, s, o, _ = lax.fori_loop(0, hops, body, (m, s, o, (k, v)))
    out = o / jnp.maximum(s, 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    window: int | None = None,
    kv_lens: jax.Array | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
) -> jax.Array:
    """:func:`ring_attention` with the within-device attend replaced by the
    Pallas flash kernel (``ops/pallas_attention``): the cross-device KV ring
    is unchanged, but each hop's local block-pair runs blockwise in VMEM, so
    per-device memory is O(block) end to end — no [L_local, L_local] score
    matrix either. Exact (not approximate): each hop returns (partial out,
    per-row logsumexp) over its KV chunk and the running result is the
    lse-weighted combination, which telescopes to the full softmax.

    Causal masking decomposes per hop: the KV block held at hop ``step``
    originated ``step`` positions behind this device, so it is entirely in
    the past (plain full attention), the diagonal (standard causal flash —
    offsets coincide), or entirely in the future (skipped; its weight in the
    combine is exactly zero via lse = -inf). Differentiation rides the flash
    kernel's custom VJP — the lse cotangent folds into its delta term.

    ``kv_lens`` [B] int32: key-padding in right-padded form, GLOBAL
    positions (replicated across the seq axis) — same semantics as
    :func:`ring_attention`. Each hop passes the kernel its block-relative
    remainder ``clip(kv_lens − src·L_loc, 0, L_loc)``; a fully-padded hop
    contributes weight exp(lse≈−inf) = 0 in the combine.

    Grouped-query attention: k/v may carry fewer heads (Hkv ≤ Hq). Like
    :func:`ring_attention`, only the Hkv-head blocks ride the ring; the
    flash kernel maps query-head groups onto KV heads via its grid index
    maps, so there is no materialized repeat at all on this path.

    ``window=W`` (requires ``causal``): the ring runs only
    ``ceil((W−1)/L_loc)+1`` statically-unrolled hops (the traffic win —
    out-of-band blocks never move), the diagonal hop runs causal+windowed
    flash, and each past hop runs the kernel with a static position
    ``offset`` of ``step·L_loc`` — the shifted band.
    """
    from distributed_tensorflow_tpu.ops.pallas_attention import (
        flash_attention_with_lse,
    )

    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, l_loc, h, d = q.shape
    perm = _ring_perm(n)
    # The kernel's declared output vma must match ALL axes the inputs vary
    # over — under a 2-D dp×sp shard_map that is {data, seq}, not just the
    # ring axis (jax.typeof reads the tracer's vma; a plain jit gives the
    # empty set plus the ring axis).
    from distributed_tensorflow_tpu.ops.collectives import _vma_of

    vma = _vma_of(q) | {axis_name}
    kw = dict(block_q=block_q, block_k=block_k, vma=tuple(vma))

    pvary = partial(to_varying, axis_name=(axis_name,))
    # Zero/-inf init carries and skip-branch constants derived from q
    # (stop_gradient(q)*0 — off the AD path) so they inherit q's full vma
    # (see ring_attention above).
    _q0 = lax.stop_gradient(q) * 0
    _zo = lambda dt=jnp.float32: _q0.astype(dt)  # noqa: E731
    _zlse = _q0[..., 0].astype(jnp.float32) + _NEG_INF  # [b, l_loc, h]

    def _hop_lens(src):
        # Block-relative key-padding for the block held this hop (its keys
        # cover global positions [src·L_loc, (src+1)·L_loc)).
        if kv_lens is None:
            return None
        return jnp.clip(kv_lens - src * l_loc, 0, l_loc)

    def _skip(q, kb, vb, lens):
        # Constants, but typed varying to match the flash branches' outputs
        # under check_vma (all lax.switch/cond branches must agree).
        return pvary(_zo(q.dtype)), pvary(_zlse)

    def _combine(o, lse, o_i, lse_i):
        new_lse = jnp.logaddexp(lse, lse_i)
        # Weights sum to exactly 1; fully-masked rows keep lse ~ -inf and
        # contribute 0 (exp of a huge negative), never NaN.
        w_prev = jnp.exp(lse - new_lse)
        w_new = jnp.exp(lse_i - new_lse)
        o = o * w_prev[..., None] + o_i.astype(jnp.float32) * w_new[..., None]
        return o, new_lse

    if window is not None:
        # Statically-unrolled bounded ring: hop count and each hop's kernel
        # offset are compile-time constants (the kernel's masks are static).
        hops = _window_hops(window, l_loc, n)
        o = pvary(_zo())
        lse = pvary(_zlse)
        kv = (k, v)
        for step in range(hops):
            k_blk, v_blk = kv
            src = (my - step) % n
            lens = _hop_lens(src)
            if step == 0:
                # src == my always: the diagonal hop.
                o_i, lse_i = flash_attention_with_lse(
                    q, k_blk, v_blk,
                    causal=True, window=window, kv_lens=lens, **kw,
                )
            else:
                o_i, lse_i = lax.cond(
                    src > my,  # wrapped around: a future block
                    _skip,
                    lambda q, kb, vb, lens, _off=step * l_loc: (
                        flash_attention_with_lse(
                            q, kb, vb,
                            causal=True, window=window, offset=_off,
                            kv_lens=lens, **kw,
                        )
                    ),
                    q, k_blk, v_blk, lens,  # lens=None is an empty pytree
                )
            o, lse = _combine(o, lse, o_i, lse_i)
            if step < hops - 1:
                kv = jax.tree.map(
                    lambda x: lax.ppermute(x, axis_name, perm), kv
                )
        return o.astype(q.dtype)

    o = pvary(_zo())
    lse = pvary(_zlse)

    def _full(q, kb, vb, lens):
        return flash_attention_with_lse(
            q, kb, vb, causal=False, kv_lens=lens, **kw
        )

    def _diag(q, kb, vb, lens):
        return flash_attention_with_lse(
            q, kb, vb, causal=True, kv_lens=lens, **kw
        )

    def body(step, carry):
        o, lse, (k_blk, v_blk) = carry
        src = (my - step) % n
        lens = _hop_lens(src)
        if causal:
            idx = jnp.where(src > my, 2, jnp.where(src == my, 1, 0))
            o_i, lse_i = lax.switch(
                idx, (_full, _diag, _skip), q, k_blk, v_blk, lens
            )
        else:
            o_i, lse_i = _full(q, k_blk, v_blk, lens)
        o, lse = _combine(o, lse, o_i, lse_i)
        kv = _rotate_unless_last(
            (k_blk, v_blk), step, n, axis_name=axis_name, perm=perm
        )
        return o, lse, kv

    o, lse, _ = lax.fori_loop(0, n, body, (o, lse, (k, v)))
    return o.astype(q.dtype)


def group_query_heads(q: jax.Array, num_kv_heads: int) -> jax.Array:
    """[..., Hq, D] → [..., Hkv, G, D]: the NON-materializing side of the
    GQA contract — query head h belongs to KV head ``h // (Hq/Hkv)``,
    exactly the mapping :func:`repeat_kv` expands (and the flash kernel's
    grid index maps implement). Callers that contract grouped queries
    against Hkv-width keys/values (the decode path) go through this helper
    so the mapping lives in one place."""
    *lead, hq, d = q.shape
    if hq % num_kv_heads:
        raise ValueError(
            f"query heads {hq} must be a multiple of KV heads {num_kv_heads}"
        )
    return q.reshape(*lead, num_kv_heads, hq // num_kv_heads, d)


def repeat_kv(k, v, num_q_heads: int):
    """Repeat k/v heads up to ``num_q_heads`` (GQA semantics as one helper
    so the dense reference, the LM's ring/decode paths, and any future
    caller can't silently diverge from the flash kernel's group mapping)."""
    hkv = k.shape[2]
    if hkv == num_q_heads:
        return k, v
    if num_q_heads % hkv:
        raise ValueError(
            f"query heads {num_q_heads} must be a multiple of KV heads {hkv}"
        )
    g = num_q_heads // hkv
    return jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2)


def dense_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    window: int | None = None,
    kv_lens: jax.Array | None = None,
) -> jax.Array:
    """Reference dense attention on unsharded [B, L, H, D] (for tests and
    single-device use). ``window=W`` (requires ``causal``) restricts each
    query to its last W keys, self included — the sliding-window mask.
    ``kv_lens`` [B] int32 is the key-padding mask in right-padded form:
    keys at positions ≥ kv_lens[b] are masked out for every query (each
    length must be ≥ 1; queries at padded positions produce well-defined
    garbage — mask them in the loss, e.g. ``GPTLM.loss(lengths=...)``).
    Grouped-query attention: k/v with fewer heads are repeated up to the
    query head count (the semantics the flash kernel implements without the
    materialized repeat)."""
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    k, v = repeat_kv(k, v, q.shape[2])
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.array(d, jnp.float32))
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        l_q, l_k = scores.shape[-2], scores.shape[-1]
        diff = jnp.arange(l_q)[:, None] - jnp.arange(l_k)[None, :]
        mask = diff >= 0
        if window is not None:
            mask &= diff < window
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    if kv_lens is not None:
        l_k = scores.shape[-1]
        valid_k = jnp.arange(l_k)[None, :] < kv_lens[:, None]  # [B, Lk]
        scores = jnp.where(valid_k[:, None, None, :], scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bhqd", w, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


# ---------------------------------------------------------------------------
# Ulysses-style alternative: all-to-all resharding seq <-> heads
# ---------------------------------------------------------------------------


def all_to_all_seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, L/n, H, D] seq-sharded → [B, L, H/n, D] head-sharded: each device
    trades sequence shards for a head subset (one all-to-all), after which
    plain full-sequence attention runs locally per head group."""
    n = lax.axis_size(axis_name)
    b, l_loc, h, d = x.shape
    x = x.reshape(b, l_loc, n, h // n, d)
    x = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=False)
    # all_to_all with these axes yields [B, n, l_loc, h//n, d] → merge seq.
    return x.reshape(b, n * l_loc, h // n, d)


def all_to_all_heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """Inverse of :func:`all_to_all_seq_to_heads`."""
    n = lax.axis_size(axis_name)
    b, l, h_loc, d = x.shape
    x = x.reshape(b, n, l // n, h_loc, d)
    x = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=3, tiled=False)
    # yields [B, l//n, h_loc, n, d]; the received axis (3) indexes the head
    # *group*, which is the major part of the head index — transpose it in
    # front of h_loc before merging, or heads come back interleaved.
    x = jnp.einsum("blhnd->blnhd", x)
    return x.reshape(b, l // n, n * h_loc, d)


def ulysses_attention(
    q, k, v, axis_name: str, *, causal: bool = False,
    window: int | None = None,
):
    """Sequence-parallel attention via all-to-all (Ulysses): reshard to
    head-parallel, run dense attention on the full sequence locally, reshard
    back. Requires H divisible by the axis size — under GQA, BOTH head
    counts (k/v trade their own Hkv heads, and the n-chunking of q heads
    aligns with the kv chunks exactly when n | Hkv: local q head j maps to
    local kv head j//g, which is ``repeat_kv``'s convention, so the local
    dense attention needs no cross-device head traffic). ``window`` is the
    sliding-window mask, applied by the full-sequence local attention (no
    hop-skipping to reason about — the ring's banding trick has no analog
    here; Ulysses moves heads, not KV blocks)."""
    q2 = all_to_all_seq_to_heads(q, axis_name)
    k2 = all_to_all_seq_to_heads(k, axis_name)
    v2 = all_to_all_seq_to_heads(v, axis_name)
    out = dense_attention(q2, k2, v2, causal=causal, window=window)
    return all_to_all_heads_to_seq(out, axis_name)
