"""Paged KV-cache primitives: block-table gather/scatter + extend attention.

The reference has no attention — and no serving — at all (SURVEY.md
§2b: its model is a fixed MLP and its only "inference" is the in-loop
eval fetch, reference tfsingle.py:94); this module is new capability on
round-2's attention surface, with masking semantics matching
``ops/pallas_attention.py`` (causal + optional sliding window + ragged
``kv_lens``) re-addressed through block tables.

The device half of the paged serving cache (host half:
``serve_pool.py``; model plumbing: ``GPTLM.extend_paged`` /
``decode_paged``). K/V live in one shared pool of fixed-size blocks
``[num_blocks, block_size, Hkv, Dh]`` per layer; each serving slot maps
its logical positions through a block table ``[S, max_blocks]`` —
position ``p`` of slot ``s`` lives at
``pool[table[s, p // bs], p % bs]``. Attention reads K/V through the
table with a GATHER into a per-slot contiguous view (the vLLM dense
path): correctness lives in the masks, not the layout, so the flash
kernel is off the critical path — a contiguous gathered view feeds the
same dense math the slab cache used, and a Pallas kernel that walks the
table natively can slot in later without touching the engine.

Out-of-range discipline: unused table entries and masked (pad /
non-admitted) writes are routed to a sentinel block index ``num_blocks``
(one PAST the pool) and dropped via scatter ``mode="drop"`` — never
``-1``, which JAX index arithmetic would wrap to the pool's last block
and silently corrupt it. Gathers of garbage table entries are fine:
their positions are masked out of every softmax by the validity masks
below (same stale-bytes-unreachable stance as ``SlotKVCache``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.ops.ring_attention import group_query_heads

_NEG_INF = -1e30


def gather_block_view(pool_layer: jax.Array, block_tables: jax.Array):
    """One layer's per-slot contiguous K (or V) view through the block
    tables: ``[num_blocks, bs, Hkv, Dh]`` + ``[S, NB]`` →
    ``[S, NB*bs, Hkv, Dh]``, where view position ``p`` is logical
    position ``p`` of the slot. Unused table entries gather garbage that
    the caller's validity mask must keep out of the softmax."""
    bs = pool_layer.shape[1]
    view = jnp.take(pool_layer, block_tables, axis=0)  # [S, NB, bs, H, D]
    s, tabs = block_tables.shape
    return view.reshape(s, tabs * bs, *pool_layer.shape[2:])


def scatter_token_kv(
    pool_layer: jax.Array,
    kv: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    valid: jax.Array,
):
    """Write per-slot K (or V) rows into one layer's pool through the
    block tables. ``kv`` [S, L, Hkv, Dh] holds the rows for logical
    ``positions`` [S, L] (absolute per slot); ``valid`` [S, L] masks pad
    positions and non-admitted slots — their writes drop at the sentinel
    block. Distinct live slots never map the same WRITABLE block (the
    allocator shares only immutable full prompt blocks, and writes land
    past the prompt), so the scatter rows are disjoint by construction.

    Delegates to :func:`scatter_token_kv_all_layers` with a 1-layer pool
    so the sentinel/index arithmetic lives in exactly one place."""
    return scatter_token_kv_all_layers(
        pool_layer[None], kv[None], block_tables, positions, valid
    )[0]


def scatter_token_kv_all_layers(
    pool: jax.Array,
    kvs: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    valid: jax.Array,
):
    """All-layer variant (the extend path scatters once after its layer
    scan): ``pool`` [n, NB, bs, Hkv, Dh], ``kvs`` [n, S, L, Hkv, Dh]."""
    n, nb, bs = pool.shape[0], pool.shape[1], pool.shape[2]
    bidx = jnp.take_along_axis(block_tables, positions // bs, axis=1)
    bidx = jnp.where(valid, bidx, nb)
    off = positions % bs
    s, l = positions.shape
    flat = kvs.reshape(n, s * l, *kvs.shape[3:])
    return pool.at[:, bidx.reshape(-1), off.reshape(-1)].set(
        flat, mode="drop"
    )


def paged_extend_attention(
    q: jax.Array,
    k_new: jax.Array,
    v_new: jax.Array,
    k_view: jax.Array,
    v_view: jax.Array,
    q_positions: jax.Array,
    prefix_lens: jax.Array,
    suffix_lens: jax.Array,
    window: int | None = None,
):
    """Attention for an EXTEND step: suffix queries over (cached prefix
    read through the block tables) ++ (the suffix's own fresh K/V),
    causal by ABSOLUTE position.

    q [S, L, Hq, Dh] at absolute ``q_positions`` [S, L]
    (= prefix + 0..L-1 per slot); k_new/v_new [S, L, Hkv, Dh] are the
    suffix's keys/values (same positions); k_view/v_view [S, C, Hkv, Dh]
    are the gathered pool views, where view index j IS absolute position
    j. Validity: view keys need ``j < prefix_lens`` STRICTLY — the view
    also covers the suffix's (not yet scattered) positions, which hold
    garbage here and arrive via the fresh half instead; fresh keys need
    the in-suffix causal triangle and ``< suffix_lens`` (pad rows).
    ``window=W`` adds the sliding band ``key_pos > q_pos − W`` on both
    halves (the paged cache addresses absolutely, so the band is a mask,
    not a rolling layout). GQA contracts grouped queries against
    Hkv-width keys directly (``group_query_heads`` — no materialized
    repeat), f32 scores like every attention here."""
    s, l, hq, dh = q.shape
    hkv = k_new.shape[2]
    c = k_view.shape[1]
    qg = group_query_heads(q, hkv)  # [S, L, Hkv, G, Dh]
    scale = 1.0 / jnp.sqrt(jnp.asarray(dh, jnp.float32))

    kcat = jnp.concatenate(
        [k_view.astype(jnp.float32), k_new.astype(jnp.float32)], axis=1
    )  # [S, C+L, Hkv, Dh]
    vcat = jnp.concatenate(
        [v_view.astype(jnp.float32), v_new.astype(jnp.float32)], axis=1
    )
    scores = (
        jnp.einsum(
            "slhgd,skhd->shglk",
            qg.astype(jnp.float32),
            kcat,
            preferred_element_type=jnp.float32,
        )
        * scale
    )  # [S, Hkv, G, L, C+L]

    kpos = jnp.concatenate(
        [
            jnp.broadcast_to(jnp.arange(c)[None, :], (s, c)),
            q_positions,
        ],
        axis=1,
    )  # [S, C+L] absolute key positions
    real = jnp.concatenate(
        [
            jnp.arange(c)[None, :] < prefix_lens[:, None],
            jnp.arange(l)[None, :] < suffix_lens[:, None],
        ],
        axis=1,
    )  # [S, C+L]
    mask = real[:, None, :] & (kpos[:, None, :] <= q_positions[:, :, None])
    if window is not None:
        mask &= kpos[:, None, :] > q_positions[:, :, None] - window
    # [S, L, C+L] → broadcast over (Hkv, G)
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "shglk,skhd->slhgd", w, vcat, preferred_element_type=jnp.float32
    )
    return out.reshape(s, l, hq, dh).astype(q.dtype)
