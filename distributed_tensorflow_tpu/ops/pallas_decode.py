"""Pallas TPU kernel: fused single-token decode step for one GPT block.

The reference has no generative path at all (its one "inference" is the
in-loop accuracy fetch, reference tfsingle.py:94); serving decode is this
framework's hottest un-kerneled path. At L=1 each transformer block of
``models/gpt.py`` lowers to ~20 small XLA ops (the ``decode_step``
docstring), so per-token time is dominated by per-op dispatch overhead
and KV-cache HBM traffic, not FLOPs — the round-5 unroll fix
(939→306 µs/token) showed decode gaps track cache-traffic ratios. This
module collapses one block's whole single-token step into ONE Pallas
launch per layer:

    layernorm₁ → QKV projection → RoPE → quantize-on-write of the fresh
    K/V row → online-softmax attention over the resident cache →
    output projection → residual → layernorm₂ → dense FFN → residual

with the block's weights and the token's activations VMEM-resident
across the launch, and the KV cache read block-by-block straight from
the slab rows or the paged pool (block tables ride as scalar-prefetch
arguments, so the pool gather is grid index-map arithmetic — no XLA
gather materializes a contiguous view). Quantized caches (round 15)
dequantize int8/fp8 payload blocks *inside* the kernel — the launch
reads 1-byte elements plus the per-row f32 scales and upcasts in VMEM,
which is where the 2× HBM-bytes claim becomes a latency claim. Per the
round-15 rule, dequantization targets the COMPUTE dtype, never f32
storage (the f32 view exists only as the transient dot operand).

Grid: ``(S, Hkv·nc + 1)`` — per serving slot, one step per
(KV head, cache block) pair plus one finalize step. TPU grids run
sequentially with the minor dimension fastest, so VMEM scratch carries
the layernormed token row, the current head's online-softmax state
(m/l/acc as [g, 1]/[g, Dh] 2-D tiles — 1-D vectors trip Mosaic relayout
bugs, CLAUDE.md), and the per-head attention outputs across the slot's
steps. Weight refs use constant index maps, so Mosaic fetches them once
per launch and re-uses the resident copy every step.

The fresh K/V row is folded into the attention ONLINE-SOFTMAX INIT
(m = s_fresh, l = 1, acc = v_fresh — exactly one unmasked entry) after
a round-trip through the cache's storage dtype, so the kernel attends
precisely the values the cache will hold — the round-15 uniform rule
("a quantized cache attends stored values EVERYWHERE") that keeps the
fused engine token-compatible with the XLA engine. The cache blocks
themselves are attended with the fresh position masked OUT
(``idx != slot`` / ``idx < length``): the kernel reads the PRE-write
cache, so the write's slot must come from registers, not memory.

In the PER-LAYER kernel the one-row cache COMMIT stays outside the
launch (models/gpt.py applies the same ``.at[rows, slot].set`` /
``scatter_token_kv`` index math as the XLA engine): TPU output blocks
may only be revisited on consecutive grid steps, so an in-kernel
scatter would either copy the whole cache through an aliased output
(doubling the HBM traffic this kernel exists to remove) or need a
manual-DMA HBM path. Same division of labor as the fused flash
backward's dq-partial sum (ops/pallas_attention.py).

Round 20 grows the per-layer kernel into a per-TOKEN tier
(:func:`decode_token_slab` / :func:`decode_token_paged` /
:func:`verify_tokens_paged` — ``decode_engine="pallas"``; the per-layer
kernel stays as ``"pallas-layer"``, the escape hatch + parity oracle,
the round-13 fused-vs-split pattern):

- **Multi-layer megakernel**: the layer loop joins the grid as the
  OUTERMOST dimension ``(n_layers, S, Hkv·nc + 1)`` and per-layer
  weights are STREAMED through layer-indexed block maps instead of
  held constant-index-map resident — one launch per token amortizes
  the per-layer launch overhead, and the VMEM weight budget becomes a
  per-LAYER cap (only the current layer's blocks are resident). The
  residual rows live in an [S, d] f32 VMEM scratch across the whole
  sequential grid.
- **In-kernel cache commit**: the cache arrays ride the launch TWICE —
  once as BlockSpec-pipelined read operands (unchanged structure) and
  once as ``memory_space=ANY`` operands aliased input→output
  (``input_output_aliases``), written by small manual DMAs at each
  layer's finalize step. That sidesteps the output-revisit rule (the
  commit is a DMA, not a pipelined output block) without copying the
  cache. Inactive rows SKIP the DMA — exactly the XLA scatter's
  drop-at-sentinel / write-old-value-back no-op, so the committed
  bytes match the XLA index math bit-for-bit on the storage dtype
  (scale side tensors included). Writes are disjoint from every read
  by construction: the kernel attends the PRE-write cache (write slot
  masked out / ``idx < length`` strict), and active slots never share
  writable blocks (the serve_pool allocator invariant — COW prefixes
  are read-only).
- **Fused speculation-verify**: a small-L (L ≤ spec_draft+1) paged
  verify kernel — the ragged ``extend_paged`` math with the suffix
  causal block folded into the online-softmax init, fresh rows
  round-tripped through the storage dtype (round-15 uniform rule),
  strict ``idx < prefix_len`` cache validity, and per-position commit
  DMAs gated on ``li < suffix_len`` — the greedy-exact acceptance
  contract ("a bad draft never changes a token") rides on the same
  quantize-on-write parity as the decode kernels.

``interpret=None`` auto-selects the Pallas interpreter off-TPU and the
Mosaic compiler on TPU (the ops/pallas_attention.py convention); parity
vs the XLA engine is pinned in tests/test_pallas_decode.py (interpreter)
and recorded on-chip by ``tools/attention_parity.py --write-docs``
(``decode-fused-vs-xla:*`` per-layer rows; round 20 adds
``decode-mega-vs-xla:*`` and ``verify-fused-vs-xla:*``).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_EPS = 1e-12
# qmax per quantized KV dtype — MUST match ops/quantized._QMAX (the
# kernel re-derives the same symmetric per-row scales the XLA engine
# commits, so both engines attend identical stored values).
_QMAX = {"int8": 127.0, "fp8": 448.0}
_STORAGE = {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}


def _pick_cache_block(c: int, requested: int | None) -> int:
    """Largest power-of-two divisor of the cache length ≤ 512 (one score
    tile is [g, bc] — tiny; the cap bounds the resident KV block at
    bc·Dh elements), or ``c`` itself for short/odd caches (Mosaic pads
    non-tile-multiple shapes; serving caches are small enough that a
    single whole-cache block is fine)."""
    if requested is not None:
        if c % requested:
            raise ValueError(f"block {requested} must divide cache {c}")
        return requested
    for cand in (512, 256, 128, 64, 32, 16, 8):
        if c % cand == 0 and cand <= c:
            return cand
    return c


def _ln_row(x, scale_ref, bias_ref):
    """f32 layernorm on a [1, d] row — the models/base.layernorm
    arithmetic verbatim (eps included), so the fused block cannot drift
    numerically from the XLA block."""
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + 1e-5)) * scale_ref[:] + bias_ref[:]


def _rope_rows(x, pos_f, dh: int, base: float):
    """Rotary embedding on [rows, Dh] — the models/gpt._rope pair
    rotation in f32. ``pos_f`` is a scalar (all rows at the slot's own
    position — the decode step) or a [rows, 1] f32 column (per-row
    positions — the verify kernel's suffix rows); both broadcast
    against the [1, half] frequency row identically."""
    half = dh // 2
    io = lax.broadcasted_iota(jnp.float32, (1, half), 1)
    # base ** (-i/half) in the models/gpt._rope evaluation order (the
    # exp(-ln·i/half) refactoring differs in the last ulp, which the
    # parity tests would otherwise have to budget for).
    freqs = jnp.power(base, -io / half)
    ang = pos_f * freqs
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[:, :half], x[:, half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def _quant_row(x, kv_q: str):
    """Symmetric per-row quantization of [rows, Dh] — the
    ops/quantized.quantize_kv recipe (amax over the lane dim, eps floor,
    int8 round-and-clip / fp8 cast) re-derived in-kernel so the fused
    engine commits bit-identical rows to the XLA engine."""
    qmax = _QMAX[kv_q]
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, _EPS) / qmax
    xs = x.astype(jnp.float32) / scale
    if kv_q == "int8":
        q = jnp.clip(jnp.round(xs), -qmax, qmax).astype(jnp.int8)
    else:
        q = xs.astype(jnp.float8_e4m3fn)
    return q, scale


def _fused_decode_kernel(
    *refs,
    nc: int, hkv_n: int, g: int, dh: int, bc: int, cache_len: int,
    window: int | None, rolling: bool, kv_q: str | None, cd,
    rope: bool, rope_base: float, n_prefetch: int,
):
    lens_ref = refs[0]
    i = n_prefetch  # tables (paged) are consumed by index maps only
    (h_ref, wq_ref, wk_ref, wv_ref, wo_ref, ln1s_ref, ln1b_ref,
     ln2s_ref, ln2b_ref, wup_ref, bup_ref, wdn_ref, bdn_ref,
     ck_ref, cv_ref) = refs[i:i + 15]
    i += 15
    if kv_q is not None:
        ks_ref, vs_ref = refs[i:i + 2]
        i += 2
        ho_ref, kq_ref, vq_ref, ksc_ref, vsc_ref = refs[i:i + 5]
        i += 5
    else:
        ho_ref, kq_ref, vq_ref = refs[i:i + 3]
        i += 3
    hn_scr, q_scr, m_scr, l_scr, acc_scr, attn_scr = refs[i:i + 6]

    s_i = pl.program_id(0)
    j = pl.program_id(1)
    t_att = hkv_n * nc
    jc = jnp.minimum(j, t_att - 1)
    hkv = jc // nc
    ic = jc % nc
    length = lens_ref[s_i]
    scale = 1.0 / math.sqrt(dh)

    @pl.when(j == 0)
    def _ln1():
        hn_scr[:] = _ln_row(h_ref[:], ln1s_ref, ln1b_ref)

    @pl.when((j < t_att) & (ic == 0))
    def _head_start():
        # This KV head's projections: hn @ per-head weight columns, in
        # the compute dtype with f32 accumulation (GPTLM._dot). The g
        # query rows are produced one static slice at a time — a
        # [1, g·Dh] → [g, Dh] reshape would cross the lane/sublane
        # boundary, the relayout class CLAUDE.md warns about.
        hn = hn_scr[:].astype(cd)
        for gi in range(g):
            q_scr[gi:gi + 1, :] = jnp.dot(
                hn, wq_ref[:, gi * dh:(gi + 1) * dh],
                preferred_element_type=jnp.float32,
            )
        kf = jnp.dot(hn, wk_ref[:], preferred_element_type=jnp.float32)
        vf = jnp.dot(hn, wv_ref[:], preferred_element_type=jnp.float32)
        if rope:
            pos_f = length.astype(jnp.float32)
            q_scr[:] = _rope_rows(q_scr[:], pos_f, dh, rope_base)
            kf = _rope_rows(kf, pos_f, dh, rope_base)
        # Quantize-on-write, then attend the ROUND-TRIPPED values — the
        # round-15 uniform rule: position `length` must score exactly as
        # a later decode re-reading it from the cache will.
        if kv_q is None:
            kq_row = kf.astype(kq_ref.dtype)
            vq_row = vf.astype(vq_ref.dtype)
            kf_att = kq_row.astype(jnp.float32)
            vf_att = vq_row.astype(jnp.float32)
        else:
            kq_row, k_sc = _quant_row(kf, kv_q)
            vq_row, v_sc = _quant_row(vf, kv_q)
            kf_att = (kq_row.astype(jnp.float32) * k_sc).astype(cd).astype(
                jnp.float32
            )
            vf_att = (vq_row.astype(jnp.float32) * v_sc).astype(cd).astype(
                jnp.float32
            )
            ksc_ref[0, 0] = k_sc[0, 0]
            vsc_ref[0, 0] = v_sc[0, 0]
        kq_ref[:] = kq_row
        vq_ref[:] = vq_row
        # Online-softmax INIT from the fresh row: exactly one unmasked
        # entry, so m = its score, l = exp(0) = 1, acc = its value.
        sf = jnp.sum(q_scr[:] * kf_att, axis=-1, keepdims=True) * scale
        m_scr[:] = sf
        l_scr[:] = jnp.ones_like(l_scr)
        acc_scr[:] = jnp.broadcast_to(vf_att, acc_scr.shape)

    def _attend():
        kblk = ck_ref[0, :, 0, :]  # [bc, Dh]
        vblk = cv_ref[0, :, 0, :]
        if kv_q is None:
            kb = kblk.astype(jnp.float32)
            vb = vblk.astype(jnp.float32)
        else:
            # Per-block scales arrive as [bc, Hkv] (all heads — a 2-D
            # tile); this head's column is selected by an iota mask, the
            # lane-dynamic-index-free idiom.
            hsel = (
                lax.broadcasted_iota(jnp.int32, (1, hkv_n), 1) == hkv
            ).astype(jnp.float32)
            ksc = jnp.sum(ks_ref[0] * hsel, axis=-1, keepdims=True)
            vsc = jnp.sum(vs_ref[0] * hsel, axis=-1, keepdims=True)
            # Dequantize to the COMPUTE dtype (round-15 rule); the f32
            # upcast after is the transient dot operand, matching the
            # XLA engine's f32-promoted score einsum.
            kb = (kblk.astype(jnp.float32) * ksc).astype(cd).astype(
                jnp.float32
            )
            vb = (vblk.astype(jnp.float32) * vsc).astype(cd).astype(
                jnp.float32
            )
        sblk = jnp.dot(
            q_scr[:], kb.T, preferred_element_type=jnp.float32
        ) * scale  # [g, bc]
        idx = ic * bc + lax.broadcasted_iota(jnp.int32, (g, bc), 1)
        if rolling:
            # Rolling slab (windowed models): slot i holds absolute
            # position length − ((slot − i) mod C) — the
            # models/gpt._decode_block identity — minus the write slot
            # itself (handled exactly at init; the cache block read here
            # predates the write).
            slot = length % cache_len
            slot_pos = length - jnp.mod(slot - idx, cache_len)
            valid = (slot_pos >= 0) & (idx != slot)
        else:
            valid = idx < length
            if window is not None:
                valid &= idx > length - window
        sblk = jnp.where(valid, sblk, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=-1, keepdims=True))
        # m is always finite (the fresh-row init), so exp underflows
        # masked entries to exact zeros; the where is belt-and-braces.
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(valid, jnp.exp(sblk - m_new), 0.0)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p, vb, preferred_element_type=jnp.float32
        )
        m_scr[:] = m_new

    # Skip cache blocks that cannot hold a valid position (absolute
    # layouts: written positions are 0..length-1, windowed also
    # > length-W). Rolling slabs interleave positions across blocks, so
    # every block is live there.
    if rolling:
        live = j < t_att
    else:
        live = (j < t_att) & (ic * bc < length)
        if window is not None:
            live &= (ic + 1) * bc - 1 > length - window
    pl.when(live)(_attend)

    @pl.when((j < t_att) & (ic == nc - 1))
    def _head_end():
        out_h = acc_scr[:] / l_scr[:]  # l >= exp(m_f - m) > 0 always
        pl.store(attn_scr, (pl.ds(hkv * g, g), slice(None)), out_h)

    @pl.when(j == t_att)
    def _final():
        attn = attn_scr[:].astype(cd)  # [Hq, Dh]
        d = wo_ref.shape[1]
        out = jnp.zeros((1, d), jnp.float32)
        # attn·wo as a static per-head sum of [1, Dh]·[Dh, d] dots — the
        # [Hq, Dh] → [1, Hq·Dh] flatten it avoids is a cross-tile
        # relayout.
        for h in range(hkv_n * g):
            out = out + jnp.dot(
                attn[h:h + 1, :], wo_ref[h * dh:(h + 1) * dh, :],
                preferred_element_type=jnp.float32,
            )
        h1 = h_ref[:].astype(jnp.float32) + out
        hn2 = _ln_row(h1, ln2s_ref, ln2b_ref)
        up = jnp.dot(
            hn2.astype(cd), wup_ref[:], preferred_element_type=jnp.float32
        ) + bup_ref[:]
        dn = jnp.dot(
            jax.nn.gelu(up).astype(cd), wdn_ref[:],
            preferred_element_type=jnp.float32,
        ) + bdn_ref[:]
        ho_ref[:] = (h1 + dn).astype(ho_ref.dtype)


def _weight_inputs(w: dict, cd):
    """Order + cast the block weights for the kernel call: projections
    and FFN weights to the compute dtype (GPTLM._dot's operand cast),
    layernorm params and biases f32 as [1, n] rows."""
    row = lambda a: a.astype(jnp.float32).reshape(1, -1)  # noqa: E731
    return [
        w["wq"].astype(cd), w["wk"].astype(cd), w["wv"].astype(cd),
        w["wo"].astype(cd),
        row(w["ln1_scale"]), row(w["ln1_bias"]),
        row(w["ln2_scale"]), row(w["ln2_bias"]),
        w["w_up"].astype(cd), row(w["b_up"]),
        w["w_down"].astype(cd), row(w["b_down"]),
    ]


def _fused_call(
    h, w, ck, cv, k_scale, v_scale, lengths, tables,
    *, num_heads, window, rolling, kv_dtype, compute_dtype,
    rope, rope_base, block_c, cache_len, interpret,
):
    """Shared launch builder for both cache layouts. ``tables`` is None
    for the slab (cache indexed [S, C, ...] by slot) or [S, nc] int32
    for the paged pool (cache indexed [NB, bs, ...] through the
    scalar-prefetched tables)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s, d = h.shape
    hkv_n, dh = ck.shape[-2], ck.shape[-1]
    g = num_heads // hkv_n
    kv_q = None if kv_dtype == "bf16" else kv_dtype
    paged = tables is not None
    if paged:
        bc = ck.shape[1]  # pool block size
        nc = tables.shape[1]
    else:
        bc = _pick_cache_block(ck.shape[1], block_c)
        nc = ck.shape[1] // bc
    t_total = hkv_n * nc + 1
    t_att = hkv_n * nc

    def _hkv_ic(j):
        jc = jnp.minimum(j, t_att - 1)
        return jc // nc, jc % nc

    n_prefetch = 2 if paged else 1

    if paged:
        def cmap(s_i, j, lens, tab):
            hkv, ic = _hkv_ic(j)
            return (tab[s_i, ic], 0, hkv, 0)

        def smap(s_i, j, lens, tab):
            _, ic = _hkv_ic(j)
            return (tab[s_i, ic], 0, 0)
    else:
        def cmap(s_i, j, lens):
            hkv, ic = _hkv_ic(j)
            return (s_i, ic, hkv, 0)

        def smap(s_i, j, lens):
            _, ic = _hkv_ic(j)
            return (s_i, ic, 0)

    def hmap(s_i, j, *pref):
        return (s_i, 0)

    def headmap(s_i, j, *pref):
        return (0, _hkv_ic(j)[0])

    def const(s_i, j, *pref):
        return (0, 0)

    def freshmap(s_i, j, *pref):
        return (s_i * hkv_n + _hkv_ic(j)[0], 0)

    in_specs = [
        pl.BlockSpec((1, d), hmap),
        pl.BlockSpec((d, g * dh), headmap),   # wq columns of this head group
        pl.BlockSpec((d, dh), headmap),       # wk column
        pl.BlockSpec((d, dh), headmap),       # wv column
        pl.BlockSpec((d, d), const),          # wo
        pl.BlockSpec((1, d), const),          # ln1 scale
        pl.BlockSpec((1, d), const),          # ln1 bias
        pl.BlockSpec((1, d), const),          # ln2 scale
        pl.BlockSpec((1, d), const),          # ln2 bias
        pl.BlockSpec((d, w["w_up"].shape[-1]), const),
        pl.BlockSpec((1, w["w_up"].shape[-1]), const),
        pl.BlockSpec((w["w_down"].shape[-2], d), const),
        pl.BlockSpec((1, d), const),          # b_down
        pl.BlockSpec((1, bc, 1, dh), cmap),   # cache K block
        pl.BlockSpec((1, bc, 1, dh), cmap),   # cache V block
    ]
    inputs = [h.astype(jnp.float32)] + _weight_inputs(w, compute_dtype) + [
        ck, cv,
    ]
    if kv_q is not None:
        in_specs += [
            pl.BlockSpec((1, bc, hkv_n), smap),
            pl.BlockSpec((1, bc, hkv_n), smap),
        ]
        inputs += [k_scale, v_scale]

    out_specs = [
        pl.BlockSpec((1, d), hmap),
        pl.BlockSpec((1, dh), freshmap),
        pl.BlockSpec((1, dh), freshmap),
    ]
    storage = ck.dtype
    out_shape = [
        jax.ShapeDtypeStruct((s, d), jnp.float32),
        jax.ShapeDtypeStruct((s * hkv_n, dh), storage),
        jax.ShapeDtypeStruct((s * hkv_n, dh), storage),
    ]
    if kv_q is not None:
        out_specs += [
            pl.BlockSpec((1, 1), freshmap),
            pl.BlockSpec((1, 1), freshmap),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((s * hkv_n, 1), jnp.float32),
            jax.ShapeDtypeStruct((s * hkv_n, 1), jnp.float32),
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(s, t_total),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),        # hn (post-LN1 row)
            pltpu.VMEM((g, dh), jnp.float32),       # q of the current head
            pltpu.VMEM((g, 1), jnp.float32),        # m
            pltpu.VMEM((g, 1), jnp.float32),        # l
            pltpu.VMEM((g, dh), jnp.float32),       # acc
            pltpu.VMEM((num_heads, dh), jnp.float32),  # per-head attn out
        ],
    )
    kern = partial(
        _fused_decode_kernel,
        nc=nc, hkv_n=hkv_n, g=g, dh=dh, bc=bc, cache_len=cache_len,
        window=window, rolling=rolling, kv_q=kv_q, cd=compute_dtype,
        rope=rope, rope_base=rope_base, n_prefetch=n_prefetch,
    )
    prefetch = (lengths.astype(jnp.int32),)
    if paged:
        prefetch += (tables.astype(jnp.int32),)
    outs = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=tuple(out_shape),
        interpret=interpret,
    )(*prefetch, *inputs)
    if kv_q is not None:
        ho, kq, vq, ksc, vsc = outs
        return (
            ho,
            kq.reshape(s, hkv_n, dh),
            vq.reshape(s, hkv_n, dh),
            ksc.reshape(s, hkv_n),
            vsc.reshape(s, hkv_n),
        )
    ho, kq, vq = outs
    return ho, kq.reshape(s, hkv_n, dh), vq.reshape(s, hkv_n, dh), None, None


def decode_block_slab(
    h: jax.Array,
    weights: dict,
    ck: jax.Array,
    cv: jax.Array,
    k_scale: jax.Array | None,
    v_scale: jax.Array | None,
    lengths: jax.Array,
    *,
    num_heads: int,
    window: int | None = None,
    kv_dtype: str = "bf16",
    compute_dtype=jnp.bfloat16,
    rope: bool = False,
    rope_base: float = 10000.0,
    block_c: int | None = None,
    interpret: bool | None = None,
):
    """One GPT block's fused single-token step over a SLAB cache layer.

    ``h`` [S, d] f32 residual rows (one token per slot), ``weights`` the
    block's parameter dict (raw f32 leaves — cast happens inside),
    ``ck``/``cv`` [S, C, Hkv, Dh] (this layer's cache, PRE-write),
    ``k_scale``/``v_scale`` [S, C, Hkv] f32 or None (bf16), ``lengths``
    [S] int32 write positions. Windowed models pass their rolling-buffer
    cache (C = min(window, max_len)); the in-kernel validity reproduces
    the ``models/gpt._decode_block`` rolling identity.

    Returns ``(h_out [S, d] f32, k_fresh [S, Hkv, Dh] storage-dtype,
    v_fresh, k_fresh_scale [S, Hkv] f32 | None, v_fresh_scale)`` — the
    caller commits the fresh row with the SAME scatter index math as the
    XLA engine (``models/gpt.py``), which is what keeps the two engines
    attending identical caches."""
    return _fused_call(
        h, weights, ck, cv, k_scale, v_scale, lengths, None,
        num_heads=num_heads, window=window, rolling=window is not None,
        kv_dtype=kv_dtype, compute_dtype=compute_dtype, rope=rope,
        rope_base=rope_base, block_c=block_c, cache_len=ck.shape[1],
        interpret=interpret,
    )


def decode_block_paged(
    h: jax.Array,
    weights: dict,
    pool_k: jax.Array,
    pool_v: jax.Array,
    k_scale: jax.Array | None,
    v_scale: jax.Array | None,
    tables: jax.Array,
    lengths: jax.Array,
    *,
    num_heads: int,
    window: int | None = None,
    kv_dtype: str = "bf16",
    compute_dtype=jnp.bfloat16,
    rope: bool = False,
    rope_base: float = 10000.0,
    interpret: bool | None = None,
):
    """One GPT block's fused single-token step against the PAGED pool:
    ``pool_k``/``pool_v`` [NB, bs, Hkv, Dh] (this layer's pool),
    ``k_scale``/``v_scale`` [NB, bs, Hkv] f32 or None, ``tables``
    [S, max_blocks] int32. The block tables ride as scalar-prefetch
    arguments and the pool gather happens in the grid index maps — the
    kernel DMAs exactly the slot's blocks, no contiguous view is ever
    materialized (the XLA engine's ``gather_block_view`` copy). Validity
    is the absolute-position rule of ``models/gpt._decode_block_paged``
    (``idx < length``, windowed ``idx > length − W``); unused table
    entries gather garbage blocks the mask keeps out of the softmax.
    Return contract matches :func:`decode_block_slab` (the caller
    commits via ``ops/paged_attention.scatter_token_kv``)."""
    return _fused_call(
        h, weights, pool_k, pool_v, k_scale, v_scale, lengths, tables,
        num_heads=num_heads, window=window, rolling=False,
        kv_dtype=kv_dtype, compute_dtype=compute_dtype, rope=rope,
        rope_base=rope_base, block_c=None, cache_len=pool_k.shape[1],
        interpret=interpret,
    )


# -- round 20: the per-token megakernel tier -------------------------------


def _dma(src, dst, sem):
    """One synchronous manual copy (start + wait) — the in-kernel cache
    commit's write primitive. Serialized on one DMA semaphore: commits
    are a few rows per layer, latency-insignificant next to the cache
    read stream."""
    cp = pltpu.make_async_copy(src, dst, sem)
    cp.start()
    cp.wait()


def _mega_decode_kernel(
    *refs,
    n_layers: int, nc: int, hkv_n: int, g: int, dh: int, bc: int,
    cache_len: int, window: int | None, rolling: bool, paged: bool,
    bs: int, kv_q: str | None, cd, rope: bool, rope_base: float,
    n_prefetch: int,
):
    lens_ref, act_ref = refs[0], refs[1]
    tab_ref = refs[2] if paged else None
    i = n_prefetch
    (h_ref, wq_ref, wk_ref, wv_ref, wo_ref, ln1s_ref, ln1b_ref,
     ln2s_ref, ln2b_ref, wup_ref, bup_ref, wdn_ref, bdn_ref,
     ck_ref, cv_ref) = refs[i:i + 15]
    i += 15
    if kv_q is not None:
        ks_ref, vs_ref = refs[i:i + 2]
        i += 2
    # ANY-space alias sources: unused in the body (their whole purpose
    # is donating the cache buffers into the outputs).
    i += 2 if kv_q is None else 4
    if kv_q is not None:
        ho_any, cko, cvo, kso, vso = refs[i:i + 5]
        i += 5
    else:
        ho_any, cko, cvo = refs[i:i + 3]
        kso = vso = None
        i += 3
    (h_scr, hn_scr, q_scr, m_scr, l_scr, acc_scr, attn_scr,
     kf_scr, vf_scr) = refs[i:i + 9]
    i += 9
    if kv_q is not None:
        ksc_scr, vsc_scr = refs[i:i + 2]
        i += 2
    else:
        ksc_scr = vsc_scr = None
    out_scr, sem = refs[i], refs[i + 1]

    l_i = pl.program_id(0)
    s_i = pl.program_id(1)
    j = pl.program_id(2)
    t_att = hkv_n * nc
    jc = jnp.minimum(j, t_att - 1)
    hkv = jc // nc
    ic = jc % nc
    length = lens_ref[s_i]
    is_act = act_ref[s_i] != 0
    scale = 1.0 / math.sqrt(dh)

    @pl.when((l_i == 0) & (j == 0))
    def _seed_residual():
        pl.store(h_scr, (pl.ds(s_i, 1), slice(None)), h_ref[:])

    h_row = pl.load(h_scr, (pl.ds(s_i, 1), slice(None)))  # [1, d] f32

    @pl.when(j == 0)
    def _ln1():
        hn_scr[:] = _ln_row(h_row, ln1s_ref[0], ln1b_ref[0])

    @pl.when((j < t_att) & (ic == 0))
    def _head_start():
        # Identical math to _fused_decode_kernel's head start, with the
        # weight blocks carrying a leading streamed-layer axis and the
        # fresh quantized rows landing in scratch for the commit DMA.
        hn = hn_scr[:].astype(cd)
        wq = wq_ref[0]
        for gi in range(g):
            q_scr[gi:gi + 1, :] = jnp.dot(
                hn, wq[:, gi * dh:(gi + 1) * dh],
                preferred_element_type=jnp.float32,
            )
        kf = jnp.dot(hn, wk_ref[0], preferred_element_type=jnp.float32)
        vf = jnp.dot(hn, wv_ref[0], preferred_element_type=jnp.float32)
        if rope:
            pos_f = length.astype(jnp.float32)
            q_scr[:] = _rope_rows(q_scr[:], pos_f, dh, rope_base)
            kf = _rope_rows(kf, pos_f, dh, rope_base)
        if kv_q is None:
            kq_row = kf.astype(kf_scr.dtype)
            vq_row = vf.astype(vf_scr.dtype)
            kf_att = kq_row.astype(jnp.float32)
            vf_att = vq_row.astype(jnp.float32)
        else:
            kq_row, k_sc = _quant_row(kf, kv_q)
            vq_row, v_sc = _quant_row(vf, kv_q)
            kf_att = (kq_row.astype(jnp.float32) * k_sc).astype(cd).astype(
                jnp.float32
            )
            vf_att = (vq_row.astype(jnp.float32) * v_sc).astype(cd).astype(
                jnp.float32
            )
            # Head column selected by iota mask — scale scratch is a
            # [1, Hkv] row, no lane-dynamic store.
            col = lax.broadcasted_iota(jnp.int32, (1, hkv_n), 1) == hkv
            ksc_scr[:] = jnp.where(col, k_sc[0, 0], ksc_scr[:])
            vsc_scr[:] = jnp.where(col, v_sc[0, 0], vsc_scr[:])
        pl.store(kf_scr, (pl.ds(hkv, 1), slice(None)), kq_row)
        pl.store(vf_scr, (pl.ds(hkv, 1), slice(None)), vq_row)
        sf = jnp.sum(q_scr[:] * kf_att, axis=-1, keepdims=True) * scale
        m_scr[:] = sf
        l_scr[:] = jnp.ones_like(l_scr)
        acc_scr[:] = jnp.broadcast_to(vf_att, acc_scr.shape)

    def _attend():
        kblk = ck_ref[0, 0, :, 0, :]  # [bc, Dh]
        vblk = cv_ref[0, 0, :, 0, :]
        if kv_q is None:
            kb = kblk.astype(jnp.float32)
            vb = vblk.astype(jnp.float32)
        else:
            hsel = (
                lax.broadcasted_iota(jnp.int32, (1, hkv_n), 1) == hkv
            ).astype(jnp.float32)
            ksc = jnp.sum(ks_ref[0, 0] * hsel, axis=-1, keepdims=True)
            vsc = jnp.sum(vs_ref[0, 0] * hsel, axis=-1, keepdims=True)
            kb = (kblk.astype(jnp.float32) * ksc).astype(cd).astype(
                jnp.float32
            )
            vb = (vblk.astype(jnp.float32) * vsc).astype(cd).astype(
                jnp.float32
            )
        sblk = jnp.dot(
            q_scr[:], kb.T, preferred_element_type=jnp.float32
        ) * scale  # [g, bc]
        idx = ic * bc + lax.broadcasted_iota(jnp.int32, (g, bc), 1)
        if rolling:
            slot = length % cache_len
            slot_pos = length - jnp.mod(slot - idx, cache_len)
            valid = (slot_pos >= 0) & (idx != slot)
        else:
            valid = idx < length
            if window is not None:
                valid &= idx > length - window
        sblk = jnp.where(valid, sblk, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(valid, jnp.exp(sblk - m_new), 0.0)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p, vb, preferred_element_type=jnp.float32
        )
        m_scr[:] = m_new

    if rolling:
        live = j < t_att
    else:
        live = (j < t_att) & (ic * bc < length)
        if window is not None:
            live &= (ic + 1) * bc - 1 > length - window
    pl.when(live)(_attend)

    @pl.when((j < t_att) & (ic == nc - 1))
    def _head_end():
        out_h = acc_scr[:] / l_scr[:]  # l >= exp(m_f - m) > 0 always
        pl.store(attn_scr, (pl.ds(hkv * g, g), slice(None)), out_h)

    @pl.when(j == t_att)
    def _final():
        attn = attn_scr[:].astype(cd)  # [Hq, Dh]
        wo = wo_ref[0]
        d = wo.shape[1]
        out = jnp.zeros((1, d), jnp.float32)
        for h in range(hkv_n * g):
            out = out + jnp.dot(
                attn[h:h + 1, :], wo[h * dh:(h + 1) * dh, :],
                preferred_element_type=jnp.float32,
            )
        h1 = h_row + out
        hn2 = _ln_row(h1, ln2s_ref[0], ln2b_ref[0])
        up = jnp.dot(
            hn2.astype(cd), wup_ref[0], preferred_element_type=jnp.float32
        ) + bup_ref[0]
        dn = jnp.dot(
            jax.nn.gelu(up).astype(cd), wdn_ref[0],
            preferred_element_type=jnp.float32,
        ) + bdn_ref[0]
        h_new = h1 + dn
        pl.store(h_scr, (pl.ds(s_i, 1), slice(None)), h_new)

        # In-kernel commit: the XLA engines' exact scatter index math
        # (slot = length % C rolling / length absolute; paged through
        # the block table at position length), as manual DMAs into the
        # aliased cache outputs. Inactive rows SKIP — the scatter's
        # drop / write-old-back no-op, bit-for-bit.
        @pl.when(is_act)
        def _commit():
            if paged:
                blk_i = tab_ref[s_i, length // bs]
                off = length % bs
                _dma(kf_scr, cko.at[l_i, blk_i, off], sem)
                _dma(vf_scr, cvo.at[l_i, blk_i, off], sem)
                if kv_q is not None:
                    _dma(ksc_scr, kso.at[l_i, blk_i, pl.ds(off, 1)], sem)
                    _dma(vsc_scr, vso.at[l_i, blk_i, pl.ds(off, 1)], sem)
            else:
                slot = length % cache_len if rolling else length
                _dma(kf_scr, cko.at[l_i, s_i, slot], sem)
                _dma(vf_scr, cvo.at[l_i, s_i, slot], sem)
                if kv_q is not None:
                    _dma(ksc_scr, kso.at[l_i, s_i, pl.ds(slot, 1)], sem)
                    _dma(vsc_scr, vso.at[l_i, s_i, pl.ds(slot, 1)], sem)

        @pl.when(l_i == n_layers - 1)
        def _emit():
            out_scr[:] = h_new
            _dma(out_scr, ho_any.at[pl.ds(s_i, 1)], sem)


def _stacked_weight_inputs(w: dict, cd):
    """Layer-stacked counterpart of :func:`_weight_inputs`: every leaf
    keeps its leading [n_layers] axis (the streamed dimension);
    projections cast to the compute dtype, layernorm/bias rows f32 as
    [n_layers, 1, n]."""
    n = w["wq"].shape[0]
    row = lambda a: a.astype(jnp.float32).reshape(n, 1, -1)  # noqa: E731
    return [
        w["wq"].astype(cd), w["wk"].astype(cd), w["wv"].astype(cd),
        w["wo"].astype(cd),
        row(w["ln1_scale"]), row(w["ln1_bias"]),
        row(w["ln2_scale"]), row(w["ln2_bias"]),
        w["w_up"].astype(cd), row(w["b_up"]),
        w["w_down"].astype(cd), row(w["b_down"]),
    ]


def _stacked_weight_specs(w, d, g, dh, headmap, lconst):
    """BlockSpecs streaming ONE layer's weights per grid step: every
    map leads with the layer coordinate, so Mosaic double-buffers the
    next layer's blocks while the current one computes — the VMEM
    budget is per-layer, not per-model."""
    return [
        pl.BlockSpec((1, d, g * dh), headmap),  # wq columns of the head group
        pl.BlockSpec((1, d, dh), headmap),      # wk column
        pl.BlockSpec((1, d, dh), headmap),      # wv column
        pl.BlockSpec((1, d, d), lconst),        # wo
        pl.BlockSpec((1, 1, d), lconst),        # ln1 scale
        pl.BlockSpec((1, 1, d), lconst),        # ln1 bias
        pl.BlockSpec((1, 1, d), lconst),        # ln2 scale
        pl.BlockSpec((1, 1, d), lconst),        # ln2 bias
        pl.BlockSpec((1, d, w["w_up"].shape[-1]), lconst),
        pl.BlockSpec((1, 1, w["w_up"].shape[-1]), lconst),
        pl.BlockSpec((1, w["w_down"].shape[-2], d), lconst),
        pl.BlockSpec((1, 1, d), lconst),        # b_down
    ]


def _mega_call(
    h, w, ck, cv, k_scale, v_scale, lengths, active, tables,
    *, num_heads, window, rolling, kv_dtype, compute_dtype,
    rope, rope_base, block_c, cache_len, interpret,
):
    """Launch builder for the multi-layer megakernel: ONE launch per
    token over grid ``(n_layers, S, Hkv·nc + 1)``. ``ck``/``cv`` (and
    scales) are the FULL layer-stacked cache arrays; they enter the
    call twice — blocked read operands plus ANY-space operands aliased
    onto the outputs (``input_output_aliases``; alias indices count the
    scalar-prefetch operands) — and come back committed."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s, d = h.shape
    n_layers = ck.shape[0]
    hkv_n, dh = ck.shape[-2], ck.shape[-1]
    g = num_heads // hkv_n
    kv_q = None if kv_dtype == "bf16" else kv_dtype
    paged = tables is not None
    if paged:
        bc = ck.shape[2]  # pool block size
        nc = tables.shape[1]
    else:
        bc = _pick_cache_block(ck.shape[2], block_c)
        nc = ck.shape[2] // bc
    t_total = hkv_n * nc + 1
    t_att = hkv_n * nc

    def _hkv_ic(j):
        jc = jnp.minimum(j, t_att - 1)
        return jc // nc, jc % nc

    n_prefetch = 3 if paged else 2

    if paged:
        def cmap(l_i, s_i, j, lens, act, tab):
            hkv, ic = _hkv_ic(j)
            return (l_i, tab[s_i, ic], 0, hkv, 0)

        def smap(l_i, s_i, j, lens, act, tab):
            _, ic = _hkv_ic(j)
            return (l_i, tab[s_i, ic], 0, 0)
    else:
        def cmap(l_i, s_i, j, lens, act):
            hkv, ic = _hkv_ic(j)
            return (l_i, s_i, ic, hkv, 0)

        def smap(l_i, s_i, j, lens, act):
            _, ic = _hkv_ic(j)
            return (l_i, s_i, ic, 0)

    def hmap(l_i, s_i, j, *pref):
        return (s_i, 0)

    def headmap(l_i, s_i, j, *pref):
        return (l_i, 0, _hkv_ic(j)[0])

    def lconst(l_i, s_i, j, *pref):
        return (l_i, 0, 0)

    in_specs = [pl.BlockSpec((1, d), hmap)]
    in_specs += _stacked_weight_specs(w, d, g, dh, headmap, lconst)
    in_specs += [
        pl.BlockSpec((1, 1, bc, 1, dh), cmap),  # cache K block
        pl.BlockSpec((1, 1, bc, 1, dh), cmap),  # cache V block
    ]
    inputs = [h.astype(jnp.float32)]
    inputs += _stacked_weight_inputs(w, compute_dtype)
    inputs += [ck, cv]
    if kv_q is not None:
        in_specs += [
            pl.BlockSpec((1, 1, bc, hkv_n), smap),
            pl.BlockSpec((1, 1, bc, hkv_n), smap),
        ]
        inputs += [k_scale, v_scale]
    # The alias sources: same arrays again, whole-buffer ANY operands.
    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    n_alias = 2 if kv_q is None else 4
    alias_base = n_prefetch + len(inputs)
    in_specs += [any_spec] * n_alias
    inputs += [ck, cv] if kv_q is None else [ck, cv, k_scale, v_scale]

    out_specs = [any_spec] * (1 + n_alias)
    out_shape = [jax.ShapeDtypeStruct((s, d), jnp.float32)]
    out_shape += [
        jax.ShapeDtypeStruct(a.shape, a.dtype)
        for a in ([ck, cv] if kv_q is None else [ck, cv, k_scale, v_scale])
    ]
    aliases = {alias_base + i: 1 + i for i in range(n_alias)}

    storage = ck.dtype
    scratch = [
        pltpu.VMEM((s, d), jnp.float32),           # residual rows
        pltpu.VMEM((1, d), jnp.float32),           # hn (post-LN1 row)
        pltpu.VMEM((g, dh), jnp.float32),          # q of the current head
        pltpu.VMEM((g, 1), jnp.float32),           # m
        pltpu.VMEM((g, 1), jnp.float32),           # l
        pltpu.VMEM((g, dh), jnp.float32),          # acc
        pltpu.VMEM((num_heads, dh), jnp.float32),  # per-head attn out
        pltpu.VMEM((hkv_n, dh), storage),          # fresh K rows (commit src)
        pltpu.VMEM((hkv_n, dh), storage),          # fresh V rows
    ]
    if kv_q is not None:
        scratch += [
            pltpu.VMEM((1, hkv_n), jnp.float32),   # fresh K scales
            pltpu.VMEM((1, hkv_n), jnp.float32),   # fresh V scales
        ]
    scratch += [
        pltpu.VMEM((1, d), jnp.float32),           # h_out DMA staging
        pltpu.SemaphoreType.DMA,
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(n_layers, s, t_total),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    kern = partial(
        _mega_decode_kernel,
        n_layers=n_layers, nc=nc, hkv_n=hkv_n, g=g, dh=dh, bc=bc,
        cache_len=cache_len, window=window, rolling=rolling, paged=paged,
        bs=bc if paged else 0, kv_q=kv_q, cd=compute_dtype,
        rope=rope, rope_base=rope_base, n_prefetch=n_prefetch,
    )
    prefetch = (lengths.astype(jnp.int32), active.astype(jnp.int32))
    if paged:
        prefetch += (tables.astype(jnp.int32),)
    outs = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=tuple(out_shape),
        input_output_aliases=aliases,
        interpret=interpret,
    )(*prefetch, *inputs)
    if kv_q is not None:
        ho, nk, nv, nks, nvs = outs
        return ho, nk, nv, nks, nvs
    ho, nk, nv = outs
    return ho, nk, nv, None, None


def decode_token_slab(
    h: jax.Array,
    weights: dict,
    ck: jax.Array,
    cv: jax.Array,
    k_scale: jax.Array | None,
    v_scale: jax.Array | None,
    lengths: jax.Array,
    active: jax.Array,
    *,
    num_heads: int,
    window: int | None = None,
    kv_dtype: str = "bf16",
    compute_dtype=jnp.bfloat16,
    rope: bool = False,
    rope_base: float = 10000.0,
    block_c: int | None = None,
    interpret: bool | None = None,
):
    """The WHOLE model's fused single-token step over a SLAB cache —
    one launch per token (``decode_engine="pallas"``).

    ``h`` [S, d] f32 embedded token rows, ``weights`` the layer-STACKED
    parameter dict (every leaf leading [n_layers] — the streamed axis),
    ``ck``/``cv`` [n_layers, S, C, Hkv, Dh], scales
    [n_layers, S, C, Hkv] f32 or None, ``lengths`` [S] int32 write
    positions, ``active`` [S] bool (inactive rows compute but never
    commit — the scatter no-op, in-kernel). Returns
    ``(h_out [S, d] f32, ck', cv', k_scale', v_scale')`` with the fresh
    rows ALREADY committed at the XLA engine's exact indices."""
    return _mega_call(
        h, weights, ck, cv, k_scale, v_scale, lengths, active, None,
        num_heads=num_heads, window=window, rolling=window is not None,
        kv_dtype=kv_dtype, compute_dtype=compute_dtype, rope=rope,
        rope_base=rope_base, block_c=block_c, cache_len=ck.shape[2],
        interpret=interpret,
    )


def decode_token_paged(
    h: jax.Array,
    weights: dict,
    pool_k: jax.Array,
    pool_v: jax.Array,
    k_scale: jax.Array | None,
    v_scale: jax.Array | None,
    tables: jax.Array,
    lengths: jax.Array,
    active: jax.Array,
    *,
    num_heads: int,
    window: int | None = None,
    kv_dtype: str = "bf16",
    compute_dtype=jnp.bfloat16,
    rope: bool = False,
    rope_base: float = 10000.0,
    interpret: bool | None = None,
):
    """Paged counterpart of :func:`decode_token_slab`:
    ``pool_k``/``pool_v`` [n_layers, NB, bs, Hkv, Dh] (scales one axis
    fewer), ``tables`` [S, max_blocks] int32 riding as scalar prefetch.
    The commit lands at ``(table[s, len // bs], len % bs)`` — inactive
    rows skip, the ``scatter_token_kv`` sentinel-drop semantics (the
    sentinel itself never materializes: no DMA is issued at all).
    Active slots never share writable blocks (the serve_pool allocator
    invariant), so in-kernel writes stay disjoint from every read."""
    return _mega_call(
        h, weights, pool_k, pool_v, k_scale, v_scale, lengths, active,
        tables,
        num_heads=num_heads, window=window, rolling=False,
        kv_dtype=kv_dtype, compute_dtype=compute_dtype, rope=rope,
        rope_base=rope_base, block_c=None, cache_len=pool_k.shape[2],
        interpret=interpret,
    )


def _verify_kernel(
    *refs,
    n_layers: int, nc: int, hkv_n: int, g: int, dh: int, L: int,
    window: int | None, bs: int, kv_q: str | None, cd, rope: bool,
    rope_base: float,
):
    plen_ref, slen_ref, act_ref, tab_ref = refs[:4]
    i = 4
    (h_ref, wq_ref, wk_ref, wv_ref, wo_ref, ln1s_ref, ln1b_ref,
     ln2s_ref, ln2b_ref, wup_ref, bup_ref, wdn_ref, bdn_ref,
     ck_ref, cv_ref) = refs[i:i + 15]
    i += 15
    if kv_q is not None:
        ks_ref, vs_ref = refs[i:i + 2]
        i += 2
    i += 2 if kv_q is None else 4  # ANY-space alias sources, unread
    if kv_q is not None:
        ho_any, cko, cvo, kso, vso = refs[i:i + 5]
        i += 5
    else:
        ho_any, cko, cvo = refs[i:i + 3]
        kso = vso = None
        i += 3
    (h_scr, hn_scr, q_scr, m_scr, l_scr, acc_scr, attn_scr,
     kf_scr, vf_scr) = refs[i:i + 9]
    i += 9
    if kv_q is not None:
        ksc_scr, vsc_scr = refs[i:i + 2]
        i += 2
    else:
        ksc_scr = vsc_scr = None
    out_scr, sem = refs[i], refs[i + 1]

    l_i = pl.program_id(0)
    s_i = pl.program_id(1)
    j = pl.program_id(2)
    t_att = hkv_n * nc
    jc = jnp.minimum(j, t_att - 1)
    hkv = jc // nc
    ic = jc % nc
    plen = plen_ref[s_i]
    slen = slen_ref[s_i]
    is_act = act_ref[s_i] != 0
    scale = 1.0 / math.sqrt(dh)
    # Row r of the [g·L, …] q tiles is suffix position r % L of head
    # hkv·g + r // L.
    li_col = lax.broadcasted_iota(jnp.int32, (g * L, 1), 0) % L

    @pl.when((l_i == 0) & (j == 0))
    def _seed_residual():
        pl.store(h_scr, (pl.ds(s_i * L, L), slice(None)), h_ref[0])

    h_rows = pl.load(h_scr, (pl.ds(s_i * L, L), slice(None)))  # [L, d] f32

    @pl.when(j == 0)
    def _ln1():
        hn_scr[:] = _ln_row(h_rows, ln1s_ref[0], ln1b_ref[0])

    @pl.when((j < t_att) & (ic == 0))
    def _head_start():
        hn = hn_scr[:].astype(cd)
        wq = wq_ref[0]
        for gi in range(g):
            q_scr[gi * L:(gi + 1) * L, :] = jnp.dot(
                hn, wq[:, gi * dh:(gi + 1) * dh],
                preferred_element_type=jnp.float32,
            )
        kf = jnp.dot(hn, wk_ref[0], preferred_element_type=jnp.float32)
        vf = jnp.dot(hn, wv_ref[0], preferred_element_type=jnp.float32)
        if rope:
            plen_f = plen.astype(jnp.float32)
            q_scr[:] = _rope_rows(
                q_scr[:], plen_f + li_col.astype(jnp.float32), dh, rope_base
            )
            pos_k = plen_f + lax.broadcasted_iota(
                jnp.float32, (L, 1), 0
            )
            kf = _rope_rows(kf, pos_k, dh, rope_base)
        if kv_q is None:
            kq_rows = kf.astype(kf_scr.dtype)
            vq_rows = vf.astype(vf_scr.dtype)
            kf_att = kq_rows.astype(jnp.float32)
            vf_att = vq_rows.astype(jnp.float32)
        else:
            kq_rows, k_sc = _quant_row(kf, kv_q)  # [L, dh], [L, 1]
            vq_rows, v_sc = _quant_row(vf, kv_q)
            kf_att = (kq_rows.astype(jnp.float32) * k_sc).astype(cd).astype(
                jnp.float32
            )
            vf_att = (vq_rows.astype(jnp.float32) * v_sc).astype(cd).astype(
                jnp.float32
            )
            col = lax.broadcasted_iota(jnp.int32, (1, hkv_n), 1) == hkv
            ksc_scr[:] = jnp.where(col, k_sc, ksc_scr[:])
            vsc_scr[:] = jnp.where(col, v_sc, vsc_scr[:])
        pl.store(kf_scr, (pl.ds(hkv * L, L), slice(None)), kq_rows)
        pl.store(vf_scr, (pl.ds(hkv * L, L), slice(None)), vq_rows)
        # Softmax INIT from the fresh causal block: query row li attends
        # suffix keys lj ≤ li (within the real suffix; windowed models
        # also bound the band). Dead rows (no valid key) are guarded —
        # their m is _NEG_INF and their l stays 0.
        sf = jnp.dot(
            q_scr[:], kf_att.T, preferred_element_type=jnp.float32
        ) * scale  # [g·L, L]
        lj = lax.broadcasted_iota(jnp.int32, (g * L, L), 1)
        valid = (lj <= li_col) & (lj < slen)
        if window is not None:
            valid &= lj > li_col - window
        sf = jnp.where(valid, sf, _NEG_INF)
        m0 = jnp.max(sf, axis=-1, keepdims=True)
        m_safe = jnp.where(m0 > _NEG_INF * 0.5, m0, 0.0)
        p = jnp.where(valid, jnp.exp(sf - m_safe), 0.0)
        m_scr[:] = m0
        l_scr[:] = jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = jnp.dot(p, vf_att, preferred_element_type=jnp.float32)

    def _attend():
        kblk = ck_ref[0, 0, :, 0, :]  # [bs, Dh]
        vblk = cv_ref[0, 0, :, 0, :]
        if kv_q is None:
            kb = kblk.astype(jnp.float32)
            vb = vblk.astype(jnp.float32)
        else:
            hsel = (
                lax.broadcasted_iota(jnp.int32, (1, hkv_n), 1) == hkv
            ).astype(jnp.float32)
            ksc = jnp.sum(ks_ref[0, 0] * hsel, axis=-1, keepdims=True)
            vsc = jnp.sum(vs_ref[0, 0] * hsel, axis=-1, keepdims=True)
            kb = (kblk.astype(jnp.float32) * ksc).astype(cd).astype(
                jnp.float32
            )
            vb = (vblk.astype(jnp.float32) * vsc).astype(cd).astype(
                jnp.float32
            )
        sblk = jnp.dot(
            q_scr[:], kb.T, preferred_element_type=jnp.float32
        ) * scale  # [g·L, bs]
        idx = ic * bs + lax.broadcasted_iota(jnp.int32, (g * L, bs), 1)
        valid = idx < plen  # STRICT: the kernel reads the PRE-write pool
        if window is not None:
            valid &= idx > plen + li_col - window
        sblk = jnp.where(valid, sblk, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(valid, jnp.exp(sblk - m_new), 0.0)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p, vb, preferred_element_type=jnp.float32
        )
        m_scr[:] = m_new

    live = (j < t_att) & (ic * bs < plen)
    if window is not None:
        # A row's band low edge is plen + li − W; the lowest (most
        # permissive) belongs to li = 0 — skip only blocks that sit
        # below EVERY row's band.
        live &= (ic + 1) * bs - 1 > plen - window
    pl.when(live)(_attend)

    @pl.when((j < t_att) & (ic == nc - 1))
    def _head_end():
        out_h = jnp.where(l_scr[:] > 0, acc_scr[:] / l_scr[:], 0.0)
        pl.store(attn_scr, (pl.ds(hkv * g * L, g * L), slice(None)), out_h)

    @pl.when(j == t_att)
    def _final():
        attn = attn_scr[:].astype(cd)  # [Hq·L, Dh]
        wo = wo_ref[0]
        d = wo.shape[1]
        out = jnp.zeros((L, d), jnp.float32)
        for h in range(hkv_n * g):
            out = out + jnp.dot(
                attn[h * L:(h + 1) * L, :], wo[h * dh:(h + 1) * dh, :],
                preferred_element_type=jnp.float32,
            )
        h1 = h_rows + out
        hn2 = _ln_row(h1, ln2s_ref[0], ln2b_ref[0])
        up = jnp.dot(
            hn2.astype(cd), wup_ref[0], preferred_element_type=jnp.float32
        ) + bup_ref[0]
        dn = jnp.dot(
            jax.nn.gelu(up).astype(cd), wdn_ref[0],
            preferred_element_type=jnp.float32,
        ) + bdn_ref[0]
        h_new = h1 + dn
        pl.store(h_scr, (pl.ds(s_i * L, L), slice(None)), h_new)

        # Per-position commit: extend_paged's scatter validity is
        # token_mask & admit = (li < slen) & active — invalid positions
        # issue NO DMA (the sentinel-drop no-op, bit-for-bit).
        for li in range(L):
            @pl.when(is_act & (li < slen))
            def _commit(li=li):
                pos = plen + li
                blk_i = tab_ref[s_i, pos // bs]
                off = pos % bs
                for hk in range(hkv_n):
                    _dma(
                        kf_scr.at[pl.ds(hk * L + li, 1)],
                        cko.at[l_i, blk_i, off, pl.ds(hk, 1)], sem,
                    )
                    _dma(
                        vf_scr.at[pl.ds(hk * L + li, 1)],
                        cvo.at[l_i, blk_i, off, pl.ds(hk, 1)], sem,
                    )
                if kv_q is not None:
                    _dma(
                        ksc_scr.at[pl.ds(li, 1)],
                        kso.at[l_i, blk_i, pl.ds(off, 1)], sem,
                    )
                    _dma(
                        vsc_scr.at[pl.ds(li, 1)],
                        vso.at[l_i, blk_i, pl.ds(off, 1)], sem,
                    )

        @pl.when(l_i == n_layers - 1)
        def _emit():
            out_scr[:] = h_new
            _dma(out_scr, ho_any.at[s_i], sem)


def verify_tokens_paged(
    h: jax.Array,
    weights: dict,
    pool_k: jax.Array,
    pool_v: jax.Array,
    k_scale: jax.Array | None,
    v_scale: jax.Array | None,
    tables: jax.Array,
    prefix_lens: jax.Array,
    suffix_lens: jax.Array,
    active: jax.Array,
    *,
    num_heads: int,
    window: int | None = None,
    kv_dtype: str = "bf16",
    compute_dtype=jnp.bfloat16,
    rope: bool = False,
    rope_base: float = 10000.0,
    interpret: bool | None = None,
):
    """Fused small-L speculation-verify over the paged pool — the whole
    model's ``extend_paged`` math in ONE launch (``decode_engine=
    "pallas"`` with ``spec_draft > 0``).

    ``h`` [S, L, d] f32 embedded draft rows (L ≤ spec_draft + 1),
    ``prefix_lens`` [S] committed lengths (positions for rows li are
    ``prefix + li``), ``suffix_lens`` [S] real suffix sizes (rows past
    them neither attend as keys nor commit), ``active`` [S] bool.
    Attention is causal WITHIN the suffix (folded into the softmax init)
    and STRICT ``idx < prefix_len`` over the pool; fresh K/V round-trips
    through the storage dtype before both attention and commit (the
    round-15 uniform rule — greedy-exact acceptance needs the verify
    pass to attend exactly what the decode pass will). Returns
    ``(h_out [S, L, d] f32, pool_k', pool_v', k_scale', v_scale')`` with
    valid rows committed at extend_paged's exact indices; lengths and
    tables stay caller-owned (the round-11 commit contract)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s, L, d = h.shape
    n_layers = pool_k.shape[0]
    hkv_n, dh = pool_k.shape[-2], pool_k.shape[-1]
    g = num_heads // hkv_n
    kv_q = None if kv_dtype == "bf16" else kv_dtype
    bs = pool_k.shape[2]
    nc = tables.shape[1]
    t_total = hkv_n * nc + 1
    t_att = hkv_n * nc

    def _hkv_ic(j):
        jc = jnp.minimum(j, t_att - 1)
        return jc // nc, jc % nc

    def cmap(l_i, s_i, j, plens, slens, act, tab):
        hkv, ic = _hkv_ic(j)
        return (l_i, tab[s_i, ic], 0, hkv, 0)

    def smap(l_i, s_i, j, plens, slens, act, tab):
        _, ic = _hkv_ic(j)
        return (l_i, tab[s_i, ic], 0, 0)

    def hmap(l_i, s_i, j, *pref):
        return (s_i, 0, 0)

    def headmap(l_i, s_i, j, *pref):
        return (l_i, 0, _hkv_ic(j)[0])

    def lconst(l_i, s_i, j, *pref):
        return (l_i, 0, 0)

    in_specs = [pl.BlockSpec((1, L, d), hmap)]
    in_specs += _stacked_weight_specs(weights, d, g, dh, headmap, lconst)
    in_specs += [
        pl.BlockSpec((1, 1, bs, 1, dh), cmap),
        pl.BlockSpec((1, 1, bs, 1, dh), cmap),
    ]
    inputs = [h.astype(jnp.float32)]
    inputs += _stacked_weight_inputs(weights, compute_dtype)
    inputs += [pool_k, pool_v]
    if kv_q is not None:
        in_specs += [
            pl.BlockSpec((1, 1, bs, hkv_n), smap),
            pl.BlockSpec((1, 1, bs, hkv_n), smap),
        ]
        inputs += [k_scale, v_scale]
    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    n_alias = 2 if kv_q is None else 4
    n_prefetch = 4
    alias_base = n_prefetch + len(inputs)
    in_specs += [any_spec] * n_alias
    inputs += (
        [pool_k, pool_v]
        if kv_q is None
        else [pool_k, pool_v, k_scale, v_scale]
    )

    out_specs = [any_spec] * (1 + n_alias)
    out_shape = [jax.ShapeDtypeStruct((s, L, d), jnp.float32)]
    out_shape += [
        jax.ShapeDtypeStruct(a.shape, a.dtype)
        for a in (
            [pool_k, pool_v]
            if kv_q is None
            else [pool_k, pool_v, k_scale, v_scale]
        )
    ]
    aliases = {alias_base + i: 1 + i for i in range(n_alias)}

    storage = pool_k.dtype
    scratch = [
        pltpu.VMEM((s * L, d), jnp.float32),
        pltpu.VMEM((L, d), jnp.float32),
        pltpu.VMEM((g * L, dh), jnp.float32),
        pltpu.VMEM((g * L, 1), jnp.float32),
        pltpu.VMEM((g * L, 1), jnp.float32),
        pltpu.VMEM((g * L, dh), jnp.float32),
        pltpu.VMEM((num_heads * L, dh), jnp.float32),
        pltpu.VMEM((hkv_n * L, dh), storage),
        pltpu.VMEM((hkv_n * L, dh), storage),
    ]
    if kv_q is not None:
        scratch += [
            pltpu.VMEM((L, hkv_n), jnp.float32),
            pltpu.VMEM((L, hkv_n), jnp.float32),
        ]
    scratch += [
        pltpu.VMEM((L, d), jnp.float32),
        pltpu.SemaphoreType.DMA,
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(n_layers, s, t_total),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    kern = partial(
        _verify_kernel,
        n_layers=n_layers, nc=nc, hkv_n=hkv_n, g=g, dh=dh, L=L,
        window=window, bs=bs, kv_q=kv_q, cd=compute_dtype,
        rope=rope, rope_base=rope_base,
    )
    outs = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=tuple(out_shape),
        input_output_aliases=aliases,
        interpret=interpret,
    )(
        prefix_lens.astype(jnp.int32),
        suffix_lens.astype(jnp.int32),
        active.astype(jnp.int32),
        tables.astype(jnp.int32),
        *inputs,
    )
    if kv_q is not None:
        return outs
    ho, nk, nv = outs
    return ho, nk, nv, None, None
