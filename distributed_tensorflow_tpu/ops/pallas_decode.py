"""Pallas TPU kernel: fused single-token decode step for one GPT block.

The reference has no generative path at all (its one "inference" is the
in-loop accuracy fetch, reference tfsingle.py:94); serving decode is this
framework's hottest un-kerneled path. At L=1 each transformer block of
``models/gpt.py`` lowers to ~20 small XLA ops (the ``decode_step``
docstring), so per-token time is dominated by per-op dispatch overhead
and KV-cache HBM traffic, not FLOPs — the round-5 unroll fix
(939→306 µs/token) showed decode gaps track cache-traffic ratios. This
module collapses one block's whole single-token step into ONE Pallas
launch per layer:

    layernorm₁ → QKV projection → RoPE → quantize-on-write of the fresh
    K/V row → online-softmax attention over the resident cache →
    output projection → residual → layernorm₂ → dense FFN → residual

with the block's weights and the token's activations VMEM-resident
across the launch, and the KV cache read block-by-block straight from
the slab rows or the paged pool (block tables ride as scalar-prefetch
arguments, so the pool gather is grid index-map arithmetic — no XLA
gather materializes a contiguous view). Quantized caches (round 15)
dequantize int8/fp8 payload blocks *inside* the kernel — the launch
reads 1-byte elements plus the per-row f32 scales and upcasts in VMEM,
which is where the 2× HBM-bytes claim becomes a latency claim. Per the
round-15 rule, dequantization targets the COMPUTE dtype, never f32
storage (the f32 view exists only as the transient dot operand).

Grid: ``(S, Hkv·nc + 1)`` — per serving slot, one step per
(KV head, cache block) pair plus one finalize step. TPU grids run
sequentially with the minor dimension fastest, so VMEM scratch carries
the layernormed token row, the current head's online-softmax state
(m/l/acc as [g, 1]/[g, Dh] 2-D tiles — 1-D vectors trip Mosaic relayout
bugs, CLAUDE.md), and the per-head attention outputs across the slot's
steps. Weight refs use constant index maps, so Mosaic fetches them once
per launch and re-uses the resident copy every step.

The fresh K/V row is folded into the attention ONLINE-SOFTMAX INIT
(m = s_fresh, l = 1, acc = v_fresh — exactly one unmasked entry) after
a round-trip through the cache's storage dtype, so the kernel attends
precisely the values the cache will hold — the round-15 uniform rule
("a quantized cache attends stored values EVERYWHERE") that keeps the
fused engine token-compatible with the XLA engine. The cache blocks
themselves are attended with the fresh position masked OUT
(``idx != slot`` / ``idx < length``): the kernel reads the PRE-write
cache, so the write's slot must come from registers, not memory.

The one-row cache COMMIT stays outside the launch (models/gpt.py applies
the same ``.at[rows, slot].set`` / ``scatter_token_kv`` index math as
the XLA engine): TPU output blocks may only be revisited on consecutive
grid steps, so an in-kernel scatter would either copy the whole cache
through an aliased output (doubling the HBM traffic this kernel exists
to remove) or need a manual-DMA HBM path; the row is S·Hkv·Dh elements
— negligible next to the cache read — and XLA fuses the scatter with
the launch's epilogue. Same division of labor as the fused flash
backward's dq-partial sum (ops/pallas_attention.py).

``interpret=None`` auto-selects the Pallas interpreter off-TPU and the
Mosaic compiler on TPU (the ops/pallas_attention.py convention); parity
vs the XLA engine is pinned in tests/test_pallas_decode.py (interpreter)
and recorded on-chip by ``tools/attention_parity.py --write-docs``
(``decode-fused-vs-xla:*`` rows).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_EPS = 1e-12
# qmax per quantized KV dtype — MUST match ops/quantized._QMAX (the
# kernel re-derives the same symmetric per-row scales the XLA engine
# commits, so both engines attend identical stored values).
_QMAX = {"int8": 127.0, "fp8": 448.0}
_STORAGE = {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}


def _pick_cache_block(c: int, requested: int | None) -> int:
    """Largest power-of-two divisor of the cache length ≤ 512 (one score
    tile is [g, bc] — tiny; the cap bounds the resident KV block at
    bc·Dh elements), or ``c`` itself for short/odd caches (Mosaic pads
    non-tile-multiple shapes; serving caches are small enough that a
    single whole-cache block is fine)."""
    if requested is not None:
        if c % requested:
            raise ValueError(f"block {requested} must divide cache {c}")
        return requested
    for cand in (512, 256, 128, 64, 32, 16, 8):
        if c % cand == 0 and cand <= c:
            return cand
    return c


def _ln_row(x, scale_ref, bias_ref):
    """f32 layernorm on a [1, d] row — the models/base.layernorm
    arithmetic verbatim (eps included), so the fused block cannot drift
    numerically from the XLA block."""
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + 1e-5)) * scale_ref[:] + bias_ref[:]


def _rope_rows(x, pos_f, dh: int, base: float):
    """Rotary embedding on [rows, Dh] at one shared position (all rows
    of a decode step sit at the slot's own position) — the
    models/gpt._rope pair rotation in f32."""
    half = dh // 2
    io = lax.broadcasted_iota(jnp.float32, (1, half), 1)
    # base ** (-i/half) in the models/gpt._rope evaluation order (the
    # exp(-ln·i/half) refactoring differs in the last ulp, which the
    # parity tests would otherwise have to budget for).
    freqs = jnp.power(base, -io / half)
    ang = pos_f * freqs
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[:, :half], x[:, half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def _quant_row(x, kv_q: str):
    """Symmetric per-row quantization of [rows, Dh] — the
    ops/quantized.quantize_kv recipe (amax over the lane dim, eps floor,
    int8 round-and-clip / fp8 cast) re-derived in-kernel so the fused
    engine commits bit-identical rows to the XLA engine."""
    qmax = _QMAX[kv_q]
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, _EPS) / qmax
    xs = x.astype(jnp.float32) / scale
    if kv_q == "int8":
        q = jnp.clip(jnp.round(xs), -qmax, qmax).astype(jnp.int8)
    else:
        q = xs.astype(jnp.float8_e4m3fn)
    return q, scale


def _fused_decode_kernel(
    *refs,
    nc: int, hkv_n: int, g: int, dh: int, bc: int, cache_len: int,
    window: int | None, rolling: bool, kv_q: str | None, cd,
    rope: bool, rope_base: float, n_prefetch: int,
):
    lens_ref = refs[0]
    i = n_prefetch  # tables (paged) are consumed by index maps only
    (h_ref, wq_ref, wk_ref, wv_ref, wo_ref, ln1s_ref, ln1b_ref,
     ln2s_ref, ln2b_ref, wup_ref, bup_ref, wdn_ref, bdn_ref,
     ck_ref, cv_ref) = refs[i:i + 15]
    i += 15
    if kv_q is not None:
        ks_ref, vs_ref = refs[i:i + 2]
        i += 2
        ho_ref, kq_ref, vq_ref, ksc_ref, vsc_ref = refs[i:i + 5]
        i += 5
    else:
        ho_ref, kq_ref, vq_ref = refs[i:i + 3]
        i += 3
    hn_scr, q_scr, m_scr, l_scr, acc_scr, attn_scr = refs[i:i + 6]

    s_i = pl.program_id(0)
    j = pl.program_id(1)
    t_att = hkv_n * nc
    jc = jnp.minimum(j, t_att - 1)
    hkv = jc // nc
    ic = jc % nc
    length = lens_ref[s_i]
    scale = 1.0 / math.sqrt(dh)

    @pl.when(j == 0)
    def _ln1():
        hn_scr[:] = _ln_row(h_ref[:], ln1s_ref, ln1b_ref)

    @pl.when((j < t_att) & (ic == 0))
    def _head_start():
        # This KV head's projections: hn @ per-head weight columns, in
        # the compute dtype with f32 accumulation (GPTLM._dot). The g
        # query rows are produced one static slice at a time — a
        # [1, g·Dh] → [g, Dh] reshape would cross the lane/sublane
        # boundary, the relayout class CLAUDE.md warns about.
        hn = hn_scr[:].astype(cd)
        for gi in range(g):
            q_scr[gi:gi + 1, :] = jnp.dot(
                hn, wq_ref[:, gi * dh:(gi + 1) * dh],
                preferred_element_type=jnp.float32,
            )
        kf = jnp.dot(hn, wk_ref[:], preferred_element_type=jnp.float32)
        vf = jnp.dot(hn, wv_ref[:], preferred_element_type=jnp.float32)
        if rope:
            pos_f = length.astype(jnp.float32)
            q_scr[:] = _rope_rows(q_scr[:], pos_f, dh, rope_base)
            kf = _rope_rows(kf, pos_f, dh, rope_base)
        # Quantize-on-write, then attend the ROUND-TRIPPED values — the
        # round-15 uniform rule: position `length` must score exactly as
        # a later decode re-reading it from the cache will.
        if kv_q is None:
            kq_row = kf.astype(kq_ref.dtype)
            vq_row = vf.astype(vq_ref.dtype)
            kf_att = kq_row.astype(jnp.float32)
            vf_att = vq_row.astype(jnp.float32)
        else:
            kq_row, k_sc = _quant_row(kf, kv_q)
            vq_row, v_sc = _quant_row(vf, kv_q)
            kf_att = (kq_row.astype(jnp.float32) * k_sc).astype(cd).astype(
                jnp.float32
            )
            vf_att = (vq_row.astype(jnp.float32) * v_sc).astype(cd).astype(
                jnp.float32
            )
            ksc_ref[0, 0] = k_sc[0, 0]
            vsc_ref[0, 0] = v_sc[0, 0]
        kq_ref[:] = kq_row
        vq_ref[:] = vq_row
        # Online-softmax INIT from the fresh row: exactly one unmasked
        # entry, so m = its score, l = exp(0) = 1, acc = its value.
        sf = jnp.sum(q_scr[:] * kf_att, axis=-1, keepdims=True) * scale
        m_scr[:] = sf
        l_scr[:] = jnp.ones_like(l_scr)
        acc_scr[:] = jnp.broadcast_to(vf_att, acc_scr.shape)

    def _attend():
        kblk = ck_ref[0, :, 0, :]  # [bc, Dh]
        vblk = cv_ref[0, :, 0, :]
        if kv_q is None:
            kb = kblk.astype(jnp.float32)
            vb = vblk.astype(jnp.float32)
        else:
            # Per-block scales arrive as [bc, Hkv] (all heads — a 2-D
            # tile); this head's column is selected by an iota mask, the
            # lane-dynamic-index-free idiom.
            hsel = (
                lax.broadcasted_iota(jnp.int32, (1, hkv_n), 1) == hkv
            ).astype(jnp.float32)
            ksc = jnp.sum(ks_ref[0] * hsel, axis=-1, keepdims=True)
            vsc = jnp.sum(vs_ref[0] * hsel, axis=-1, keepdims=True)
            # Dequantize to the COMPUTE dtype (round-15 rule); the f32
            # upcast after is the transient dot operand, matching the
            # XLA engine's f32-promoted score einsum.
            kb = (kblk.astype(jnp.float32) * ksc).astype(cd).astype(
                jnp.float32
            )
            vb = (vblk.astype(jnp.float32) * vsc).astype(cd).astype(
                jnp.float32
            )
        sblk = jnp.dot(
            q_scr[:], kb.T, preferred_element_type=jnp.float32
        ) * scale  # [g, bc]
        idx = ic * bc + lax.broadcasted_iota(jnp.int32, (g, bc), 1)
        if rolling:
            # Rolling slab (windowed models): slot i holds absolute
            # position length − ((slot − i) mod C) — the
            # models/gpt._decode_block identity — minus the write slot
            # itself (handled exactly at init; the cache block read here
            # predates the write).
            slot = length % cache_len
            slot_pos = length - jnp.mod(slot - idx, cache_len)
            valid = (slot_pos >= 0) & (idx != slot)
        else:
            valid = idx < length
            if window is not None:
                valid &= idx > length - window
        sblk = jnp.where(valid, sblk, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=-1, keepdims=True))
        # m is always finite (the fresh-row init), so exp underflows
        # masked entries to exact zeros; the where is belt-and-braces.
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(valid, jnp.exp(sblk - m_new), 0.0)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p, vb, preferred_element_type=jnp.float32
        )
        m_scr[:] = m_new

    # Skip cache blocks that cannot hold a valid position (absolute
    # layouts: written positions are 0..length-1, windowed also
    # > length-W). Rolling slabs interleave positions across blocks, so
    # every block is live there.
    if rolling:
        live = j < t_att
    else:
        live = (j < t_att) & (ic * bc < length)
        if window is not None:
            live &= (ic + 1) * bc - 1 > length - window
    pl.when(live)(_attend)

    @pl.when((j < t_att) & (ic == nc - 1))
    def _head_end():
        out_h = acc_scr[:] / l_scr[:]  # l >= exp(m_f - m) > 0 always
        pl.store(attn_scr, (pl.ds(hkv * g, g), slice(None)), out_h)

    @pl.when(j == t_att)
    def _final():
        attn = attn_scr[:].astype(cd)  # [Hq, Dh]
        d = wo_ref.shape[1]
        out = jnp.zeros((1, d), jnp.float32)
        # attn·wo as a static per-head sum of [1, Dh]·[Dh, d] dots — the
        # [Hq, Dh] → [1, Hq·Dh] flatten it avoids is a cross-tile
        # relayout.
        for h in range(hkv_n * g):
            out = out + jnp.dot(
                attn[h:h + 1, :], wo_ref[h * dh:(h + 1) * dh, :],
                preferred_element_type=jnp.float32,
            )
        h1 = h_ref[:].astype(jnp.float32) + out
        hn2 = _ln_row(h1, ln2s_ref, ln2b_ref)
        up = jnp.dot(
            hn2.astype(cd), wup_ref[:], preferred_element_type=jnp.float32
        ) + bup_ref[:]
        dn = jnp.dot(
            jax.nn.gelu(up).astype(cd), wdn_ref[:],
            preferred_element_type=jnp.float32,
        ) + bdn_ref[:]
        ho_ref[:] = (h1 + dn).astype(ho_ref.dtype)


def _weight_inputs(w: dict, cd):
    """Order + cast the block weights for the kernel call: projections
    and FFN weights to the compute dtype (GPTLM._dot's operand cast),
    layernorm params and biases f32 as [1, n] rows."""
    row = lambda a: a.astype(jnp.float32).reshape(1, -1)  # noqa: E731
    return [
        w["wq"].astype(cd), w["wk"].astype(cd), w["wv"].astype(cd),
        w["wo"].astype(cd),
        row(w["ln1_scale"]), row(w["ln1_bias"]),
        row(w["ln2_scale"]), row(w["ln2_bias"]),
        w["w_up"].astype(cd), row(w["b_up"]),
        w["w_down"].astype(cd), row(w["b_down"]),
    ]


def _fused_call(
    h, w, ck, cv, k_scale, v_scale, lengths, tables,
    *, num_heads, window, rolling, kv_dtype, compute_dtype,
    rope, rope_base, block_c, cache_len, interpret,
):
    """Shared launch builder for both cache layouts. ``tables`` is None
    for the slab (cache indexed [S, C, ...] by slot) or [S, nc] int32
    for the paged pool (cache indexed [NB, bs, ...] through the
    scalar-prefetched tables)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s, d = h.shape
    hkv_n, dh = ck.shape[-2], ck.shape[-1]
    g = num_heads // hkv_n
    kv_q = None if kv_dtype == "bf16" else kv_dtype
    paged = tables is not None
    if paged:
        bc = ck.shape[1]  # pool block size
        nc = tables.shape[1]
    else:
        bc = _pick_cache_block(ck.shape[1], block_c)
        nc = ck.shape[1] // bc
    t_total = hkv_n * nc + 1
    t_att = hkv_n * nc

    def _hkv_ic(j):
        jc = jnp.minimum(j, t_att - 1)
        return jc // nc, jc % nc

    n_prefetch = 2 if paged else 1

    if paged:
        def cmap(s_i, j, lens, tab):
            hkv, ic = _hkv_ic(j)
            return (tab[s_i, ic], 0, hkv, 0)

        def smap(s_i, j, lens, tab):
            _, ic = _hkv_ic(j)
            return (tab[s_i, ic], 0, 0)
    else:
        def cmap(s_i, j, lens):
            hkv, ic = _hkv_ic(j)
            return (s_i, ic, hkv, 0)

        def smap(s_i, j, lens):
            _, ic = _hkv_ic(j)
            return (s_i, ic, 0)

    def hmap(s_i, j, *pref):
        return (s_i, 0)

    def headmap(s_i, j, *pref):
        return (0, _hkv_ic(j)[0])

    def const(s_i, j, *pref):
        return (0, 0)

    def freshmap(s_i, j, *pref):
        return (s_i * hkv_n + _hkv_ic(j)[0], 0)

    in_specs = [
        pl.BlockSpec((1, d), hmap),
        pl.BlockSpec((d, g * dh), headmap),   # wq columns of this head group
        pl.BlockSpec((d, dh), headmap),       # wk column
        pl.BlockSpec((d, dh), headmap),       # wv column
        pl.BlockSpec((d, d), const),          # wo
        pl.BlockSpec((1, d), const),          # ln1 scale
        pl.BlockSpec((1, d), const),          # ln1 bias
        pl.BlockSpec((1, d), const),          # ln2 scale
        pl.BlockSpec((1, d), const),          # ln2 bias
        pl.BlockSpec((d, w["w_up"].shape[-1]), const),
        pl.BlockSpec((1, w["w_up"].shape[-1]), const),
        pl.BlockSpec((w["w_down"].shape[-2], d), const),
        pl.BlockSpec((1, d), const),          # b_down
        pl.BlockSpec((1, bc, 1, dh), cmap),   # cache K block
        pl.BlockSpec((1, bc, 1, dh), cmap),   # cache V block
    ]
    inputs = [h.astype(jnp.float32)] + _weight_inputs(w, compute_dtype) + [
        ck, cv,
    ]
    if kv_q is not None:
        in_specs += [
            pl.BlockSpec((1, bc, hkv_n), smap),
            pl.BlockSpec((1, bc, hkv_n), smap),
        ]
        inputs += [k_scale, v_scale]

    out_specs = [
        pl.BlockSpec((1, d), hmap),
        pl.BlockSpec((1, dh), freshmap),
        pl.BlockSpec((1, dh), freshmap),
    ]
    storage = ck.dtype
    out_shape = [
        jax.ShapeDtypeStruct((s, d), jnp.float32),
        jax.ShapeDtypeStruct((s * hkv_n, dh), storage),
        jax.ShapeDtypeStruct((s * hkv_n, dh), storage),
    ]
    if kv_q is not None:
        out_specs += [
            pl.BlockSpec((1, 1), freshmap),
            pl.BlockSpec((1, 1), freshmap),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((s * hkv_n, 1), jnp.float32),
            jax.ShapeDtypeStruct((s * hkv_n, 1), jnp.float32),
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(s, t_total),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),        # hn (post-LN1 row)
            pltpu.VMEM((g, dh), jnp.float32),       # q of the current head
            pltpu.VMEM((g, 1), jnp.float32),        # m
            pltpu.VMEM((g, 1), jnp.float32),        # l
            pltpu.VMEM((g, dh), jnp.float32),       # acc
            pltpu.VMEM((num_heads, dh), jnp.float32),  # per-head attn out
        ],
    )
    kern = partial(
        _fused_decode_kernel,
        nc=nc, hkv_n=hkv_n, g=g, dh=dh, bc=bc, cache_len=cache_len,
        window=window, rolling=rolling, kv_q=kv_q, cd=compute_dtype,
        rope=rope, rope_base=rope_base, n_prefetch=n_prefetch,
    )
    prefetch = (lengths.astype(jnp.int32),)
    if paged:
        prefetch += (tables.astype(jnp.int32),)
    outs = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=tuple(out_shape),
        interpret=interpret,
    )(*prefetch, *inputs)
    if kv_q is not None:
        ho, kq, vq, ksc, vsc = outs
        return (
            ho,
            kq.reshape(s, hkv_n, dh),
            vq.reshape(s, hkv_n, dh),
            ksc.reshape(s, hkv_n),
            vsc.reshape(s, hkv_n),
        )
    ho, kq, vq = outs
    return ho, kq.reshape(s, hkv_n, dh), vq.reshape(s, hkv_n, dh), None, None


def decode_block_slab(
    h: jax.Array,
    weights: dict,
    ck: jax.Array,
    cv: jax.Array,
    k_scale: jax.Array | None,
    v_scale: jax.Array | None,
    lengths: jax.Array,
    *,
    num_heads: int,
    window: int | None = None,
    kv_dtype: str = "bf16",
    compute_dtype=jnp.bfloat16,
    rope: bool = False,
    rope_base: float = 10000.0,
    block_c: int | None = None,
    interpret: bool | None = None,
):
    """One GPT block's fused single-token step over a SLAB cache layer.

    ``h`` [S, d] f32 residual rows (one token per slot), ``weights`` the
    block's parameter dict (raw f32 leaves — cast happens inside),
    ``ck``/``cv`` [S, C, Hkv, Dh] (this layer's cache, PRE-write),
    ``k_scale``/``v_scale`` [S, C, Hkv] f32 or None (bf16), ``lengths``
    [S] int32 write positions. Windowed models pass their rolling-buffer
    cache (C = min(window, max_len)); the in-kernel validity reproduces
    the ``models/gpt._decode_block`` rolling identity.

    Returns ``(h_out [S, d] f32, k_fresh [S, Hkv, Dh] storage-dtype,
    v_fresh, k_fresh_scale [S, Hkv] f32 | None, v_fresh_scale)`` — the
    caller commits the fresh row with the SAME scatter index math as the
    XLA engine (``models/gpt.py``), which is what keeps the two engines
    attending identical caches."""
    return _fused_call(
        h, weights, ck, cv, k_scale, v_scale, lengths, None,
        num_heads=num_heads, window=window, rolling=window is not None,
        kv_dtype=kv_dtype, compute_dtype=compute_dtype, rope=rope,
        rope_base=rope_base, block_c=block_c, cache_len=ck.shape[1],
        interpret=interpret,
    )


def decode_block_paged(
    h: jax.Array,
    weights: dict,
    pool_k: jax.Array,
    pool_v: jax.Array,
    k_scale: jax.Array | None,
    v_scale: jax.Array | None,
    tables: jax.Array,
    lengths: jax.Array,
    *,
    num_heads: int,
    window: int | None = None,
    kv_dtype: str = "bf16",
    compute_dtype=jnp.bfloat16,
    rope: bool = False,
    rope_base: float = 10000.0,
    interpret: bool | None = None,
):
    """One GPT block's fused single-token step against the PAGED pool:
    ``pool_k``/``pool_v`` [NB, bs, Hkv, Dh] (this layer's pool),
    ``k_scale``/``v_scale`` [NB, bs, Hkv] f32 or None, ``tables``
    [S, max_blocks] int32. The block tables ride as scalar-prefetch
    arguments and the pool gather happens in the grid index maps — the
    kernel DMAs exactly the slot's blocks, no contiguous view is ever
    materialized (the XLA engine's ``gather_block_view`` copy). Validity
    is the absolute-position rule of ``models/gpt._decode_block_paged``
    (``idx < length``, windowed ``idx > length − W``); unused table
    entries gather garbage blocks the mask keeps out of the softmax.
    Return contract matches :func:`decode_block_slab` (the caller
    commits via ``ops/paged_attention.scatter_token_kv``)."""
    return _fused_call(
        h, weights, pool_k, pool_v, k_scale, v_scale, lengths, tables,
        num_heads=num_heads, window=window, rolling=False,
        kv_dtype=kv_dtype, compute_dtype=compute_dtype, rope=rope,
        rope_base=rope_base, block_c=None, cache_len=pool_k.shape[1],
        interpret=interpret,
    )
