"""Pallas TPU kernel: blockwise fused (flash) attention, forward + backward.

The reference has no attention at all (SURVEY.md §2b: the model is a fixed
784-feature MLP) — long-context support is one of this framework's
first-class upgrades. ``ops/ring_attention.py`` supplies the cross-device
algorithms (ring / Ulysses); this module supplies the *within-device* hot
op: exact softmax attention computed block-by-block in VMEM so the [L, L]
score matrix is never materialized in HBM.

Forward (online softmax, one grid step per (batch·head, q-block, k-block)):

    s    = q·kᵀ·scale                     (bq, bk) f32 on the MXU
    m'   = max(m, rowmax(s)); corr = exp(m - m')
    p    = exp(s - m')
    l    = l·corr + rowsum(p)
    acc  = acc·corr + p·v
    out  = acc / l;  lse = m + log(l)     (written at the last k-block)

Backward re-derives p from the saved row-wise log-sum-exp instead of
storing it:

    p  = exp(s - lse)                      (exact, no second softmax pass)
    dv += pᵀ·do
    ds = p·(do·vᵀ - delta)·scale           delta = rowsum(do·out)
    dk += dsᵀ·q
    dq += ds·k

The default backward (round 13) is ONE fused k-major kernel: a single
pass over KV blocks computes s/p/dp/ds once and produces all three
gradients — dk/dv accumulate in VMEM scratch exactly as before, while
each grid step writes its dq *partial* to its own block of a
[nk, B·H, L, D] output that one XLA sum reduces afterwards (TPU grids
may only revisit output blocks in consecutive iterations, so cross-k
in-kernel dq accumulation is illegal; the partial-sum layout is the
same one jax's splash-attention fused backward uses). ``fused=False``
restores the classic two-kernel split (q-major dq kernel + k-major dkv
kernel), which computes the score-space work twice — kept as the escape
hatch and the parity oracle for the fused path.

Accumulators live in VMEM scratch that persists across the innermost grid
dimension (TPU grids run sequentially, minor-most fastest); causal masking
skips fully-masked blocks entirely via ``pl.when`` — past-diagonal work is
never issued, so causal runs ~2× faster than masked-dense. All per-row
statistics (m, l, lse, delta) are carried as [rows, 1] 2-D columns — 1-D
vectors trip Mosaic relayout bugs (CLAUDE.md).

Layout: public API takes [B, L, H, D] (matching ``dense_attention`` /
``ring_attention``); kernels run on [B·H, L, D] with f32 math regardless of
input dtype. ``interpret=None`` auto-selects the Pallas interpreter
off-TPU, the Mosaic compiler on TPU (same convention as ops/pallas_mlp.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# Measured dense/flash crossover (tools/attention_bench.py, two-point
# timing — docs/benchmarks/attention_tpu.md): below L≈1024 XLA's fused
# dense attention beats the Pallas kernel even at its best block size
# (L=512 fwd+bwd: dense 0.013 ms vs flash 0.097; L=1024 is the first
# length where flash's fwd+bwd wins, 1.34x), above it the gap widens
# (4.7x at 2048). The ONE shared default for every model's
# ``flash_min_len`` knob — re-measure with the tool before changing it.
FLASH_MIN_LEN = 1024


def _pick_block(l: int, requested: int | None) -> int:
    """Largest MXU-friendly block that divides ``l`` (512 below L=4096,
    1024 from there up), or ``l`` itself for short/odd sequences (Mosaic pads
    non-tile-multiple shapes). A long sequence with no small divisor would
    silently degenerate to one whole-sequence block — an O(L²) VMEM score
    tile, exactly what this kernel exists to avoid — so that case is an
    error, not a fallback.

    The caps are MEASURED, not guessed (tools/attention_bench.py with the
    round-4 two-point discipline — the round-3 cap of 128 cost flash its
    wins exactly where users run it, VERDICT round-3 weak #3): fwd+bwd
    per call at L=2048 is 3.53 ms at block 128 vs 0.87 ms at block 512
    (vs dense 3.38 ms) — the 128-block grid is 16x more grid steps, each
    too small to keep the MXU busy while Mosaic's pipeline turns over.
    Block 1024 loses slightly at L=2048 (0.96 vs 0.89 ms) but wins from
    L=4096 up (2.89 vs 3.62 ms; L=8192 11.0 vs 14.6, and windowed
    likewise — W=1024: 4.97 vs 6.36), hence the length-dependent cap;
    2048 fails to compile (VMEM). A 1024² f32 score tile is 4 MB —
    fine."""
    if requested is not None:
        if l % requested:
            raise ValueError(f"block {requested} must divide sequence {l}")
        return requested
    cands = (1024, 512, 256, 128, 64, 32, 16, 8)
    if l < 4096:
        cands = cands[1:]
    for cand in cands:
        if l % cand == 0:
            return cand
    if l > 512:
        raise ValueError(
            f"sequence length {l} has no power-of-two block divisor (tried"
            f" down from {cands[0]}); pad the sequence or pass an explicit"
            f" block_q/block_k that divides it"
        )
    return l


def _causal_mask(iq, ik, bq, bk, window=None, offset=0):
    """[bq, bk] bool: global q position >= global k position (and, with
    ``window=W``, within the last W keys). ``offset`` shifts every q
    position forward — the ring composition's past hops, where the held KV
    block originated ``offset`` positions behind the local queries. 2-D
    broadcasted_iota — plain ``jnp.arange`` is 1-D and TPU rejects it."""
    q_pos = iq * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + offset
    k_pos = ik * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    diff = q_pos - k_pos
    mask = diff >= 0
    if window is not None:
        mask &= diff < window
    return mask


def _block_needed(iq, ik, bq, bk, window, offset=0):
    """Whether any (q, k) pair in this block pair survives the causal(+
    window) mask: max diff >= 0 (not fully above the diagonal) and, with a
    window, min diff < W (not fully fallen out of it)."""
    needed = (iq + 1) * bq - 1 + offset >= ik * bk
    if window is not None:
        needed &= iq * bq + offset - (ik + 1) * bk + 1 < window
    return needed


def _kvlen_valid(ik, bq, bk, kvlen_ref, by_row: bool):
    """[bq, bk] bool key-padding validity for one score block: keys at
    global position >= this grid row's kv_len are invalid — one definition
    shared by the forward and both backward kernels.

    Two static layouts (``by_row``): on Mosaic the whole [rows, 1] int32
    array sits in SMEM (full-array blocks are the only sub-(8,128) shapes
    the TPU lowering accepts) and the row is selected by grid position; the
    CPU interpreter instead gets a per-row (1, 1) block (it cannot lower
    ``program_id`` through the whole-array path)."""
    kl = kvlen_ref[pl.program_id(0), 0] if by_row else kvlen_ref[0, 0]
    k_pos = ik * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return k_pos < kl


def _use_banding(window, l) -> bool:
    """Banded (clamped) index maps defeat Mosaic's affine prefetch analysis,
    which costs more than the saved DMA until the band is much smaller than
    the row: measured on v5e (block 512, W=1024), banding LOSES below
    L≈4·W (18.8 vs 11.4 ms at L=2048) and wins above (13.2 vs 15.6 ms at
    L=8192, 17.1 vs 29.9 at 16384 — docs/performance.md). Below the
    crossover the plain affine walk with in-kernel masking is used; the
    math is identical either way."""
    return window is not None and 4 * window <= l


def _kv_row(hq: int, hkv: int):
    """Grid-row mapping for grouped-query attention: q grid row
    ``b = batch·Hq + hq_head`` reads the KV row of its head *group*
    (``Hq/Hkv`` query heads share one KV head). Identity when Hq == Hkv."""
    g = hq // hkv
    if g == 1:
        return lambda b: b
    return lambda b: (b // hq) * hkv + (b % hq) // g


def _banded_k_index(window, bq, bk, row=lambda b: b):
    """Index-map factory clamping the k-block index into the causal window
    band of its q block (and routing through the GQA ``row`` mapping).
    Out-of-band grid steps re-reference an in-band (already-resident)
    block, so they cost no DMA — their compute is skipped by
    ``_block_needed`` anyway. Purely an index-map change: the kernels
    never see the clamped index (they recompute the true one from
    ``pl.program_id``)."""

    def index_map(b, iq, ik):
        lo = jnp.maximum((iq * bq - window + 1) // bk, 0)
        hi = ((iq + 1) * bq - 1) // bk
        return (row(b), jnp.clip(ik, lo, hi), 0)

    return index_map


def _banded_q_index(window, bq, bk, nq):
    """Transposed band for the k-major (dkv) kernel: clamp the q-block
    index into [first q attending this k, last q within the window]."""

    def index_map(b, ik, iq):
        lo = (ik * bk) // bq
        hi = jnp.minimum(((ik + 1) * bk - 2 + window) // bq, nq - 1)
        return (b, jnp.clip(iq, lo, hi), 0)

    return index_map


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, *rest,
    scale: float, causal: bool, window: int | None, nk: int, has_lens: bool,
    offset: int = 0, lens_by_row: bool = True,
):
    if has_lens:
        kvlen_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
        kvlen_ref = None
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _accumulate():
        # Matmuls run in the input dtype with f32 accumulation — one MXU
        # pass for bf16 inputs, matching XLA's DEFAULT precision. Softmax
        # statistics stay f32 regardless.
        q = q_ref[0]
        k = k_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_causal_mask(iq, ik, bq, bk, window, offset), s, _NEG_INF)
        if has_lens:
            s = jnp.where(_kvlen_valid(ik, bq, bk, kvlen_ref, lens_by_row), s, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # A still-empty row (everything masked so far) has m_new == -inf;
        # exp(s - -inf) would be exp(+inf). Causal rows always include the
        # diagonal eventually, but guard the not-yet-reached iterations.
        m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        corr = jnp.exp(m_prev - m_safe)
        p = jnp.exp(s - m_safe)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jnp.dot(
            p.astype(v_ref.dtype), v_ref[0], preferred_element_type=jnp.float32
        )
        m_scr[:] = m_new

    if causal:
        # Skip blocks whose every score is masked: strictly above the
        # diagonal, or (windowed) entirely fallen out of the window.
        pl.when(_block_needed(iq, ik, bq, bk, window, offset))(_accumulate)
    else:
        _accumulate()

    @pl.when(ik == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _fwd_call(
    q, k, v, kv_lens, *, causal, window, offset, bq, bk, scale, interpret,
    vma, hq, hkv
):
    """q [B·Hq, L, D], k/v [B·Hkv, L, D] → (out [B·Hq, L, D], lse
    [B·Hq, L, 1]). ``kv_lens`` is None or [B] int32 (right-padded
    key-padding; expanded per query head here). ``vma`` marks the outputs
    as varying over those mesh axes — required under a ``check_vma=True``
    shard_map (the ring composition)."""
    sds = partial(jax.ShapeDtypeStruct, vma=vma) if vma else jax.ShapeDtypeStruct
    bh, l, d = q.shape
    nq, nk = l // bq, l // bk
    row = _kv_row(hq, hkv)
    kmap = (
        _banded_k_index(window, bq, bk, row)
        if offset == 0 and _use_banding(window, l)
        else (lambda b, iq, ik: (row(b), ik, 0))
    )
    has_lens = kv_lens is not None
    lens_spec = _lens_blockspec(interpret)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, iq, ik: (b, iq, 0)),
        pl.BlockSpec((1, bk, d), kmap),
        pl.BlockSpec((1, bk, d), kmap),
    ]
    inputs = [q, k, v]
    if has_lens:
        in_specs.append(lens_spec)
        inputs.append(jnp.repeat(kv_lens.astype(jnp.int32), hq)[:, None])
    return pl.pallas_call(
        partial(
            _fwd_kernel,
            scale=scale, causal=causal, window=window, nk=nk,
            has_lens=has_lens, offset=offset, lens_by_row=not interpret,
        ),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, bq, d), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, iq, ik: (b, iq, 0)),
        ),
        out_shape=(
            sds((bh, l, d), q.dtype),
            sds((bh, l, 1), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    scale: float, causal: bool, window: int | None, nk: int, has_lens: bool,
    offset: int = 0, lens_by_row: bool = True,
):
    if has_lens:
        kvlen_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
        kvlen_ref = None
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _accumulate():
        q = q_ref[0]
        k = k_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        # p must be masked EXPLICITLY here, not via -1e30 underflow: a
        # fully-masked row (offset past the window, or window+padding)
        # saved lse ~= -1e30 too, so exp(s - lse) would be exp(0) = 1 and
        # the row would inject garbage into every gradient.
        mask = None
        if causal:
            mask = _causal_mask(iq, ik, bq, bk, window, offset)
        if has_lens:
            lm = _kvlen_valid(ik, bq, bk, kvlen_ref, lens_by_row)
            mask = lm if mask is None else mask & lm
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = jnp.dot(do_ref[0], v_ref[0].T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dq_scr[:] += jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(_block_needed(iq, ik, bq, bk, window, offset))(_accumulate)
    else:
        _accumulate()

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    scale: float, causal: bool, window: int | None, nq: int, total: int,
    has_lens: bool, offset: int = 0, lens_by_row: bool = True,
):
    if has_lens:
        kvlen_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
        kvlen_ref = None
    ik = pl.program_id(1)
    j = pl.program_id(2)
    iq = j % nq  # positional q block; j // nq is the GQA head in the group
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _accumulate():
        q = q_ref[0]
        k = k_ref[0]
        do = do_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        # Explicit p masking — see _dq_kernel (fully-masked rows saved
        # lse ~= -1e30; underflow alone would give p = 1 there).
        mask = None
        if causal:
            mask = _causal_mask(iq, ik, bq, bk, window, offset)
        if has_lens:
            lm = _kvlen_valid(ik, bq, bk, kvlen_ref, lens_by_row)
            mask = lm if mask is None else mask & lm
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dv_scr[:] += jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v_ref[0].T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dk_scr[:] += jnp.dot(
            ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(_block_needed(iq, ik, bq, bk, window, offset))(_accumulate)
    else:
        _accumulate()

    @pl.when(j == total - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _fused_bwd_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    scale: float, causal: bool, window: int | None, nq: int, total: int,
    has_lens: bool, offset: int = 0, lens_by_row: bool = True,
):
    """One pass over the KV stream producing ALL THREE gradients: the
    k-major ``_dkv_kernel`` grid, with the score-space work (s, p, dp,
    ds) computed ONCE per block pair — the two-kernel split computes it
    twice. dk/dv accumulate in VMEM scratch exactly as in
    ``_dkv_kernel``; dq cannot accumulate the same way (its q-block is
    revisited at every non-consecutive k step, which TPU output
    semantics forbid), so each grid step writes its dq *partial* to its
    own block of a [nk, B·H, L, D] f32 output and ``_bwd_call`` sums
    the leading axis in XLA — the splash-attention fused-backward
    layout. Skipped (fully-masked) block pairs still own a block, which
    is zeroed up front so the sum sees no garbage."""
    if has_lens:
        kvlen_ref, dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
        kvlen_ref = None
    ik = pl.program_id(1)
    j = pl.program_id(2)
    iq = j % nq  # positional q block; j // nq is the GQA head in the group
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # Every grid step owns exactly one dq-partial block: zero it first so
    # block pairs the causal/window predicate skips contribute zero.
    dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])

    def _accumulate():
        q = q_ref[0]
        k = k_ref[0]
        do = do_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        # Explicit p masking — see _dq_kernel (fully-masked rows saved
        # lse ~= -1e30; underflow alone would give p = 1 there).
        mask = None
        if causal:
            mask = _causal_mask(iq, ik, bq, bk, window, offset)
        if has_lens:
            lm = _kvlen_valid(ik, bq, bk, kvlen_ref, lens_by_row)
            mask = lm if mask is None else mask & lm
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dv_scr[:] += jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v_ref[0].T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dk_scr[:] += jnp.dot(
            ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32
        )
        dqp_ref[0, 0] = jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(_block_needed(iq, ik, bq, bk, window, offset))(_accumulate)
    else:
        _accumulate()

    @pl.when(j == total - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _lens_blockspec(interpret):
    """Key-padding lengths spec shared by every kernel launch (forward,
    dq, dkv, fused — see ``_kvlen_valid`` for the two layouts): the
    whole [rows, 1] array in SMEM on Mosaic, a per-row (1, 1) block on
    the CPU interpreter."""
    return (
        pl.BlockSpec((1, 1), lambda b, i, j: (b, 0))
        if interpret
        else pl.BlockSpec(memory_space=pltpu.SMEM)  # whole array
    )


def _qrow_specs(bq, d, qmap):
    """[1, bq, d] q/do blocks and the matching [1, bq, 1] lse/delta
    row-statistic blocks walking one shared index map — the ONE builder
    for every backward launch (q-major dq, k-major dkv, fused), so the
    row-spec layout cannot drift between consumers."""
    return pl.BlockSpec((1, bq, d), qmap), pl.BlockSpec((1, bq, 1), qmap)


def _bwd_call(
    q, k, v, o, lse, do, delta, kv_lens,
    *, causal, window, offset, bq, bk, scale, interpret, vma, hq, hkv,
    fused,
):
    sds = partial(jax.ShapeDtypeStruct, vma=vma) if vma else jax.ShapeDtypeStruct
    bh, l, d = q.shape
    bhkv = k.shape[0]
    g = hq // hkv
    nq, nk = l // bq, l // bk
    row = _kv_row(hq, hkv)
    has_lens = kv_lens is not None
    lens_spec = _lens_blockspec(interpret)
    banded = offset == 0 and _use_banding(window, l)

    # k-major layout (dkv and fused launches): q/do/lse/delta blocks walk
    # the innermost dim, which under GQA spans all g query heads sharing
    # this KV head (j = head·nq + jq) — dk/dv accumulate over the whole
    # group in one scratch pass.
    def qrow(b, j):
        return (b // hkv) * hq + (b % hkv) * g + j // nq

    if banded:
        _band = _banded_q_index(window, bq, bk, nq)

        def qmap2(b, i, j):
            _, jq, _ = _band(b, i, j % nq)
            return (qrow(b, j), jq, 0)

    else:

        def qmap2(b, i, j):
            return (qrow(b, j), j % nq, 0)

    qspec2, rowspec2 = _qrow_specs(bq, d, qmap2)
    kspec2 = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0))
    kv_inputs = [q, k, v, do, lse, delta]
    kv_specs = [qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2]
    if has_lens:
        # k-major grid: b indexes B·Hkv rows.
        kv_inputs.append(jnp.repeat(kv_lens.astype(jnp.int32), hkv)[:, None])
        kv_specs.append(lens_spec)

    if fused:
        # dq partials: each grid step's own block (index map UNclamped —
        # banding only redirects the resident input blocks), reduced in
        # XLA. f32 partials + f32 sum match the two-kernel path's f32
        # scratch accumulation.
        dqp_spec = pl.BlockSpec(
            (1, 1, bq, d), lambda b, i, j: (i, qrow(b, j), j % nq, 0)
        )
        dqp, dk, dv = pl.pallas_call(
            partial(
                _fused_bwd_kernel,
                scale=scale, causal=causal, window=window, nq=nq,
                total=nq * g, has_lens=has_lens, offset=offset,
                lens_by_row=not interpret,
            ),
            grid=(bhkv, nk, nq * g),
            in_specs=kv_specs,
            out_specs=(dqp_spec, kspec2, kspec2),
            out_shape=(
                sds((nk, bh, l, d), jnp.float32),
                sds((bhkv, l, d), k.dtype),
                sds((bhkv, l, d), v.dtype),
            ),
            scratch_shapes=[
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
            interpret=interpret,
        )(*kv_inputs)
        return jnp.sum(dqp, axis=0).astype(q.dtype), dk, dv

    # Two-kernel escape hatch (fused=False): q-major dq kernel + k-major
    # dkv kernel, each re-deriving p — the parity oracle for the fused
    # path and the fallback if a Mosaic regression ever hits it.
    kmap = (
        _banded_k_index(window, bq, bk, row)
        if banded
        else (lambda b, i, j: (row(b), j, 0))
    )
    qspec, rowspec = _qrow_specs(bq, d, lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, bk, d), kmap)
    dq_inputs = [q, k, v, do, lse, delta]
    dq_specs = [qspec, kspec, kspec, qspec, rowspec, rowspec]
    if has_lens:
        dq_inputs.append(jnp.repeat(kv_lens.astype(jnp.int32), hq)[:, None])
        dq_specs.append(lens_spec)
    dq = pl.pallas_call(
        partial(
            _dq_kernel,
            scale=scale, causal=causal, window=window, nk=nk,
            has_lens=has_lens, offset=offset, lens_by_row=not interpret,
        ),
        grid=(bh, nq, nk),
        in_specs=dq_specs,
        out_specs=qspec,
        out_shape=sds((bh, l, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(*dq_inputs)

    dk, dv = pl.pallas_call(
        partial(
            _dkv_kernel,
            scale=scale, causal=causal, window=window, nq=nq, total=nq * g,
            has_lens=has_lens, offset=offset, lens_by_row=not interpret,
        ),
        grid=(bhkv, nk, nq * g),
        in_specs=kv_specs,
        out_specs=(kspec2, kspec2),
        out_shape=(
            sds((bhkv, l, d), k.dtype),
            sds((bhkv, l, d), v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(*kv_inputs)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper and public API
# ---------------------------------------------------------------------------


def _to_bh(x):
    """[B, L, H, D] → [B·H, L, D]."""
    b, l, h, d = x.shape
    return jnp.einsum("blhd->bhld", x).reshape(b * h, l, d)


def _from_bh(x, b, h):
    bh, l, d = x.shape
    return jnp.einsum("bhld->blhd", x.reshape(b, h, l, d))


@partial(jax.custom_vjp, nondiff_argnums=tuple(range(10)))
def _flash(
    causal, window, offset, bq, bk, interpret, vma, hq, hkv, fused,
    q, k, v, kv_lens,
):
    """Primal returns (out, lse) — both differentiable. The lse output is
    what makes blockwise *composition* (ring attention) differentiable: a
    cotangent on lse folds into the backward's delta term, since
    ∂lse_i/∂s_ij = p_ij means ds = p·(dp − (delta − g_lse))·scale.
    ``kv_lens`` (None or [B] int32) is an integer side input — its
    "gradient" is None. ``fused`` picks the backward implementation
    (one-pass fused kernel vs the two-kernel split); the primal ignores
    it."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    return _fwd_call(
        q, k, v, kv_lens,
        causal=causal, window=window, offset=offset, bq=bq, bk=bk,
        scale=scale, interpret=interpret, vma=vma, hq=hq, hkv=hkv,
    )


def _flash_fwd(
    causal, window, offset, bq, bk, interpret, vma, hq, hkv, fused,
    q, k, v, kv_lens,
):
    o, lse = _flash(
        causal, window, offset, bq, bk, interpret, vma, hq, hkv, fused,
        q, k, v, kv_lens,
    )
    return (o, lse), (q, k, v, o, lse, kv_lens)


def _flash_bwd_impl(
    causal, window, offset, bq, bk, interpret, vma, hq, hkv, fused, res, g
):
    """(dq, dk, dv) from the saved residuals — shared by ``_flash``'s vjp
    and the selective-remat rebuild (``_flash_rebuild``), whose residual
    tuples are identical by construction."""
    q, k, v, o, lse, kv_lens = res
    do, dlse = g
    scale = 1.0 / (q.shape[-1] ** 0.5)
    # delta_i = rowsum(do ⊙ out) − g_lse: tiny elementwise reduce, XLA fuses
    # it into the surrounding graph — not worth a kernel. g_lse is symbolic
    # zero (materialized as zeros) when the caller discards lse.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    ) - dlse.astype(jnp.float32)
    return _bwd_call(
        q, k, v, o, lse, do, delta, kv_lens,
        causal=causal, window=window, offset=offset, bq=bq, bk=bk,
        scale=scale, interpret=interpret, vma=vma, hq=hq, hkv=hkv,
        fused=fused,
    )


def _flash_bwd(
    causal, window, offset, bq, bk, interpret, vma, hq, hkv, fused, res, g
):
    dq, dk, dv = _flash_bwd_impl(
        causal, window, offset, bq, bk, interpret, vma, hq, hkv, fused,
        res, g,
    )
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


# The checkpoint_name labels under which the selective-remat policy saves
# the attention forward (models/gpt.py remat="selective" builds
# jax.checkpoint_policies.save_only_these_names(*REMAT_SAVE_NAMES)).
REMAT_SAVE_NAMES = ("flash_out", "flash_lse")

# Auto-fusion cap: the fused backward's dq-partial buffer is
# nk · (B·H·L·D) f32 in HBM — (L/bk) full gradient copies. The default
# (fused=None) picks the fused kernel only while that buffer stays under
# this cap, so extreme-length configs (L=16k attention-bench rows and
# beyond) silently keep the two-kernel split instead of OOMing a 16 GB
# v5e that is already carrying the xl activation stash. 1 GiB keeps the
# primary target (gpt-xl-L2048: ~536 MB of partials) fused. PROVISIONAL
# until the chip rerun measures where the fused win stops paying for the
# extra HBM traffic — an explicit fused=True/False always wins.
_FUSED_DQ_CAP_BYTES = 1 << 30


def _resolve_fused(
    fused: bool | None, bh: int, l: int, d: int, bk: int,
    window: int | None = None,
) -> bool:
    """fused=None → auto: fuse unless (a) the [nk, B·H, L, D] f32
    dq-partial output would exceed ``_FUSED_DQ_CAP_BYTES``, or (b) the
    call is in the BANDED-window regime (``_use_banding``) — there the
    fused kernel would write and re-read mostly structurally-zero
    partial planes (only in-band k-blocks contribute to a q-block's dq,
    but every plane exists), multiplying dq HBM traffic by ~nk against
    the split path's single VMEM-accumulated dq. Both rules are
    PROVISIONAL pending the chip rerun; an explicit bool always wins."""
    if fused is not None:
        return fused
    if _use_banding(window, l):
        return False
    return (l // bk) * bh * l * d * 4 <= _FUSED_DQ_CAP_BYTES


@partial(jax.custom_vjp, nondiff_argnums=tuple(range(10)))
def _flash_rebuild(
    causal, window, offset, bq, bk, interpret, vma, hq, hkv, fused,
    q, k, v, kv_lens, o, lse,
):
    """Identity on (o, lse) whose VJP is the real flash backward — the
    selective-remat composition hook (``save_names=`` in the public
    API). Its residuals are its own INPUTS, so under
    ``jax.checkpoint(policy=save_only_these_names(...))`` the saved
    (named) o/lse substitute directly and DCE drops the flash *forward*
    from the backward recompute. Naming the outputs of ``_flash`` alone
    cannot achieve that: a custom-vjp's residuals are the pre-name
    values, so the kernel still reruns (measured — recompute FLOPs
    unchanged). The gradient path is exclusively through this function
    (the primal ``_flash`` call is gradient-stopped), so nothing double
    counts; o/lse arrive via stop_gradient and get zero cotangents."""
    return o, lse


def _flash_rebuild_fwd(
    causal, window, offset, bq, bk, interpret, vma, hq, hkv, fused,
    q, k, v, kv_lens, o, lse,
):
    return (o, lse), (q, k, v, o, lse, kv_lens)


def _flash_rebuild_bwd(
    causal, window, offset, bq, bk, interpret, vma, hq, hkv, fused, res, g
):
    dq, dk, dv = _flash_bwd_impl(
        causal, window, offset, bq, bk, interpret, vma, hq, hkv, fused,
        res, g,
    )
    _, _, _, o, lse, _ = res
    return dq, dk, dv, None, jnp.zeros_like(o), jnp.zeros_like(lse)


_flash_rebuild.defvjp(_flash_rebuild_fwd, _flash_rebuild_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    window: int | None = None,
    kv_lens: jax.Array | None = None,
    offset: int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    vma: tuple[str, ...] | None = None,
    fused: bool | None = None,
    save_names: tuple[str, str] | None = None,
) -> jax.Array:
    """Exact attention on [B, L, H, D] without materializing [L, L] scores.

    ``window=W`` (requires ``causal``) is sliding-window attention: each
    query sees only its last W keys (self included), and block pairs wholly
    outside the band are skipped — compute scales O(L·W) instead of O(L²).

    Grouped-query attention: k/v may carry fewer heads than q (``Hq`` a
    multiple of ``Hkv``); each group of ``Hq/Hkv`` query heads reads one KV
    head via the grid index maps (no materialized repeat), and dk/dv
    accumulate over the whole group in-kernel.

    ``kv_lens`` [B] int32 is the key-padding mask in right-padded form
    (lengths ≥ 1): keys at positions ≥ kv_lens[b] are masked for every
    query, forward and backward — identical semantics to
    ``dense_attention(kv_lens=...)``. Padded *query* rows still produce
    (well-defined) outputs; mask them downstream (``GPTLM.loss(lengths=)``).

    Drop-in for :func:`ops.ring_attention.dense_attention` (same signature,
    same math, differentiable via fused Pallas backward kernels); use it as
    the within-device attention whenever L is long enough that the score
    matrix dominates memory (the crossover on v5e is roughly L ≥ 512).

    Auto-picked blocks follow the measured per-length policy in
    ``_pick_block`` (512 below L=4096, 1024 from there up — the round-3 ≤128
    cap was 4x slower at L=2048); pass ``block_q``/``block_k`` to
    override for odd shapes.

    ``fused`` picks the backward: the default (None) runs the one-pass
    fused dq+dk+dv kernel whenever its dq-partial buffer fits
    ``_FUSED_DQ_CAP_BYTES`` (see :func:`_resolve_fused`), falling back
    to the two-kernel split past the cap; an explicit True/False always
    wins. Gradients are identical either way within accumulation-order
    tolerance — pinned in tests/test_pallas_attention.py and
    tools/attention_parity.py. ``save_names`` — see
    :func:`flash_attention_with_lse`.
    """
    out, _ = flash_attention_with_lse(
        q, k, v,
        causal=causal, window=window, kv_lens=kv_lens, offset=offset,
        block_q=block_q, block_k=block_k,
        interpret=interpret, vma=vma, fused=fused, save_names=save_names,
    )
    return out


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    window: int | None = None,
    kv_lens: jax.Array | None = None,
    offset: int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    vma: tuple[str, ...] | None = None,
    fused: bool | None = None,
    save_names: tuple[str, str] | None = None,
) -> tuple[jax.Array, jax.Array]:
    """:func:`flash_attention` that also returns the per-row softmax
    log-sum-exp, shape [B, L, H] f32 — the statistic needed to *combine*
    partial attention over disjoint KV chunks exactly (ring attention's
    per-hop accumulation). Both outputs are differentiable. Pass
    ``vma=(axis,...)`` when calling inside a ``shard_map`` that checks
    varying-mesh-axes types (Pallas outputs carry no vma by default).

    ``offset=F`` (static, requires ``causal``) shifts every query's global
    position F ahead of the keys': the mask keeps ``0 <= q+F-k`` (and
    ``< window``). This is the blockwise-composition hook — a ring hop
    holding a KV block that originated F positions behind the local queries
    is exactly causal+window attention at offset F (all-past blocks without
    a window are the degenerate ``F >= L`` case, where it equals
    ``causal=False``).

    ``save_names=(out_name, lse_name)`` arms the selective-remat
    composition (pass :data:`REMAT_SAVE_NAMES` unless you need distinct
    labels): the forward is computed gradient-stopped, both outputs are
    tagged with ``jax.ad_checkpoint.checkpoint_name``, and gradients
    route through :func:`_flash_rebuild` whose residuals ARE the named
    values — so an enclosing ``jax.checkpoint`` with
    ``save_only_these_names(*save_names)`` stores only out+lse
    (O(B·L·d), cheap) and the backward recompute skips the O(L²)-work
    forward kernel entirely. Without an enclosing policy the naming is
    inert and the math/gradients are unchanged (pinned in
    tests/test_gpt.py selective-remat grad-identity tests)."""
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes must match: {k.shape} {v.shape}")
    if (
        q.shape[0] != k.shape[0]
        or q.shape[1] != k.shape[1]
        or q.shape[3] != k.shape[3]
        or k.shape[2] < 1
        or q.shape[2] % k.shape[2]
    ):
        raise ValueError(
            f"q {q.shape} incompatible with k/v {k.shape}: batch/len/head_dim"
            f" must match and query heads must be a multiple of KV heads"
        )
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if offset:
        if not causal:
            raise ValueError("offset requires causal=True")
        if offset < 0:
            raise ValueError(f"offset must be >= 0, got {offset}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, l, h, d = q.shape
    hkv = k.shape[2]
    if kv_lens is not None and kv_lens.shape != (b,):
        raise ValueError(
            f"kv_lens must be [batch]=({b},), got {kv_lens.shape}"
        )
    bq = _pick_block(l, block_q)
    bk = _pick_block(l, block_k)
    statics = (
        causal, window, offset, bq, bk, interpret,
        frozenset(vma) if vma else None,  # ShapeDtypeStruct wants a set
        h, hkv, _resolve_fused(fused, b * h, l, d, bk, window),
    )
    qb, kb, vb = _to_bh(q), _to_bh(k), _to_bh(v)
    if save_names is None:
        out, lse = _flash(*statics, qb, kb, vb, kv_lens)
    else:
        if len(save_names) != 2:
            raise ValueError(
                f"save_names must be (out_name, lse_name), got {save_names}"
            )
        from jax.ad_checkpoint import checkpoint_name

        # Gradient-stopped primal + named outputs + rebuild: the ONLY
        # grad path is _flash_rebuild's vjp (no double counting), and
        # its residuals are the named values a selective policy saves.
        o, lse0 = _flash(
            *statics,
            lax.stop_gradient(qb), lax.stop_gradient(kb),
            lax.stop_gradient(vb), kv_lens,
        )
        o = checkpoint_name(o, save_names[0])
        lse0 = checkpoint_name(lse0, save_names[1])
        out, lse = _flash_rebuild(*statics, qb, kb, vb, kv_lens, o, lse0)
    return _from_bh(out, b, h), jnp.transpose(lse.reshape(b, h, l), (0, 2, 1))
