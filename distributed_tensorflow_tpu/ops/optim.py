"""Optimizers (components C10/C12, SURVEY.md §2).

The reference uses plain SGD at lr=0.001
(``tf.train.GradientDescentOptimizer(0.001).minimize(...)``, reference
tfdist_between.py:64-66) with a shared non-trainable ``global_step`` counter
(reference tfsingle.py:20). Here the optimizer is an optax-style pure gradient
transformation, and ``global_step`` is part of the train state pytree — it
lives on-device and is incremented inside the compiled step, so it is exact
under both sync DP (one increment per aggregated apply, matching
SyncReplicasOptimizer semantics) and async emulation (one per local apply,
matching HOGWILD counting).

The sync-aggregation machinery of ``SyncReplicasOptimizer`` (C++ conditional
accumulators + token queues, reference tfdist_between_sync.py:66-68,86) has no
equivalent here *by design*: gradient averaging is a compiled XLA all-reduce
over the mesh's ``data`` axis (see ``parallel/``), not an optimizer concern.
"""

from __future__ import annotations

import optax


def sgd(learning_rate: float = 0.001) -> optax.GradientTransformation:
    """The reference optimizer: vanilla SGD, lr=0.001."""
    return optax.sgd(learning_rate)


def make(name: str, learning_rate, **kw) -> optax.GradientTransformation:
    """Small registry so the trainer is not MLP/SGD-specific.

    ``learning_rate`` may be a float or an optax schedule (see
    :func:`schedule`) — every optimizer here accepts either.
    """
    registry = {
        "sgd": lambda: optax.sgd(learning_rate, **kw),
        "momentum": lambda: optax.sgd(
            learning_rate, momentum=kw.pop("momentum", 0.9), **kw
        ),
        "adam": lambda: optax.adam(learning_rate, **kw),
        "adamw": lambda: optax.adamw(learning_rate, **kw),
    }
    try:
        return registry[name]()
    except KeyError:
        raise ValueError(f"unknown optimizer {name!r}; have {sorted(registry)}")


def schedule(
    name: str | None,
    learning_rate: float,
    total_steps: int,
    *,
    warmup_steps: int = 0,
):
    """Learning-rate schedule factory (no reference analog — the reference's
    lr is the constant 0.001 for all 55k steps; this is framework surface).

    ``None``/"constant" returns the float unchanged so the reference-parity
    path is bitwise-identical. Schedules are pure functions of the on-device
    step count, so they compile into the train step (and into the scanned
    epoch) with no host involvement.

    ``total_steps`` must be counted in optimizer *applies* — under gradient
    accumulation (:func:`accumulate`) the inner schedule count advances once
    per apply, not per micro-step (the launcher does this conversion).
    """
    # join_schedules offsets the post-warmup schedule by the boundary, so the
    # decay horizon is what remains after the ramp.
    decay_steps = max(1, total_steps - warmup_steps)
    if name in (None, "constant"):
        base = learning_rate
    elif name == "cosine":
        base = optax.cosine_decay_schedule(learning_rate, decay_steps)
    elif name == "linear":
        base = optax.linear_schedule(learning_rate, 0.0, decay_steps)
    elif name == "exponential":
        # Decay to 1% of the peak by the horizon, stepwise-continuous.
        base = optax.exponential_decay(learning_rate, decay_steps, decay_rate=0.01)
    else:
        raise ValueError(
            f"unknown lr schedule {name!r}; use constant/cosine/linear/exponential"
        )
    if warmup_steps > 0:
        peak = base if callable(base) else (lambda _: learning_rate)
        ramp = optax.linear_schedule(0.0, learning_rate, warmup_steps)
        return optax.join_schedules([ramp, peak], boundaries=[warmup_steps])
    return base


def accumulate(
    optimizer: optax.GradientTransformation, every: int
) -> optax.GradientTransformation:
    """Gradient accumulation: average gradients over ``every`` consecutive
    micro-steps, apply once (no reference analog — the reference's only lever
    on effective batch size was adding sync replicas,
    tfdist_between_sync.py:66-68; this is the in-chip equivalent).

    The running mean makes ``every`` microbatches of size B exactly
    equivalent to one step on a batch of size ``every``×B for mean-reduced
    losses. Entirely on-device state — composes with jit/scan/sharding.
    """
    if every <= 1:
        return optimizer
    return optax.MultiSteps(optimizer, every_k_schedule=every)


def clip(
    optimizer: optax.GradientTransformation, max_norm: float
) -> optax.GradientTransformation:
    """Global-norm gradient clipping ahead of ``optimizer`` (no reference
    analog — the reference's naive ``log(softmax)`` loss can emit huge
    gradients near saturated probabilities, reference tfsingle.py:44-45,
    and simply diverges; this is the standard guard). ``max_norm <= 0``
    disables, returning the optimizer unchanged so the reference-parity
    path is untouched."""
    if max_norm <= 0:
        return optimizer
    return optax.chain(optax.clip_by_global_norm(max_norm), optimizer)
