"""Optimizers (components C10/C12, SURVEY.md §2).

The reference uses plain SGD at lr=0.001
(``tf.train.GradientDescentOptimizer(0.001).minimize(...)``, reference
tfdist_between.py:64-66) with a shared non-trainable ``global_step`` counter
(reference tfsingle.py:20). Here the optimizer is an optax-style pure gradient
transformation, and ``global_step`` is part of the train state pytree — it
lives on-device and is incremented inside the compiled step, so it is exact
under both sync DP (one increment per aggregated apply, matching
SyncReplicasOptimizer semantics) and async emulation (one per local apply,
matching HOGWILD counting).

The sync-aggregation machinery of ``SyncReplicasOptimizer`` (C++ conditional
accumulators + token queues, reference tfdist_between_sync.py:66-68,86) has no
equivalent here *by design*: gradient averaging is a compiled XLA all-reduce
over the mesh's ``data`` axis (see ``parallel/``), not an optimizer concern.
"""

from __future__ import annotations

import optax


def sgd(learning_rate: float = 0.001) -> optax.GradientTransformation:
    """The reference optimizer: vanilla SGD, lr=0.001."""
    return optax.sgd(learning_rate)


def make(name: str, learning_rate: float, **kw) -> optax.GradientTransformation:
    """Small registry so the trainer is not MLP/SGD-specific."""
    registry = {
        "sgd": lambda: optax.sgd(learning_rate, **kw),
        "momentum": lambda: optax.sgd(learning_rate, momentum=kw.pop("momentum", 0.9)),
        "adam": lambda: optax.adam(learning_rate, **kw),
        "adamw": lambda: optax.adamw(learning_rate, **kw),
    }
    try:
        return registry[name]()
    except KeyError:
        raise ValueError(f"unknown optimizer {name!r}; have {sorted(registry)}")
