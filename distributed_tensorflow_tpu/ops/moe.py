"""Expert parallelism: a Switch-style top-k MoE FFN with all-to-all dispatch.

Absent from the reference (SURVEY.md §2b: no experts anywhere in the 6
files) but provided as first-class parallelism machinery, like tensor and
sequence parallelism: the ``expert`` mesh axis hosts one expert's weights
per device, tokens are routed by a learned gate and exchanged with a single
``lax.all_to_all`` each way — the EP pattern whose transport the reference
would have had to build from PS RPCs.

Semantics (chosen to be exactly reproducible by a dense reference, which is
how the tests validate the distributed path):

- top-k routing (``k=1`` default = Switch): each token goes to its ``k``
  highest gate logits. Combine weights are the router probabilities —
  raw for k=1 (Switch: out = p·expert(x), the gradient path into the
  gate), renormalized over the chosen experts for k≥2 (the standard
  top-2/Mixtral convention: Σ over chosen = 1);
- per-source-device capacity C: each device sends at most C of its local
  (token, choice) dispatches to each expert, keeping shapes static (XLA
  requirement). Slots fill in CHOICE-MAJOR order (every token's first
  choice before any second choice — GShard priority: a later token's
  second choice never evicts an earlier token's first choice); dispatches
  over capacity contribute zero (standard Switch overflow behavior);
- combined output = Σ_choices weight·expert_out, residual-friendly.

Call :func:`moe_ffn` inside ``jax.shard_map`` over the ``expert`` axis with
tokens sharded on the leading dim and expert weights stacked [E, ...]
sharded on dim 0. :func:`moe_ffn_dense` is the single-device reference.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class MoEParams(NamedTuple):
    wg: jax.Array  # [D, E] gate
    w_up: jax.Array  # [E, D, H] expert FFN up
    b_up: jax.Array  # [E, H]
    w_down: jax.Array  # [E, H, D] expert FFN down
    b_down: jax.Array  # [E, D]


class MoEAux(NamedTuple):
    """Router observability/trainability statistics, all scalar f32, computed
    over the tokens one ``moe_ffn*`` call routes:

    - ``balance_loss``: the Switch load-balancing auxiliary loss
      ``E · Σ_e f_e · P_e`` (f_e = fraction of tokens argmax-routed to
      expert e, P_e = mean router probability of e) — differentiable through
      P, minimized at 1.0 by uniform routing; without it nothing stops
      top-1 routing from collapsing onto one expert.
    - ``z_loss``: the ST-MoE router z-loss ``mean(logsumexp(logits)²)``,
      keeping gate logits small so bf16 routing stays stable.
    - ``drop_fraction``: fraction of tokens beyond expert capacity (passed
      through with zero expert contribution). NOT differentiable — a pure
      metric, and the observable guard on every "equal in the no-drop
      regime" claim (models/gpt.py ep==dense, dp==single-device).
    - ``expert_fraction``: the dispatch distribution f itself, [E] — the
      direct utilization readout (collapse shows as one entry → 1).
    """

    balance_loss: jax.Array
    z_loss: jax.Array
    drop_fraction: jax.Array
    expert_fraction: jax.Array

    @staticmethod
    def zero() -> "MoEAux":
        z = jnp.zeros((), jnp.float32)
        return MoEAux(z, z, z, z)


def init_moe(key, d: int, hidden: int, num_experts: int) -> MoEParams:
    k1, k2, k3 = jax.random.split(key, 3)
    return MoEParams(
        wg=jax.random.normal(k1, (d, num_experts), jnp.float32) / jnp.sqrt(d),
        w_up=jax.random.normal(k2, (num_experts, d, hidden), jnp.float32)
        / jnp.sqrt(d),
        b_up=jnp.zeros((num_experts, hidden), jnp.float32),
        w_down=jax.random.normal(k3, (num_experts, hidden, d), jnp.float32)
        / jnp.sqrt(hidden),
        b_down=jnp.zeros((num_experts, d), jnp.float32),
    )


def _expert_ffn(x, w_up, b_up, w_down, b_down):
    h = jax.nn.gelu(
        jnp.dot(x, w_up, preferred_element_type=jnp.float32) + b_up
    )
    return jnp.dot(h, w_down, preferred_element_type=jnp.float32) + b_down


def _route(x, wg, num_experts: int, capacity: int, token_mask=None, k: int = 1):
    """Shared top-k routing: returns (expert_idx [T, k], gate_w [T, k],
    slot [T, k], keep [T, k], aux :class:`MoEAux`) where slot is the
    (token, choice) dispatch's position in its (expert, source) capacity
    buffer and keep = slot < capacity.

    Combine weights ``gate_w``: the raw router probability for k=1 (Switch
    — out = p·expert(x) is the gradient path into the gate), probabilities
    renormalized over the k chosen experts for k≥2 (top-2/Mixtral
    convention). Capacity slots fill CHOICE-MAJOR (all first choices in
    token order, then all second choices, ...) — GShard priority: a later
    token's second choice never evicts an earlier token's first choice.

    ``token_mask`` [T] bool marks real tokens in a right-padded ragged
    batch: pad tokens are never dispatched (keep=False), never consume a
    capacity slot, and are excluded from every aux statistic — so ragged
    MoE batches are exactly pad-content-independent (without the mask, a
    pad token could displace a real one from its expert's queue and the
    balance/z losses would average over garbage)."""
    if not 1 <= k <= num_experts:
        raise ValueError(f"top-k k={k} must be in [1, num_experts={num_experts}]")
    t = x.shape[0]
    logits = jnp.dot(x, wg, preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if k == 1:
        expert_idx = jnp.argmax(logits, axis=-1)[:, None]  # [T, 1]
    else:
        _, expert_idx = lax.top_k(logits, k)  # [T, k], rank order
    gate_w = jnp.take_along_axis(probs, expert_idx, axis=-1)  # [T, k]
    if k > 1:
        gate_w = gate_w / jnp.sum(gate_w, axis=-1, keepdims=True)
    onehot = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.int32)  # [T, k, E]
    if token_mask is not None:
        onehot = onehot * token_mask[:, None, None].astype(jnp.int32)
    # Queue position per (token, choice) dispatch: cumsum over the
    # choice-major flattening [k·T, E] (choice c of token t at row c·T+t).
    flat = onehot.swapaxes(0, 1).reshape(k * t, num_experts)
    slot_flat = (jnp.cumsum(flat, axis=0) - 1).reshape(k, t, num_experts)
    slot = jnp.take_along_axis(
        slot_flat.swapaxes(0, 1), expert_idx[:, :, None], axis=-1
    )[:, :, 0]  # [T, k]
    keep = slot < capacity
    if token_mask is not None:
        keep &= token_mask[:, None]
    # Aux statistics over this call's REAL dispatches. f rides
    # stop_gradient-free one_hot (int → no gradient anyway); the
    # differentiable path into the gate weights is P — the Switch
    # formulation, with f normalized over T·k dispatches for top-k (so
    # uniform routing still minimizes balance_loss at 1.0 for every k).
    lse2 = jax.scipy.special.logsumexp(logits, axis=-1) ** 2
    dispatch = jnp.sum(onehot, axis=1)  # [T, E] — how many choices hit e
    if token_mask is None:
        f = jnp.mean(dispatch.astype(jnp.float32), axis=0) / k  # [E]
        p_mean = jnp.mean(probs, axis=0)  # [E] mean router prob
        z = jnp.mean(lse2)
        kept = jnp.mean(keep.astype(jnp.float32))
    else:
        w = token_mask.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(w), 1.0)
        f = jnp.sum(dispatch.astype(jnp.float32), axis=0) / (denom * k)
        p_mean = jnp.sum(probs * w[:, None], axis=0) / denom
        z = jnp.sum(lse2 * w) / denom
        kept = jnp.sum(
            keep.astype(jnp.float32) * w[:, None]
        ) / (denom * k)
    aux = MoEAux(
        balance_loss=num_experts * jnp.sum(f * p_mean),
        z_loss=z,
        drop_fraction=1.0 - kept,
        expert_fraction=f,
    )
    return expert_idx, gate_w, slot, keep, aux


def _combine(gate_w, keep, gathered):
    """Weighted combine over the k choices: Σ_c keep_c·w_c·out_c.
    gate_w/keep: [T, k]; gathered: [T, k, D] → [T, D]."""
    w = jnp.where(keep, gate_w, 0.0)
    return jnp.einsum("tk,tkd->td", w, gathered)


def moe_ffn_dense(
    params: MoEParams,
    x: jax.Array,
    capacity: int,
    *,
    with_aux: bool = False,
    token_mask: jax.Array | None = None,
    k: int = 1,
):
    """Single-device reference with identical routing/drop semantics: every
    expert computed locally, per-expert capacity applied in dispatch order.
    ``with_aux=True`` also returns the router's :class:`MoEAux`;
    ``token_mask`` [T] bool excludes pad tokens from routing and ``k`` is
    the top-k routing width (see :func:`_route`)."""
    e = params.wg.shape[1]
    expert_idx, gate_w, _, keep, aux = _route(
        x, params.wg, e, capacity, token_mask, k=k
    )
    outs = jax.vmap(_expert_ffn, in_axes=(None, 0, 0, 0, 0))(
        x, params.w_up, params.b_up, params.w_down, params.b_down
    )  # [E, T, D]
    picked = outs[expert_idx, jnp.arange(x.shape[0])[:, None]]  # [T, k, D]
    out = _combine(gate_w, keep, picked)
    return (out, aux) if with_aux else out


def moe_ffn_local(
    params: MoEParams,
    x: jax.Array,
    capacity: int,
    *,
    with_aux: bool = False,
    token_mask: jax.Array | None = None,
    k: int = 1,
):
    """Single-device switch FFN at sparse cost: route, gather each expert's
    ≤``capacity`` dispatches into its buffer, run every expert ONCE on its
    buffer, scatter back. Identical semantics to :func:`moe_ffn_dense`
    (same ``_route``, same per-expert choice-major capacity — a single
    source makes per-source and global capacity the same thing) at
    ``E·capacity`` token-FFNs instead of dense's ``E·T`` — the sparse
    compute MoE exists for, without the cross-device exchange.
    ``with_aux=True`` also returns the router's :class:`MoEAux`;
    ``token_mask`` [T] bool excludes pad tokens from routing and ``k`` is
    the top-k routing width (see :func:`_route`)."""
    e = params.wg.shape[1]
    t, d = x.shape
    expert_idx, gate_w, slot, keep, aux = _route(
        x, params.wg, e, capacity, token_mask, k=k
    )

    send = jnp.zeros((e, capacity, d), x.dtype)
    rows = jnp.where(keep, expert_idx, 0)  # [T, k]
    cols = jnp.where(keep, slot, 0)
    contrib = jnp.where(keep[:, :, None], x[:, None, :], 0.0)  # [T, k, D]
    send = send.at[rows, cols].add(contrib)  # kept slots unique → add==set

    out = jax.vmap(_expert_ffn)(
        send, params.w_up, params.b_up, params.w_down, params.b_down
    )  # [E, C, D]
    gathered = out[rows, cols]  # [T, k, D]
    result = _combine(gate_w, keep, gathered)
    return (result, aux) if with_aux else result


def moe_ffn(
    params: MoEParams,
    x: jax.Array,
    axis_name: str,
    capacity: int,
    *,
    with_aux: bool = False,
    token_mask: jax.Array | None = None,
    k: int = 1,
):
    """Expert-parallel forward body (inside shard_map over ``axis_name``).

    ``x``: this device's local tokens [T_loc, D]. ``params.w_up`` etc. carry
    a leading [1, ...] slice — this device's expert. Returns [T_loc, D].
    ``with_aux=True`` also returns this device's router :class:`MoEAux`
    (local-token statistics; pmean over the axis for the global view);
    ``k`` is the top-k routing width (see :func:`_route` — k≥2 sends each
    token to up to k experts through the same two all-to-alls).
    """
    n = lax.axis_size(axis_name)
    t_loc, d = x.shape
    expert_idx, gate_w, slot, keep, aux = _route(
        x, params.wg, n, capacity, token_mask, k=k
    )

    # Build the outgoing buffers: for each destination expert e, a [C, D]
    # block of this device's dispatches routed to e (zeros elsewhere).
    send = jnp.zeros((n, capacity, d), x.dtype)
    rows = jnp.where(keep, expert_idx, 0)  # [T_loc, k]
    cols = jnp.where(keep, slot, 0)
    contrib = jnp.where(keep[:, :, None], x[:, None, :], 0.0)  # [T, k, D]
    send = send.at[rows, cols].add(contrib)  # kept slots unique → add==set

    # Exchange: device g's block e goes to device e (and we receive one
    # [C, D] block from every source) → [n, C, D] of tokens for OUR expert.
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0, tiled=True)

    # Run our expert on all received tokens.
    out = _expert_ffn(
        recv.reshape(n * capacity, d),
        params.w_up[0],
        params.b_up[0],
        params.w_down[0],
        params.b_down[0],
    ).reshape(n, capacity, d)

    # Return to senders and un-permute into token order.
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0, tiled=True)
    gathered = back[rows, cols]  # [T_loc, k, D]
    result = _combine(gate_w, keep, gathered)
    return (result, aux) if with_aux else result
