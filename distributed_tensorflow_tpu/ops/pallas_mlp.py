"""Pallas TPU kernel: the MLP train step fused into one kernel launch.

The reference's hot loop is fwd → loss → bwd → SGD apply per batch, executed
as a TF graph of many small CUDA kernels (reference tfsingle.py:78-80). XLA
already fuses most of that; this module goes the rest of the way with a
single Pallas kernel computing forward, naive-CE loss, analytic backward,
and the in-place SGD update in one VMEM-resident program:

    z1 = x·W1+b1; h = σ(z1); p = softmax(h·W2+b2)
    dlogits = (p - y)/B                        (softmax+CE analytic grad)
    dW2 = hᵀ·dlogits   dh = dlogits·W2ᵀ
    dz1 = dh·h·(1-h)   dW1 = xᵀ·dz1
    W ← W - lr·dW      b ← b - lr·db

Every tensor (batch 100×784 plus both weight matrices, ~700 KB f32) fits in
VMEM simultaneously, so HBM traffic per step is exactly one read of
x/y/params and one write of params — the bandwidth floor. The four matmuls
hit the MXU with f32 accumulation.

Biases are carried as (1, H) 2-D rows: TPU tiling is (sublane, lane)-
oriented and 1-D vectors would be padded awkwardly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_tensorflow_tpu.models.mlp import MLPParams

_LOG_EPS = 1e-30


def _mlp_sgd_math(x, y, w1, b1, w2, b2, lr: float):
    """The fwd/loss/bwd/SGD math shared by both kernels (one source of
    truth — the scan-vs-epoch equivalence test depends on it). Shapes stay
    2-D throughout: Mosaic's vector layouts are (sublane, lane)-tiled and
    1-D intermediates trip relayout bugs. Returns (nw1, nb1, nw2, nb2,
    cost_scalar)."""
    # Forward (MXU matmuls, f32 accumulation).
    z1 = jnp.dot(x, w1, preferred_element_type=jnp.float32) + b1
    h = jax.nn.sigmoid(z1)
    logits = jnp.dot(h, w2, preferred_element_type=jnp.float32) + b2
    p = jax.nn.softmax(logits, axis=-1)

    # The reference's naive CE (NaN-guarded), reference tfsingle.py:44-45.
    inv_b = 1.0 / x.shape[0]
    per_example = -jnp.sum(
        y * jnp.log(jnp.maximum(p, _LOG_EPS)), axis=-1, keepdims=True
    )
    cost = jnp.sum(per_example) * inv_b
    dlogits = (p - y) * inv_b
    dw2 = jnp.dot(h.T, dlogits, preferred_element_type=jnp.float32)
    db2 = jnp.sum(dlogits, axis=0, keepdims=True)
    dh = jnp.dot(dlogits, w2.T, preferred_element_type=jnp.float32)
    dz1 = dh * h * (1.0 - h)
    dw1 = jnp.dot(x.T, dz1, preferred_element_type=jnp.float32)
    db1 = jnp.sum(dz1, axis=0, keepdims=True)

    # Fused SGD apply (C10 semantics: plain SGD, reference tfdist_between.py:64-66).
    return w1 - lr * dw1, b1 - lr * db1, w2 - lr * dw2, b2 - lr * db2, cost


def _fused_train_kernel(
    x_ref, y_ref, w1_ref, b1_ref, w2_ref, b2_ref,
    nw1_ref, nb1_ref, nw2_ref, nb2_ref, cost_ref,
    *, lr: float,
):
    nw1, nb1, nw2, nb2, cost = _mlp_sgd_math(
        x_ref[:], y_ref[:], w1_ref[:], b1_ref[:], w2_ref[:], b2_ref[:], lr
    )
    cost_ref[0, 0] = cost
    nw1_ref[:] = nw1
    nb1_ref[:] = nb1
    nw2_ref[:] = nw2
    nb2_ref[:] = nb2


class FusedState(NamedTuple):
    """Params with 2-D biases, the kernel's native layout."""

    w1: jax.Array
    b1: jax.Array  # [1, hidden]
    w2: jax.Array
    b2: jax.Array  # [1, out]


def to_fused(params: MLPParams) -> FusedState:
    # copy=True: the caller's buffers may be donated elsewhere (the fused
    # step itself donates via input_output_aliases), so never alias them.
    return FusedState(
        jnp.array(params.w1, jnp.float32, copy=True),
        jnp.array(params.b1.reshape(1, -1), jnp.float32, copy=True),
        jnp.array(params.w2, jnp.float32, copy=True),
        jnp.array(params.b2.reshape(1, -1), jnp.float32, copy=True),
    )


def from_fused(state: FusedState) -> MLPParams:
    return MLPParams(state.w1, state.b1[0], state.w2, state.b2[0])


def make_fused_train_step(
    *,
    batch_size: int,
    in_dim: int = 784,
    hidden_dim: int = 100,
    out_dim: int = 10,
    learning_rate: float = 0.001,
    interpret: bool | None = None,
):
    """Build ``step(fused_state, x, y) -> (fused_state, cost)``, one kernel
    launch per call. ``interpret=None`` auto-selects the Pallas interpreter
    off-TPU (CI / CPU-mesh tests) and the Mosaic compiler on TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    f32 = jnp.float32
    call = pl.pallas_call(
        partial(_fused_train_kernel, lr=learning_rate),
        out_shape=(
            jax.ShapeDtypeStruct((in_dim, hidden_dim), f32),
            jax.ShapeDtypeStruct((1, hidden_dim), f32),
            jax.ShapeDtypeStruct((hidden_dim, out_dim), f32),
            jax.ShapeDtypeStruct((1, out_dim), f32),
            jax.ShapeDtypeStruct((1, 1), f32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 6,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        # Params update in place: new W/b alias the incoming buffers.
        input_output_aliases={2: 0, 3: 1, 4: 2, 5: 3},
        interpret=interpret,
    )

    @jax.jit
    def step(state: FusedState, x: jax.Array, y: jax.Array):
        nw1, nb1, nw2, nb2, cost = call(
            x.astype(f32), y.astype(f32), state.w1, state.b1, state.w2, state.b2
        )
        return FusedState(nw1, nb1, nw2, nb2), cost[0, 0]

    return step


def make_fused_scanned_fn(
    *,
    batch_size: int,
    learning_rate: float = 0.001,
    interpret: bool | None = None,
    **dims,
):
    """Scan the fused kernel over a staged epoch: [steps, B, ...] → one
    dispatch per epoch AND one kernel per step inside it."""
    step = make_fused_train_step(
        batch_size=batch_size, learning_rate=learning_rate, interpret=interpret, **dims
    )

    @partial(jax.jit, donate_argnums=0)
    def run(state: FusedState, xs: jax.Array, ys: jax.Array):
        def body(state, batch):
            x, y = batch
            state, cost = step(state, x, y)
            return state, cost

        return jax.lax.scan(body, state, (xs, ys))

    return run


def _epoch_kernel(
    x_ref, y_ref, w1_ref, b1_ref, w2_ref, b2_ref,
    nw1_ref, nb1_ref, nw2_ref, nb2_ref, cost_ref,
    *, lr: float,
):
    """Grid step i = SGD step i of the epoch. Params live in the *output*
    VMEM blocks (constant index map → resident across the whole grid, never
    round-tripping HBM between steps); each step streams only its batch
    block in. First iteration seeds the output blocks from the inputs."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _seed():
        nw1_ref[:] = w1_ref[:]
        nb1_ref[:] = b1_ref[:]
        nw2_ref[:] = w2_ref[:]
        nb2_ref[:] = b2_ref[:]

    # Batches may be staged bf16 (halves the per-step HBM stream — the
    # only HBM traffic this kernel has); math runs f32 as always.
    nw1, nb1, nw2, nb2, cost = _mlp_sgd_math(
        x_ref[0].astype(jnp.float32),
        y_ref[0].astype(jnp.float32),
        nw1_ref[:], nb1_ref[:], nw2_ref[:], nb2_ref[:], lr,
    )
    # Costs are written into (8, 128) VMEM blocks — the smallest f32 tile
    # TPU block specs allow — grouped 8 steps per block (index map i // 8):
    # the block stays resident across its 8 revisits, each step storing its
    # lane-broadcast scalar into sublane i % 8. The host reads [:, 0].
    cost_ref[pl.ds(i % 8, 1), :] = jnp.broadcast_to(
        cost, (1, cost_ref.shape[1])
    )
    nw1_ref[:] = nw1
    nb1_ref[:] = nb1
    nw2_ref[:] = nw2
    nb2_ref[:] = nb2


def make_fused_epoch_fn(
    *,
    steps: int,
    batch_size: int,
    in_dim: int = 784,
    hidden_dim: int = 100,
    out_dim: int = 10,
    learning_rate: float = 0.001,
    stream_dtype: jnp.dtype = jnp.float32,
    interpret: bool | None = None,
):
    """Build ``run(state, xs, ys) -> (state, costs)`` where the WHOLE epoch
    (or several concatenated epochs) is ONE kernel launch: ``grid=(steps,)``
    walks the staged batches, parameters stay VMEM-resident across every
    step (constant-index-map output blocks), and per-step HBM traffic is
    exactly the batch read plus one scalar cost write — strictly less than
    the scan-of-kernels path, which re-reads and re-writes the params each
    step. ``xs``/``ys`` are ``[steps, batch, ...]`` in ``stream_dtype``.

    ``stream_dtype=bf16`` stages the batches half-width — the batch read is
    the kernel's only per-step HBM traffic — and upcasts in VMEM; the
    update math stays f32 (costs differ from f32 staging only by input
    rounding).

    Tried and rejected: unrolling U steps per grid iteration (measured
    *slower* on v5e, ~6.2 vs ~5.1 ms per 550-step epoch at U=8 — the
    per-grid-step overhead is already hidden behind the batch-block DMA,
    and bigger blocks pipeline worse; see docs/performance.md).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    f32 = jnp.float32
    full = lambda *shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    call = pl.pallas_call(
        partial(_epoch_kernel, lr=learning_rate),
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((1, batch_size, in_dim), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, batch_size, out_dim), lambda i: (i, 0, 0)),
            full(in_dim, hidden_dim),
            full(1, hidden_dim),
            full(hidden_dim, out_dim),
            full(1, out_dim),
        ],
        out_specs=(
            full(in_dim, hidden_dim),
            full(1, hidden_dim),
            full(hidden_dim, out_dim),
            full(1, out_dim),
            pl.BlockSpec((8, 128), lambda i: (i // 8, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((in_dim, hidden_dim), f32),
            jax.ShapeDtypeStruct((1, hidden_dim), f32),
            jax.ShapeDtypeStruct((hidden_dim, out_dim), f32),
            jax.ShapeDtypeStruct((1, out_dim), f32),
            jax.ShapeDtypeStruct((-(-steps // 8) * 8, 128), f32),
        ),
        interpret=interpret,
    )

    @partial(jax.jit, donate_argnums=0)
    def run(state: FusedState, xs: jax.Array, ys: jax.Array):
        nw1, nb1, nw2, nb2, costs = call(
            xs.astype(stream_dtype),
            ys.astype(stream_dtype),
            state.w1, state.b1, state.w2, state.b2,
        )
        return FusedState(nw1, nb1, nw2, nb2), costs[:steps, 0]

    return run
