"""Pallas TPU kernel: the MLP train step fused into one kernel launch.

The reference's hot loop is fwd → loss → bwd → SGD apply per batch, executed
as a TF graph of many small CUDA kernels (reference tfsingle.py:78-80). XLA
already fuses most of that; this module goes the rest of the way with a
single Pallas kernel computing forward, naive-CE loss, analytic backward,
and the in-place SGD update in one VMEM-resident program:

    z1 = x·W1+b1; h = σ(z1); p = softmax(h·W2+b2)
    dlogits = (p - y)/B                        (softmax+CE analytic grad)
    dW2 = hᵀ·dlogits   dh = dlogits·W2ᵀ
    dz1 = dh·h·(1-h)   dW1 = xᵀ·dz1
    W ← W - lr·dW      b ← b - lr·db

Every tensor (batch 100×784 plus both weight matrices, ~700 KB f32) fits in
VMEM simultaneously, so HBM traffic per step is exactly one read of
x/y/params and one write of params — the bandwidth floor. The four matmuls
hit the MXU with f32 accumulation.

Biases are carried as (1, H) 2-D rows: TPU tiling is (sublane, lane)-
oriented and 1-D vectors would be padded awkwardly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_tensorflow_tpu.models.mlp import MLPParams

_LOG_EPS = 1e-30


def _mlp_sgd_math(x, y, w1, b1, w2, b2, lr: float):
    """The fwd/loss/bwd/SGD math shared by both kernels (one source of
    truth — the scan-vs-epoch equivalence test depends on it). Shapes stay
    2-D throughout: Mosaic's vector layouts are (sublane, lane)-tiled and
    1-D intermediates trip relayout bugs. Returns (nw1, nb1, nw2, nb2,
    cost_scalar)."""
    # Forward (MXU matmuls, f32 accumulation).
    z1 = jnp.dot(x, w1, preferred_element_type=jnp.float32) + b1
    h = jax.nn.sigmoid(z1)
    logits = jnp.dot(h, w2, preferred_element_type=jnp.float32) + b2
    p = jax.nn.softmax(logits, axis=-1)

    # The reference's naive CE (NaN-guarded), reference tfsingle.py:44-45.
    inv_b = 1.0 / x.shape[0]
    per_example = -jnp.sum(
        y * jnp.log(jnp.maximum(p, _LOG_EPS)), axis=-1, keepdims=True
    )
    cost = jnp.sum(per_example) * inv_b
    dlogits = (p - y) * inv_b
    dw2 = jnp.dot(h.T, dlogits, preferred_element_type=jnp.float32)
    db2 = jnp.sum(dlogits, axis=0, keepdims=True)
    dh = jnp.dot(dlogits, w2.T, preferred_element_type=jnp.float32)
    dz1 = dh * h * (1.0 - h)
    dw1 = jnp.dot(x.T, dz1, preferred_element_type=jnp.float32)
    db1 = jnp.sum(dz1, axis=0, keepdims=True)

    # Fused SGD apply (C10 semantics: plain SGD, reference tfdist_between.py:64-66).
    return w1 - lr * dw1, b1 - lr * db1, w2 - lr * dw2, b2 - lr * db2, cost


def _fused_train_kernel(
    x_ref, y_ref, w1_ref, b1_ref, w2_ref, b2_ref,
    nw1_ref, nb1_ref, nw2_ref, nb2_ref, cost_ref,
    *, lr: float,
):
    nw1, nb1, nw2, nb2, cost = _mlp_sgd_math(
        x_ref[:], y_ref[:], w1_ref[:], b1_ref[:], w2_ref[:], b2_ref[:], lr
    )
    cost_ref[0, 0] = cost
    nw1_ref[:] = nw1
    nb1_ref[:] = nb1
    nw2_ref[:] = nw2
    nb2_ref[:] = nb2


class FusedState(NamedTuple):
    """Params with 2-D biases, the kernel's native layout."""

    w1: jax.Array
    b1: jax.Array  # [1, hidden]
    w2: jax.Array
    b2: jax.Array  # [1, out]


def to_fused(params: MLPParams) -> FusedState:
    # copy=True: the caller's buffers may be donated elsewhere (the fused
    # step itself donates via input_output_aliases), so never alias them.
    return FusedState(
        jnp.array(params.w1, jnp.float32, copy=True),
        jnp.array(params.b1.reshape(1, -1), jnp.float32, copy=True),
        jnp.array(params.w2, jnp.float32, copy=True),
        jnp.array(params.b2.reshape(1, -1), jnp.float32, copy=True),
    )


def from_fused(state: FusedState) -> MLPParams:
    return MLPParams(state.w1, state.b1[0], state.w2, state.b2[0])


def make_fused_train_step(
    *,
    batch_size: int,
    in_dim: int = 784,
    hidden_dim: int = 100,
    out_dim: int = 10,
    learning_rate: float = 0.001,
    interpret: bool | None = None,
):
    """Build ``step(fused_state, x, y) -> (fused_state, cost)``, one kernel
    launch per call. ``interpret=None`` auto-selects the Pallas interpreter
    off-TPU (CI / CPU-mesh tests) and the Mosaic compiler on TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    f32 = jnp.float32
    call = pl.pallas_call(
        partial(_fused_train_kernel, lr=learning_rate),
        out_shape=(
            jax.ShapeDtypeStruct((in_dim, hidden_dim), f32),
            jax.ShapeDtypeStruct((1, hidden_dim), f32),
            jax.ShapeDtypeStruct((hidden_dim, out_dim), f32),
            jax.ShapeDtypeStruct((1, out_dim), f32),
            jax.ShapeDtypeStruct((1, 1), f32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 6,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        # Params update in place: new W/b alias the incoming buffers.
        input_output_aliases={2: 0, 3: 1, 4: 2, 5: 3},
        interpret=interpret,
    )

    @jax.jit
    def step(state: FusedState, x: jax.Array, y: jax.Array):
        nw1, nb1, nw2, nb2, cost = call(
            x.astype(f32), y.astype(f32), state.w1, state.b1, state.w2, state.b2
        )
        return FusedState(nw1, nb1, nw2, nb2), cost[0, 0]

    return step


def make_fused_scanned_fn(
    *,
    batch_size: int,
    learning_rate: float = 0.001,
    interpret: bool | None = None,
    **dims,
):
    """Scan the fused kernel over a staged epoch: [steps, B, ...] → one
    dispatch per epoch AND one kernel per step inside it."""
    step = make_fused_train_step(
        batch_size=batch_size, learning_rate=learning_rate, interpret=interpret, **dims
    )

    @partial(jax.jit, donate_argnums=0)
    def run(state: FusedState, xs: jax.Array, ys: jax.Array):
        def body(state, batch):
            x, y = batch
            state, cost = step(state, x, y)
            return state, cost

        return jax.lax.scan(body, state, (xs, ys))

    return run


def _epoch_kernel(
    x_ref, y_ref, w1_ref, b1_ref, w2_ref, b2_ref,
    nw1_ref, nb1_ref, nw2_ref, nb2_ref, cost_ref,
    *, lr: float,
):
    """Grid step i = SGD step i of the epoch. Params live in the *output*
    VMEM blocks (constant index map → resident across the whole grid, never
    round-tripping HBM between steps); each step streams only its batch
    block in. First iteration seeds the output blocks from the inputs."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _seed():
        nw1_ref[:] = w1_ref[:]
        nb1_ref[:] = b1_ref[:]
        nw2_ref[:] = w2_ref[:]
        nb2_ref[:] = b2_ref[:]

    # Batches may be staged bf16 (halves the per-step HBM stream — the
    # only HBM traffic this kernel has); math runs f32 as always.
    nw1, nb1, nw2, nb2, cost = _mlp_sgd_math(
        x_ref[0].astype(jnp.float32),
        y_ref[0].astype(jnp.float32),
        nw1_ref[:], nb1_ref[:], nw2_ref[:], nb2_ref[:], lr,
    )
    # Costs are written into (8, 128) VMEM blocks — the smallest f32 tile
    # TPU block specs allow — grouped 8 steps per block (index map i // 8):
    # the block stays resident across its 8 revisits, each step storing its
    # lane-broadcast scalar into sublane i % 8. The host reads [:, 0].
    cost_ref[pl.ds(i % 8, 1), :] = jnp.broadcast_to(
        cost, (1, cost_ref.shape[1])
    )
    nw1_ref[:] = nw1
    nb1_ref[:] = nb1
    nw2_ref[:] = nw2
    nb2_ref[:] = nb2


def _epoch_call(
    *,
    steps: int,
    batch_size: int,
    in_dim: int,
    hidden_dim: int,
    out_dim: int,
    learning_rate: float,
    interpret: bool,
):
    """The raw whole-epoch ``pallas_call`` (grid over ``steps``), shared by
    the single-chip jitted wrapper (``make_fused_epoch_fn``) and the
    data-parallel composition (``make_fused_async_epoch_fn``), which embeds
    it under ``shard_map``."""
    f32 = jnp.float32
    full = lambda *shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    return pl.pallas_call(
        partial(_epoch_kernel, lr=learning_rate),
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((1, batch_size, in_dim), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, batch_size, out_dim), lambda i: (i, 0, 0)),
            full(in_dim, hidden_dim),
            full(1, hidden_dim),
            full(hidden_dim, out_dim),
            full(1, out_dim),
        ],
        out_specs=(
            full(in_dim, hidden_dim),
            full(1, hidden_dim),
            full(hidden_dim, out_dim),
            full(1, out_dim),
            pl.BlockSpec((8, 128), lambda i: (i // 8, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((in_dim, hidden_dim), f32),
            jax.ShapeDtypeStruct((1, hidden_dim), f32),
            jax.ShapeDtypeStruct((hidden_dim, out_dim), f32),
            jax.ShapeDtypeStruct((1, out_dim), f32),
            jax.ShapeDtypeStruct((-(-steps // 8) * 8, 128), f32),
        ),
        interpret=interpret,
    )


def make_fused_epoch_fn(
    *,
    steps: int,
    batch_size: int,
    in_dim: int = 784,
    hidden_dim: int = 100,
    out_dim: int = 10,
    learning_rate: float = 0.001,
    stream_dtype: jnp.dtype = jnp.float32,
    interpret: bool | None = None,
):
    """Build ``run(state, xs, ys) -> (state, costs)`` where the WHOLE epoch
    (or several concatenated epochs) is ONE kernel launch: ``grid=(steps,)``
    walks the staged batches, parameters stay VMEM-resident across every
    step (constant-index-map output blocks), and per-step HBM traffic is
    exactly the batch read plus one scalar cost write — strictly less than
    the scan-of-kernels path, which re-reads and re-writes the params each
    step. ``xs``/``ys`` are ``[steps, batch, ...]`` in ``stream_dtype``.

    ``stream_dtype=bf16`` stages the batches half-width — the batch read is
    the kernel's only per-step HBM traffic — and upcasts in VMEM; the
    update math stays f32 (costs differ from f32 staging only by input
    rounding).

    Tried and rejected: unrolling U steps per grid iteration (measured
    *slower* on v5e, ~6.2 vs ~5.1 ms per 550-step epoch at U=8 — the
    per-grid-step overhead is already hidden behind the batch-block DMA,
    and bigger blocks pipeline worse; see docs/performance.md).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    call = _epoch_call(
        steps=steps,
        batch_size=batch_size,
        in_dim=in_dim,
        hidden_dim=hidden_dim,
        out_dim=out_dim,
        learning_rate=learning_rate,
        interpret=interpret,
    )

    @partial(jax.jit, donate_argnums=0)
    def run(state: FusedState, xs: jax.Array, ys: jax.Array):
        nw1, nb1, nw2, nb2, costs = call(
            xs.astype(stream_dtype),
            ys.astype(stream_dtype),
            state.w1, state.b1, state.w2, state.b2,
        )
        return FusedState(nw1, nb1, nw2, nb2), costs[:steps, 0]

    return run


def make_fused_async_epoch_fn(
    mesh,
    *,
    steps: int,
    batch_size: int,
    in_dim: int = 784,
    hidden_dim: int = 100,
    out_dim: int = 10,
    learning_rate: float = 0.001,
    avg_every: int = 0,
    stream_dtype: jnp.dtype = jnp.float32,
    interpret: bool | None = None,
):
    """The whole-epoch grid kernel composed with data parallelism — the
    framework's fastest engine distributed over the ``data`` mesh axis
    (round-1 gap: the bench-default kernel was single-device only; the
    reference's whole point was distributing this workload, reference
    tfdist_between.py:86-95).

    Async local-SGD is the natural first composition because an exchange
    round needs ZERO cross-chip traffic inside it: each chip runs the grid
    kernel over its own ``avg_every``-step batch slice with params
    VMEM-resident (one Mosaic launch per round), then all copies jump to the
    ``pmean`` over ICI — the same semantics as
    ``AsyncDataParallel.make_scanned_train_fn`` with the per-step XLA scan
    replaced by the Pallas grid. (A per-step sync composition would need a
    collective between grid steps, destroying the VMEM residency that makes
    the kernel fast.)

    Returns ``run(state, xs, ys) -> (state, costs)`` with ``state`` a
    ``FusedState`` of stacked per-chip copies (leading axis ``n`` sharded
    over ``data``), ``xs``/``ys`` ``[steps, n*batch, ...]`` with dim 1
    sharded over ``data``, and ``costs`` ``[steps]`` the per-step mean over
    chips. ``update_scale`` is not modeled here: per-chip lr stays the
    constructor's ``learning_rate`` (pass a pre-scaled value if emulating
    the async update-count effect).
    """
    from jax.sharding import PartitionSpec as P

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Exchange cadence must match _scan_with_exchange exactly: rounds only
    # when a full avg_every round fits (an epoch shorter than avg_every
    # runs plain, with NO exchange — strategy.py:82's `steps >= avg_every`).
    use_rounds = bool(avg_every) and steps >= avg_every
    seg = avg_every if use_rounds else steps
    rounds = steps // seg
    head = rounds * seg
    kw = dict(
        batch_size=batch_size,
        in_dim=in_dim,
        hidden_dim=hidden_dim,
        out_dim=out_dim,
        learning_rate=learning_rate,
        interpret=interpret,
    )
    call = _epoch_call(steps=seg, **kw)
    tail_call = _epoch_call(steps=steps - head, **kw) if steps % seg else None

    def _exchange(params):
        # Every copy jumps to the mean (AsyncDataParallel.make_exchange_fn
        # semantics), cast back to varying for the scan carry.
        from distributed_tensorflow_tpu.ops.collectives import to_varying

        return tuple(
            to_varying(jax.lax.pmean(p, "data"), "data") for p in params
        )

    def local_epoch(state: FusedState, xs, ys):
        # Local view: state leaves [1, ...] (this chip's copy), xs/ys
        # [steps, batch, ...] (this chip's slice of each global batch).
        params = tuple(a[0] for a in state)
        xs = xs.astype(stream_dtype)
        ys = ys.astype(stream_dtype)

        def round_body(params, xy):
            # Exchange after every round (incl. an epoch-final one when the
            # count divides) — _scan_with_exchange's cadence exactly; the
            # remainder steps run after the last exchange, below.
            xr, yr = xy
            nw1, nb1, nw2, nb2, costs = call(xr, yr, *params)
            nw1, nb1, nw2, nb2 = _exchange((nw1, nb1, nw2, nb2))
            return (nw1, nb1, nw2, nb2), costs[:seg, 0]

        if use_rounds:
            params, costs = jax.lax.scan(
                round_body,
                params,
                (
                    xs[:head].reshape(rounds, seg, *xs.shape[1:]),
                    ys[:head].reshape(rounds, seg, *ys.shape[1:]),
                ),
            )
            costs = costs.reshape(head)
            if tail_call is not None:
                nw1, nb1, nw2, nb2, tail_costs = tail_call(
                    xs[head:], ys[head:], *params
                )
                params = (nw1, nb1, nw2, nb2)
                costs = jnp.concatenate([costs, tail_costs[: steps - head, 0]])
        else:
            nw1, nb1, nw2, nb2, costs = call(xs, ys, *params)
            params = (nw1, nb1, nw2, nb2)
            costs = costs[:steps, 0]

        new = FusedState(*(p[None] for p in params))
        return new, costs[:, None]  # [steps, 1] → global [steps, n]

    mapped = jax.shard_map(
        local_epoch,
        mesh=mesh,
        in_specs=(
            FusedState(P("data"), P("data"), P("data"), P("data")),
            P(None, "data"),
            P(None, "data"),
        ),
        out_specs=(
            FusedState(P("data"), P("data"), P("data"), P("data")),
            P(None, "data"),
        ),
        # pallas_call outputs carry no varying-mesh-axes metadata; the specs
        # above are the full contract.
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=0)
    def run(state: FusedState, xs: jax.Array, ys: jax.Array):
        state, costs = mapped(state, xs, ys)
        return state, jnp.mean(costs, axis=1)

    return run


def make_fused_compiled_run_fn(
    *,
    batch_size: int,
    epochs: int,
    in_dim: int = 784,
    hidden_dim: int = 100,
    out_dim: int = 10,
    learning_rate: float = 0.001,
    shuffle: bool = True,
    steps_per_epoch: int | None = None,
    stream_dtype: jnp.dtype = jnp.bfloat16,
    interpret: bool | None = None,
):
    """The whole-run compiled path (train/compiled_run.py's contract) with
    the inner per-epoch step scan replaced by the whole-epoch Pallas grid
    kernel: ``lax.scan`` over epochs, each iteration building its shuffled
    [steps, B, ...] staging by on-device gather and running it as ONE kernel
    launch with params VMEM-resident. Same observable surface —
    ``fn(state, train_x, train_y, test_x, test_y, key) -> (state, {"costs":
    [epochs, steps], "accuracy": [epochs]})`` with ``state`` a
    ``FusedState`` — at the grid kernel's per-step cost instead of the XLA
    scan's. This is how the Trainer API reaches bench.py's engine
    (round-1 gap: the fastest kernel existed only inside bench.py).

    ``train_x``/``train_y`` are full flat arrays, any float dtype; batches
    are gathered and streamed in ``stream_dtype`` (bf16 default: the batch
    read is the kernel's only per-step HBM traffic; update math stays f32).
    Eval runs in f32 jnp ops on the current params (same math as
    ``MLP(compute_dtype=f32).apply``).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    from distributed_tensorflow_tpu.train.compiled_run import wrapped_epoch_perm

    @partial(jax.jit, donate_argnums=0)
    def run(state: FusedState, train_x, train_y, test_x, test_y, key):
        steps = (
            train_x.shape[0] // batch_size
            if steps_per_epoch is None
            else steps_per_epoch
        )
        need = steps * batch_size
        domain = need if steps_per_epoch is None else train_x.shape[0]
        k = (need + domain - 1) // domain if need else 1
        call = _epoch_call(
            steps=steps,
            batch_size=batch_size,
            in_dim=in_dim,
            hidden_dim=hidden_dim,
            out_dim=out_dim,
            learning_rate=learning_rate,
            interpret=interpret,
        )
        fx = train_x.astype(stream_dtype)
        fy = train_y.astype(stream_dtype)
        tx = test_x.astype(jnp.float32)
        ty = test_y.astype(jnp.float32)

        def epoch_body(carry, _):
            (w1, b1, w2, b2), key = carry
            key, sub = jax.random.split(key)
            perm = wrapped_epoch_perm(
                sub, domain=domain, need=need, k=k, shuffle=shuffle
            )
            xs = jnp.take(fx, perm, axis=0).reshape(steps, batch_size, in_dim)
            ys = jnp.take(fy, perm, axis=0).reshape(steps, batch_size, out_dim)
            nw1, nb1, nw2, nb2, costs = call(xs, ys, w1, b1, w2, b2)
            # In-graph eval, f32 (the per-epoch Test-Accuracy line).
            h = jax.nn.sigmoid(
                jnp.dot(tx, nw1, preferred_element_type=jnp.float32) + nb1
            )
            logits = jnp.dot(h, nw2, preferred_element_type=jnp.float32) + nb2
            acc = jnp.mean(
                (jnp.argmax(logits, -1) == jnp.argmax(ty, -1)).astype(jnp.float32)
            )
            return ((nw1, nb1, nw2, nb2), key), (costs[:steps, 0], acc)

        (params, _), (costs, accs) = jax.lax.scan(
            epoch_body, (tuple(state), key), None, length=epochs
        )
        return FusedState(*params), {"costs": costs, "accuracy": accs}

    return run


def to_fused_stacked(params: MLPParams, n: int, sharding=None) -> FusedState:
    """Stack ``n`` identical per-chip copies of ``params`` (every reference
    worker starts from the same seed-1 graph) for the async-DP composition;
    ``sharding`` (e.g. ``NamedSharding(mesh, P("data"))``) places copy i on
    chip i."""
    base = to_fused(params)
    stacked = FusedState(
        *(jnp.broadcast_to(a[None], (n,) + a.shape) for a in base)
    )
    if sharding is not None:
        stacked = jax.device_put(stacked, sharding)
    return stacked
