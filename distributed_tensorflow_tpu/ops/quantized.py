"""Low-precision (int8 / fp8) matmul with a straight-through backward.

The reference squeezes throughput out of fixed hardware by restructuring
the training step — its whole experiment table is async-vs-sync modes ×
worker counts at fixed wall-clock (reference README.md:166-254, the
multi-ps × multi-worker benchmark grid; no reference analog exists at
the arithmetic level, TF1 ran f32 throughout). This module is the same
theme one layer down:
the v5e MXU's native low-precision regime is int8 (double the bf16
TOPS), and fp8 (e4m3) rides the same hardware path. ``quantized_dot``
computes the forward contraction in the reduced dtype with
full-precision accumulation and SYMMETRIC dynamic scales — per
activation ROW and per weight COLUMN, the standard dynamic-quantization
recipe, so one outlier row/column cannot crush everyone else's
resolution — while the backward is the exact full-precision matmul
transpose via a straight-through estimator: quantization noise perturbs
the forward only, and gradients flow as if the matmul were exact (the
standard quantized-training recipe; W8A8 dynamic, LLM.int8()/SmoothQuant
lineage). The consumer contract is ``GPTLM(matmul_dtype=)`` — opt-in,
guarded by the synthetic-corpus loss-parity test in
tests/test_quantized.py.

Scope note: this is a *dot wrapper*, not a Pallas kernel — XLA lowers an
int8×int8→int32 ``dot_general`` straight onto the MXU's int8 path on
TPU, so there is nothing for a custom kernel to add at these shapes; on
CPU (tests) the same graph runs through XLA's emulation bit-exactly.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

_EPS = 1e-12
# Largest representable magnitudes the scales map amax onto: int8's 127,
# float8_e4m3fn's largest normal 448.
_QMAX = {"int8": 127.0, "fp8": 448.0}

MATMUL_DTYPES = tuple(_QMAX)


def _amax_scale(x, axis, qmax):
    """Symmetric dynamic scale mapping max|x| over ``axis`` onto qmax
    (floored at eps so all-zero rows/columns quantize to zeros instead
    of NaNs)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    return jnp.maximum(amax, _EPS) / qmax


def _qdot_impl(dtype: str, x, w):
    if dtype not in _QMAX:
        raise ValueError(
            f"unknown matmul dtype {dtype!r}; one of {MATMUL_DTYPES}"
        )
    qmax = _QMAX[dtype]
    sx = _amax_scale(x, -1, qmax)  # [..., 1]   per activation row
    sw = _amax_scale(w, 0, qmax)  # [1, N]     per weight column
    xs = x.astype(jnp.float32) / sx
    ws = w.astype(jnp.float32) / sw
    if dtype == "int8":
        xq = jnp.clip(jnp.round(xs), -qmax, qmax).astype(jnp.int8)
        wq = jnp.clip(jnp.round(ws), -qmax, qmax).astype(jnp.int8)
        # int8×int8 → int32 accumulation: the MXU-native pass.
        acc = jnp.dot(
            xq, wq, preferred_element_type=jnp.int32
        ).astype(jnp.float32)
    else:  # fp8: cast carries rounding; e4m3 covers |x| <= 448 post-scale
        acc = jnp.dot(
            xs.astype(jnp.float8_e4m3fn),
            ws.astype(jnp.float8_e4m3fn),
            preferred_element_type=jnp.float32,
        )
    return acc * sx * sw  # dequantize: [..., 1] × [1, N] broadcast


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def quantized_dot(dtype: str, x, w):
    """``x [..., K] @ w [K, N]`` with the contraction in ``dtype``
    (``"int8"`` or ``"fp8"``), f32 result — dynamic symmetric scales per
    activation row and weight column. Differentiable via the
    straight-through estimator: both gradients are the exact f32 matmul
    transposes of the UNquantized operands (residuals x, w), so
    quantization error never enters the backward. Under GSPMD the scale
    reductions partition like the dot itself (a row-sharded weight's
    per-column amax becomes one all-reduce-max)."""
    return _qdot_impl(dtype, x, w)


def _qdot_fwd(dtype, x, w):
    return _qdot_impl(dtype, x, w), (x, w)


def _qdot_bwd(dtype, res, g):
    x, w = res
    gf = g.astype(jnp.float32)
    dx = jnp.dot(gf, w.astype(jnp.float32).T).astype(x.dtype)
    g2 = gf.reshape(-1, gf.shape[-1])
    x2 = x.astype(jnp.float32).reshape(-1, x.shape[-1])
    dw = jnp.dot(x2.T, g2).astype(w.dtype)
    return dx, dw


quantized_dot.defvjp(_qdot_fwd, _qdot_bwd)
