"""Low-precision (int8 / fp8) matmuls and KV-cache quantization.

The reference squeezes throughput out of fixed hardware by restructuring
the training step — its whole experiment table is async-vs-sync modes ×
worker counts at fixed wall-clock (reference README.md:166-254, the
multi-ps × multi-worker benchmark grid; no reference analog exists at
the arithmetic level, TF1 ran f32 throughout). This module is the same
theme one layer down:
the v5e MXU's native low-precision regime is int8 (double the bf16
TOPS), and fp8 (e4m3) rides the same hardware path. ``quantized_dot``
computes the forward contraction in the reduced dtype with
full-precision accumulation and SYMMETRIC dynamic scales — per
activation ROW and per weight COLUMN, the standard dynamic-quantization
recipe, so one outlier row/column cannot crush everyone else's
resolution — while the backward is the exact full-precision matmul
transpose via a straight-through estimator: quantization noise perturbs
the forward only, and gradients flow as if the matmul were exact (the
standard quantized-training recipe; W8A8 dynamic, LLM.int8()/SmoothQuant
lineage). The consumer contract is ``GPTLM(matmul_dtype=)`` — opt-in,
guarded by the synthetic-corpus loss-parity test in
tests/test_quantized.py.

Scope note: this is a *dot wrapper*, not a Pallas kernel — XLA lowers an
int8×int8→int32 ``dot_general`` straight onto the MXU's int8 path on
TPU, so there is nothing for a custom kernel to add at these shapes; on
CPU (tests) the same graph runs through XLA's emulation bit-exactly.

Round 15 adds the INFERENCE-side primitives (ISSUE 11 — decode is
HBM-traffic-bound, so serving bytes ≈ latency AND capacity):

- :func:`quantize_kv` / :func:`dequantize_kv` — symmetric per-ROW scales
  (one f32 scale per written cache position per KV head, amax over the
  head_dim lane; the write-local granularity, so a decode step's single
  token row never re-scales — and therefore never perturbs — previously
  written positions). Scales are SMALL SIDE TENSORS riding beside the
  cache (``head_dim × elem_bytes / 4`` smaller than the payload), never
  packed into the block — the paged pool's gather/scatter index math
  applies to them unchanged, and COW prefix sharing shares them with the
  block (``models/gpt.py`` cache structs, ``serve.py kv_dtype=``).
- :class:`QuantizedLinear` + :func:`quantize_linear_columns` +
  :func:`wo_dot` — weight-only quantization for the decode projections
  (AWQ/vLLM inference lineage): weights pre-quantized ONCE at restore
  with per-output-column symmetric scales, activations stay full
  precision, no STE — forward-only by construction
  (``GPTLM.decode_weights``). The claim is bandwidth, not FLOPs: decode
  reads every weight per token, so int8 weights halve the other half of
  decode's HBM traffic (TUNNEL-TPU claim until the chip rerun, like
  ``matmul_dtype``).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

_EPS = 1e-12
# Largest representable magnitudes the scales map amax onto: int8's 127,
# float8_e4m3fn's largest normal 448.
_QMAX = {"int8": 127.0, "fp8": 448.0}

MATMUL_DTYPES = tuple(_QMAX)


def _amax_scale(x, axis, qmax):
    """Symmetric dynamic scale mapping max|x| over ``axis`` onto qmax
    (floored at eps so all-zero rows/columns quantize to zeros instead
    of NaNs)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    return jnp.maximum(amax, _EPS) / qmax


def _quantize(xs, dtype: str, qmax: float):
    """The ONE symmetric quantize step (pre-scaled ``xs = x/scale`` →
    stored values): int8 rounds-and-clips, fp8 casts (the cast carries
    rounding; e4m3 covers |x| ≤ 448 post-scale). Shared by the training
    dot, the KV cache, and the weight-only path so their rounding
    semantics cannot drift apart."""
    if dtype == "int8":
        return jnp.clip(jnp.round(xs), -qmax, qmax).astype(jnp.int8)
    return xs.astype(jnp.float8_e4m3fn)


def _qdot_impl(dtype: str, x, w):
    if dtype not in _QMAX:
        raise ValueError(
            f"unknown matmul dtype {dtype!r}; one of {MATMUL_DTYPES}"
        )
    qmax = _QMAX[dtype]
    sx = _amax_scale(x, -1, qmax)  # [..., 1]   per activation row
    sw = _amax_scale(w, 0, qmax)  # [1, N]     per weight column
    xs = x.astype(jnp.float32) / sx
    ws = w.astype(jnp.float32) / sw
    xq = _quantize(xs, dtype, qmax)
    wq = _quantize(ws, dtype, qmax)
    if dtype == "int8":
        # int8×int8 → int32 accumulation: the MXU-native pass.
        acc = jnp.dot(
            xq, wq, preferred_element_type=jnp.int32
        ).astype(jnp.float32)
    else:
        acc = jnp.dot(xq, wq, preferred_element_type=jnp.float32)
    return acc * sx * sw  # dequantize: [..., 1] × [1, N] broadcast


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def quantized_dot(dtype: str, x, w):
    """``x [..., K] @ w [K, N]`` with the contraction in ``dtype``
    (``"int8"`` or ``"fp8"``), f32 result — dynamic symmetric scales per
    activation row and weight column. Differentiable via the
    straight-through estimator: both gradients are the exact f32 matmul
    transposes of the UNquantized operands (residuals x, w), so
    quantization error never enters the backward. Under GSPMD the scale
    reductions partition like the dot itself (a row-sharded weight's
    per-column amax becomes one all-reduce-max)."""
    return _qdot_impl(dtype, x, w)


def _qdot_fwd(dtype, x, w):
    return _qdot_impl(dtype, x, w), (x, w)


def _qdot_bwd(dtype, res, g):
    x, w = res
    gf = g.astype(jnp.float32)
    dx = jnp.dot(gf, w.astype(jnp.float32).T).astype(x.dtype)
    g2 = gf.reshape(-1, gf.shape[-1])
    x2 = x.astype(jnp.float32).reshape(-1, x.shape[-1])
    dw = jnp.dot(x2.T, g2).astype(w.dtype)
    return dx, dw


quantized_dot.defvjp(_qdot_fwd, _qdot_bwd)


# -- per-tensor delta compression (round 17) -------------------------------
# The coarsest scale granularity in the family: ONE symmetric f32 scale
# per tensor. Too coarse for weights/activations (a single outlier row
# crushes resolution — hence the per-row/per-column training scales
# above), but exactly right for the DiLoCo outer pseudo-gradient
# (train/local_sgd.py delta_dtype=): the payload crossing the gang's
# wire is a whole parameter tree whose per-tensor dynamic range is
# narrow, the scale overhead must stay negligible (4 bytes per TENSOR,
# not per row), and the error-feedback residual re-injects whatever the
# coarse scale loses.


def quantize_tensor(x, dtype: str):
    """Quantize a whole tensor symmetrically: ``x`` → ``(q, scale)`` with
    ONE f32 scale (amax over every element, floored at eps so an all-zero
    tensor quantizes to zeros). ``dtype`` is ``"int8"`` or ``"fp8"``;
    rounding semantics are the shared :func:`_quantize` step, so this
    cannot drift from the training dot or the KV cache."""
    if dtype not in _QMAX:
        raise ValueError(
            f"unknown tensor dtype {dtype!r}; one of {MATMUL_DTYPES}"
        )
    qmax = _QMAX[dtype]
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    scale = jnp.maximum(amax, _EPS) / qmax
    return _quantize(x.astype(jnp.float32) / scale, dtype, qmax), scale


def dequantize_tensor(q, scale, out_dtype=jnp.float32):
    """Inverse of :func:`quantize_tensor`: ``q × scale`` in
    ``out_dtype``."""
    return (q.astype(jnp.float32) * scale).astype(out_dtype)


# -- inference-side KV-cache quantization (round 15) -----------------------

# Serving cache dtypes: "bf16" is the identity layout (the cache stores
# the model's compute_dtype, scales absent — the round-11 bitwise path);
# int8/fp8 store 1-byte elements plus the per-row scale side tensor.
KV_DTYPES = ("bf16",) + MATMUL_DTYPES


def kv_storage_dtype(kv_dtype: str, compute_dtype):
    """The jnp dtype a ``kv_dtype`` cache stores its K/V payload in."""
    if kv_dtype == "bf16":
        return compute_dtype
    if kv_dtype == "int8":
        return jnp.int8
    if kv_dtype == "fp8":
        return jnp.float8_e4m3fn
    raise ValueError(f"unknown kv dtype {kv_dtype!r}; one of {KV_DTYPES}")


def kv_elem_bytes(kv_dtype: str, compute_dtype) -> int:
    """Bytes per stored K/V element (the serve_pool HBM accounting)."""
    return jnp.dtype(kv_storage_dtype(kv_dtype, compute_dtype)).itemsize


def quantize_kv(x, kv_dtype: str):
    """Quantize K or V rows ``[..., Dh]`` → ``(q [..., Dh], scale [...])``
    with one symmetric f32 scale per row (amax over the last axis — per
    cache position per KV head). Row granularity is what makes the
    serving cache write-local: a decode step quantizes exactly the rows
    it writes; nothing already resident is ever re-scaled. A row whose
    amax is a power of two holds an EXACTLY representable scale, so
    integer-valued ``x/scale`` round-trips bit-exactly (the equality
    oracle in tests/test_serve_quantized.py)."""
    qmax = _QMAX.get(kv_dtype)
    if qmax is None:
        raise ValueError(
            f"quantize_kv needs a quantized dtype, one of {MATMUL_DTYPES}; "
            f"got {kv_dtype!r}"
        )
    scale = _amax_scale(x, -1, qmax)  # [..., 1]
    q = _quantize(x.astype(jnp.float32) / scale, kv_dtype, qmax)
    return q, scale[..., 0]


def dequantize_kv(q, scale, out_dtype=jnp.float32):
    """Inverse of :func:`quantize_kv`: ``q [..., Dh]`` × ``scale [...]``
    → ``[..., Dh] out_dtype``. Works on any gathered view of the cache —
    the scale tensor is indexed by exactly the same (block, position,
    head) coordinates as the payload, minus the lane axis."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(out_dtype)


# -- weight-only decode matmuls (round 15) ---------------------------------


class QuantizedLinear(NamedTuple):
    """A pre-quantized projection weight: ``qw [..., K, N]`` int8/fp8 with
    per-output-column f32 ``scale [..., N]`` (symmetric — dequantization
    is ``qw · scale``, no zero point). Produced once at restore by
    :func:`quantize_linear_columns` / ``GPTLM.decode_weights``; consumed
    by :func:`wo_dot` wherever ``GPTLM._dot`` meets one. Leading axes
    (the scanned ``num_layers`` stack) ride through untouched."""

    qw: jax.Array
    scale: jax.Array


def quantize_linear_columns(w, dtype: str) -> QuantizedLinear:
    """Quantize a weight ``[..., K, N]`` with one symmetric scale per
    output column (amax over the contraction axis — the round-13
    ``quantized_dot`` weight-side granularity, so one outlier column
    cannot crush the rest)."""
    if dtype not in _QMAX:
        raise ValueError(
            f"unknown weight dtype {dtype!r}; one of {MATMUL_DTYPES}"
        )
    qmax = _QMAX[dtype]
    scale = _amax_scale(w, -2, qmax)  # [..., 1, N]
    q = _quantize(w.astype(jnp.float32) / scale, dtype, qmax)
    return QuantizedLinear(qw=q, scale=scale[..., 0, :])


def wo_dot(x, qw, scale, compute_dtype=jnp.bfloat16):
    """Weight-only quantized matmul: ``x [..., K]`` (full precision) @
    pre-quantized ``qw [K, N]`` with per-column ``scale [N]`` → f32.
    The contraction runs in ``compute_dtype`` (int8/fp8 upcast exactly —
    |q| ≤ 448 — so the only approximation is the one already committed
    at quantization time) and the column scales fold in AFTER the f32
    accumulation. Forward-only by design: this is an inference
    primitive; training keeps :func:`quantized_dot`'s STE."""
    acc = jnp.dot(
        x.astype(compute_dtype),
        qw.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )
    return acc * scale
