"""Loss and metric ops (component C9, SURVEY.md §2).

The reference's loss is the numerically naive
``reduce_mean(-reduce_sum(y_ * log(y), axis=1))`` over softmax outputs
(reference tfsingle.py:44-45) — no logits-based formulation. We reproduce that
behavior (same value on the same inputs) but guard the log for TPU: softmax
runs in float32 upstream and the log input is clamped away from zero, so bf16
underflow can't produce NaN (SURVEY.md §7 hard-part c).

Accuracy is mean(argmax(y) == argmax(y_)) (reference tfsingle.py:51-53).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_LOG_EPS = 1e-30  # clamp for the naive log; far below any float32 softmax output


def cross_entropy(probs: jax.Array, labels_one_hot: jax.Array) -> jax.Array:
    """The reference's naive CE over probabilities, NaN-guarded."""
    logp = jnp.log(jnp.maximum(probs.astype(jnp.float32), _LOG_EPS))
    return jnp.mean(-jnp.sum(labels_one_hot * logp, axis=-1))


def stable_cross_entropy(logits: jax.Array, labels_one_hot: jax.Array) -> jax.Array:
    """Logits-based CE (log-softmax) — the numerically sound variant offered
    alongside reference parity; identical gradient direction, better
    conditioning for large-scale runs."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.mean(-jnp.sum(labels_one_hot * logp, axis=-1))


def accuracy(probs_or_logits: jax.Array, labels_one_hot: jax.Array) -> jax.Array:
    pred = jnp.argmax(probs_or_logits, axis=-1)
    true = jnp.argmax(labels_one_hot, axis=-1)
    return jnp.mean((pred == true).astype(jnp.float32))
