"""Cluster topology + hyperparameter configuration.

Reference parity (component C1, SURVEY.md §2): the reference declares its
cluster as two host:port lists in ``settings.py:3-4``::

    ps_svrs     = [...]
    worker_svrs = [...]

This module keeps that exact configuration surface — a user of the reference
can drop in their ``settings.py`` unchanged — but resolves it TPU-natively:
the ``ps`` list is accepted and ignored (parameters are GSPMD-replicated on
chips; there is no parameter-server role), and the ``worker`` list defines the
set of *processes* (hosts) in a ``jax.distributed`` coordination group, i.e.
the process axis of the device mesh.

Hyperparameters mirror the reference's module constants
(batch_size=100, lr=0.001, epochs=100 — reference tfdist_between.py:19-21)
but are overridable per-run, and carry TPU-specific extras (dtype, mesh shape).
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Sequence


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Topology of a training job.

    ``ps_svrs`` is retained for drop-in compatibility with the reference's
    ``settings.py`` but plays no runtime role: the PS star is replaced by
    all-reduce over ICI (SURVEY.md §2a). ``worker_svrs`` host:port entries
    map 1:1 to ``jax.distributed`` processes; entry 0 is the coordinator
    (and the chief, matching the reference's ``is_chief=(task_index==0)``,
    reference tfdist_between.py:78).
    """

    worker_svrs: tuple[str, ...] = ()
    ps_svrs: tuple[str, ...] = ()  # accepted, ignored (no PS role on TPU)
    # -- failure detection (round 7: cluster-level so launch.run(cluster)
    # arms it without the caller pre-building a ProcessContext) ----------
    # UDP port of the native heartbeat detector (runtime/csrc). None
    # disables. By default the chief hosts the coordinator; when
    # heartbeat_host is set, the detector lives THERE instead (an elastic
    # agent out-of-band of the job — train/elastic.py) and every task,
    # chief included, is a plain sender to it.
    heartbeat_port: int | None = None
    heartbeat_timeout_ms: int = 10_000
    heartbeat_host: str | None = None
    # Bounded jax.distributed.initialize (cluster.bounded_initialize): a
    # restarting gang whose coordinator isn't up yet gets timeout + retry
    # with backoff instead of an indefinite hang. The per-attempt window
    # deliberately matches jax's own initialization_timeout default
    # (300 s): a slow-assembling pod that worked under the raw call keeps
    # working; tighten it for fast local gangs where 300 s per attempt is
    # an eternity.
    connect_timeout_s: int = 300
    connect_attempts: int = 3

    @property
    def num_processes(self) -> int:
        return max(1, len(self.worker_svrs))

    @property
    def coordinator_address(self) -> str | None:
        return self.worker_svrs[0] if self.worker_svrs else None

    def is_chief(self, task_index: int) -> bool:
        return task_index == 0

    @classmethod
    def from_settings_module(cls, module: Any | str = "settings") -> "ClusterConfig":
        """Load a reference-style ``settings.py`` (C1 parity)."""
        if isinstance(module, str):
            module = importlib.import_module(module)
        return cls(
            worker_svrs=tuple(getattr(module, "worker_svrs", ())),
            ps_svrs=tuple(getattr(module, "ps_svrs", ())),
        )

    @classmethod
    def from_lists(
        cls, worker_svrs: Sequence[str], ps_svrs: Sequence[str] = ()
    ) -> "ClusterConfig":
        return cls(worker_svrs=tuple(worker_svrs), ps_svrs=tuple(ps_svrs))

    def subset(self, ranks: Sequence[int]) -> "ClusterConfig":
        """The surviving sub-cluster after an elastic resize
        (train/elastic.py, round 8): new rank ``r`` is served by the host
        that held original rank ``ranks[r]``, and ``ranks[0]``'s address
        becomes the coordinator. The full ``worker_svrs`` list stays the
        roster of POTENTIAL hosts (a regrown gang selects a superset);
        everything else (heartbeat, bootstrap bounds) carries over."""
        ranks = tuple(int(r) for r in ranks)
        if not ranks:
            raise ValueError("subset needs at least one rank")
        if len(set(ranks)) != len(ranks):
            raise ValueError(f"subset ranks must be unique, got {ranks}")
        bad = [r for r in ranks if not 0 <= r < len(self.worker_svrs)]
        if bad:
            raise ValueError(
                f"subset ranks {bad} out of range for "
                f"{len(self.worker_svrs)} worker_svrs entries"
            )
        return dataclasses.replace(
            self, worker_svrs=tuple(self.worker_svrs[r] for r in ranks)
        )


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Hyperparameters. Defaults reproduce the reference exactly
    (reference tfsingle.py:8-10, tfdist_between.py:19-21) plus TPU knobs."""

    batch_size: int = 100
    learning_rate: float = 0.001
    epochs: int = 100
    log_frequency: int = 100  # print every N batches (reference `freq`, :81)
    seed: int = 1  # reference tf.set_random_seed(1), tfsingle.py:17

    # TPU-first knobs (no reference analog)
    compute_dtype: str = "bfloat16"  # MXU-friendly activations dtype
    param_dtype: str = "float32"
    # Model family from the registry (models/__init__.py): mlp (reference
    # parity) | cnn | lstm | transformer. The reference picked its model by
    # picking which script to run; here it is one config knob.
    model: str = "mlp"
    # Optimizer surface (ops/optim.py). Defaults reproduce the reference's
    # constant-lr SGD exactly; everything else is framework surface.
    optimizer: str = "sgd"  # sgd | momentum | adam | adamw
    lr_schedule: str | None = None  # None/constant | cosine | linear | exponential
    warmup_steps: int = 0  # linear lr ramp before the schedule
    # Average grads over N micro-steps, apply once. Note: global_step counts
    # micro-steps (one per train_step call), not applies, when N > 1.
    accumulate_steps: int = 1
    # Global-norm gradient clipping; 0 disables (reference parity — the
    # reference's naive loss has no gradient guard and can diverge).
    grad_clip_norm: float = 0.0
    # Rematerialization (jax.checkpoint on the model forward): recompute
    # activations in the backward pass instead of storing them — trades MXU
    # FLOPs for HBM activation memory. Gradients unchanged. The LM family
    # additionally accepts "selective" (round 13): a Pallas-aware
    # jax.checkpoint policy that SAVES the flash-attention out+lse
    # (cheap, O(B·L·d)) and recomputes only the layernorm/QKV/MLP half of
    # each block — grad-identical to True, reaches every dp_mode through
    # LMTrainer. Wins on MXU-sized rows where the recompute third is
    # mostly attention (docs/benchmarks/lm_phases.md); keep plain True at
    # toy widths. The classifier path treats any truthy value as plain
    # remat (its models have no selective policy surface).
    remat: bool | str = False
    # Opt-in low-precision projection matmuls for the LM family
    # (models/gpt.GPTLM(matmul_dtype=), ops/quantized.py): None | "int8"
    # | "fp8". int8 is the v5e MXU's native double-rate regime; forward
    # quantized with dynamic symmetric scales, backward straight-through
    # at full precision, loss-parity-guarded (tests/test_quantized.py).
    # The classifier path rejects it (no quantized surface there).
    matmul_dtype: str | None = None
    # "naive" = reference parity (CE over softmax probabilities, NaN-guarded,
    # reference tfsingle.py:44-45); "stable" = logits-based log-softmax CE.
    loss: str = "naive"
    logs_path: str = "./logs"  # reference logs_path, tfdist_between.py:22
    checkpoint_dir: str | None = None  # deliberate upgrade: orbax checkpointing
    # -- resilience layer (train/resilience.py; no reference analog — the
    # reference configured no saver at all, SURVEY.md §5) -----------------
    # Checkpoint retention: keep the newest N step_N checkpoints, GC the
    # rest after each save (the newest VALID one is never GC'd). None/0
    # keeps everything (the old behavior).
    keep_last_n: int | None = None
    # Bounded retry-with-backoff around checkpoint save/restore I/O.
    checkpoint_retries: int = 3
    checkpoint_retry_backoff: float = 0.25
    # Async checkpoint pipeline (round 22, train/resilience.py
    # AsyncCheckpointWriter): the save boundary pays only the device→host
    # snapshot; serialize + CRC + manifest + retention GC run on a
    # bounded background writer through the SAME write sequence, so the
    # artifacts are byte-identical to the synchronous path (test-pinned)
    # and a crash mid-async-write is indistinguishable from today's torn
    # write (newest→oldest fallback covers both). At most one write in
    # flight; a newer snapshot supersedes a queued one; trainers drain at
    # run() exit and before every restore. False = the round-6
    # synchronous path, kept as the escape hatch.
    async_checkpoint: bool = True
    # Preemption contract: run() installs a SIGTERM/SIGINT handler that
    # flips Supervisor.request_stop, so the loop exits at the next epoch/
    # dispatch boundary with a final save (TPU-pod preemption semantics).
    # Only active when a supervisor exists and run() is on the main thread.
    handle_preemption: bool = True
    # Anomaly guard (PaLM-style spike/NaN rollback): watch per-epoch cost;
    # on NaN/inf — or a spike beyond spike_threshold x the median of the
    # trailing anomaly_window good epochs — restore the last valid
    # checkpoint, keep the (already advanced) host data stream so the
    # offending window is skipped, and retry, at most max_rollbacks times
    # per run. max_rollbacks=0 disables the guard; spike_threshold=0
    # keeps only the NaN/inf check.
    max_rollbacks: int = 0
    anomaly_window: int = 8
    spike_threshold: float = 3.0
    # Elastic gang-restart budget (train/elastic.py): how many times the
    # supervising agent may kill + rendezvous + relaunch the gang after a
    # worker dies or stalls, with exponential backoff between attempts.
    # 0 (default) preserves fail-stop: the first failure ends the job.
    # Consumed OUTSIDE the trainer (the agent supervises the process): the
    # elastic driver reads it via DTF_MAX_RESTARTS (tools/launch_local's
    # --max-restarts default); this knob keeps config_from_env's surface
    # the single source of truth for config-driven deployments.
    max_restarts: int = 0
    # A worker whose heartbeat keeps arriving but whose progress counter
    # has not moved for this long is classified STALLED and recovered the
    # same way as a dead one (a rank hung in a collective beats forever —
    # silence timeouts alone never fire). 0 disables stall detection.
    # Size it above the worst-case epoch + first-compile latency.
    stall_timeout_ms: int = 0
    # Shrink-to-fit floor (round 8, train/elastic.py): when a gang member
    # dies and no replacement registers within rejoin_timeout_s, the
    # elastic agent relaunches only the survivors at the reduced world
    # size — down to this floor; below it the gang fail-stops (round 6
    # semantics). 0 (default) disables resizing entirely: round 7's
    # fixed-size gang restart. Like max_restarts, consumed OUTSIDE the
    # trainer by the elastic driver (DTF_MIN_WORKERS →
    # tools/launch_local --min-workers); kept on TrainConfig so
    # config_from_env stays the single config surface.
    min_workers: int = 0
    # How long a failed member's slot may stay vacant before the gang
    # gives up on a replacement and resizes without it (only meaningful
    # with min_workers > 0). 0 decides from one availability probe.
    rejoin_timeout_s: float = 30.0
    sync: bool = True  # sync DP (pmean all-reduce) vs async emulation
    async_avg_every: int = 0  # async mode: average params every N steps (0 = never)
    # -- local-SGD / DiLoCo outer loop (train/local_sgd.py; LM family,
    # dp_mode="diloco") — the paper's async thesis in its modern
    # communication-reducing form: each worker runs sync_every = H inner
    # steps with the inner optimizer, then the gang applies ONE outer
    # update from the pseudo-gradient Δ = θ_start − mean_w(θ_w) through
    # Nesterov momentum — H× fewer all-reduce rounds per token than sync
    # dp. The DEFAULTS are the paper-parity convention, momentum-free:
    # outer_lr=None resolves to N (the worker count), the same
    # update_scale=N sequential-apply semantics as the async modes, and
    # outer_momentum=0 keeps that step un-compounded (N× PLUS momentum
    # is a regime no reference sanctions and it measurably overshoots).
    # DiLoCo-paper settings are the explicit opt-in: sync_every>=8,
    # outer_lr≈0.7-1.0, outer_momentum=0.9 — what the convergence
    # record (docs/benchmarks/diloco.md) uses. outer_momentum=0 +
    # outer_lr=1 + sync_every=1 degenerates to the per-step parameter
    # mean (the sync-dp anchor, test-pinned).
    sync_every: int = 1
    outer_lr: float | None = None
    outer_momentum: float = 0.0
    # Mesh-free diloco gang width: with dp_mode="diloco" and NO mesh, the
    # LMTrainer runs the SAME gang as one vmapped single-device program
    # over this many emulated workers (the bench/degraded-container
    # engine — tools/diloco_bench.py; mathematically the mesh gang with
    # parallel execution replaced by vectorization). 0 (default) means
    # dp_mode="diloco" requires a mesh.
    diloco_workers: int = 0
    # -- streaming/compressed DiLoCo levers (round 17, train/local_sgd.py;
    # all default-off: the round-14 outer loop stays bitwise) ------------
    # Quantize the outer pseudo-gradient Δ = θ_start − mean_w(θ_w) before
    # it crosses the wire: None (full precision) | "int8" | "fp8" —
    # per-TENSOR symmetric scales (ops/quantized.quantize_tensor) with an
    # error-feedback residual carried in DiLoCoState, so compression
    # error is re-injected into the next round's delta instead of lost.
    # ~4× fewer comm bytes per round on top of the H× round reduction
    # (one byte per element + one f32 scale per tensor).
    delta_dtype: str | None = None
    # Streaming-DiLoCo overlap: the outer delta computed at a round
    # boundary is treated as IN FLIGHT during the next H inner steps and
    # the completed outer update applies one round late — in a real gang
    # the all-reduce has the whole next round of compute to hide behind
    # (the layer-wise partition schedule lives in
    # local_sgd.streaming_schedule). The in-flight delta rides
    # DiLoCoState (world-invariant, resize-safe like θ_start/momentum).
    delta_overlap: bool = False
    # Stale-tolerant gang (LMTrainer delta_exchange=, the host-mailbox
    # outer exchange): a member that misses a round boundary contributes
    # its delta at the next one with a staleness-discounted weight
    # (1/(1+age), local_sgd.staleness_weight) instead of stalling the
    # round; deltas older than this many rounds are dropped entirely.
    # 0 = only same-round deltas participate.
    stale_limit: int = 0
    # Sync parameter layout: "replicated" (params on every chip, gradient
    # all-reduce — the reference-parity mode) or "zero" (ZeRO-3/FSDP: params
    # and optimizer state sharded over 'data', all-gather fwd/bwd +
    # reduce-scatter grads — parallel/fsdp.py); identical update semantics.
    # The LM trainer additionally accepts "tp" (Megatron tensor parallel,
    # composes with a data axis → dp×tp), "ep" (expert parallel, MoE
    # models, → dp×ep), "pp" (GPipe pipeline, → dp×pp), and "sp"
    # (sequence parallel over the causal ring / Ulysses, → dp×sp) — see
    # train/lm_trainer.py; the classifier path rejects these.
    dp_mode: str = "replicated"
    # Compile each epoch as one lax.scan dispatch (train/scan.py): identical
    # update semantics, ~100x less host overhead. Log lines are emitted from
    # the returned per-step costs after the dispatch. Supported by the
    # single-device, sync-DP (GSPMD), and async strategies. None (default)
    # resolves by backend: True on accelerators (where per-batch dispatches
    # pay the device-link latency 550x per epoch), False on CPU — set an
    # explicit bool to override.
    scan_epoch: bool | None = None
    # Compile the WHOLE run — every epoch, on-device shuffle, and per-epoch
    # test eval — into one dispatch (train/compiled_run.py). Same observable
    # surface as the eager loop; the shuffle moves from host numpy to the
    # on-device PRNG (distributionally equivalent). Wins whenever dispatch
    # latency matters. Supported by the single-device, sync-DP (GSPMD), and
    # async strategies (the async variant compiles every chip's local
    # stream, the exchanges, and the mean-params evals into the program).
    compiled_run: bool = False
    # Whole-run engine for compiled_run. "xla" (default): the generic
    # train/compiled_run.py program, any model/optimizer/strategy. "pallas":
    # the whole-epoch Pallas grid kernel inside the epoch scan
    # (ops/pallas_mlp.py make_fused_compiled_run_fn) — bench.py's fastest
    # engine behind the Trainer API; requires the reference workload shape
    # (MLP + plain sgd + naive loss + SingleDevice) and raises otherwise.
    engine: str = "xla"
    # Middle tier between the per-epoch scanned path and the all-or-nothing
    # compiled_run (round 5): run() dispatches k epochs at a time through
    # the whole-run compiled program (in-graph per-epoch eval), prints the
    # same per-epoch lines from the fetched k-epoch history, and
    # checkpoints + honors should_stop BETWEEN dispatches — the documented
    # lifecycle API at near-compiled_run throughput, with a bounded
    # resume/stop granularity of k epochs instead of the whole run.
    # None/0 disables. Ignored when compiled_run=True (strictly coarser).
    # Picking k: per-epoch cost is t + C/k (t = whole-run compute, C = the
    # per-dispatch fixed cost — benchmark_suite's `single-k*` sweep fits
    # both; docs/benchmarks/tpu_single.md), so choose the smallest k with
    # C/(k·t) at your tolerable overhead — on the tunneled v5e that knee
    # sits around k≈25-50, and smaller k buys nothing but a finer
    # checkpoint/stop boundary.
    epochs_per_dispatch: int | None = None
    # Keep N device-placed batches in flight in the eager per-batch loop
    # (data/prefetch.py): batch i+1's host→device transfer overlaps step i's
    # compute. 0 disables (reference-parity synchronous feed).
    prefetch: int = 0
    profile_dir: str | None = None  # capture a jax.profiler trace of epoch 0
    # Print each parameter's sharding at startup — the TPU analog of the
    # reference's log_device_placement=True (C4, tfdist_between.py:15).
    log_placement: bool = False
    # Epoch definition. False (default): one pass over the data per epoch
    # globally (modern convention; N replicas split the 550 batches). True:
    # the reference's convention — EACH worker runs num_examples/batch_size
    # steps per epoch (reference tfdist_between.py:87), so N sync replicas
    # make 550 aggregated applies/epoch at effective batch N*100, which is
    # what makes the reference's sync accuracy equal single-device at equal
    # epochs (README.md:148-150).
    per_worker_epoch: bool = False

    def __post_init__(self):
        # Fail fast at construction: None/0 disables the middle tier; a
        # negative value would otherwise reach run() and loop forever.
        if self.epochs_per_dispatch is not None and self.epochs_per_dispatch < 0:
            raise ValueError(
                "epochs_per_dispatch must be >= 1 (or None/0 to disable), "
                f"got {self.epochs_per_dispatch}"
            )
        if self.max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be >= 0 (0 disables), got {self.max_rollbacks}"
            )
        if not (
            isinstance(self.remat, bool) or self.remat == "selective"
        ):
            raise ValueError(
                f"remat must be False, True, or 'selective'; got "
                f"{self.remat!r} (callable policies go directly on the "
                "model: GPTLM(remat=policy))"
            )
        if self.matmul_dtype not in (None, "int8", "fp8"):
            raise ValueError(
                f"matmul_dtype must be None, 'int8', or 'fp8'; got "
                f"{self.matmul_dtype!r}"
            )
        if self.keep_last_n is not None and self.keep_last_n < 0:
            raise ValueError(
                "keep_last_n must be >= 1 (or None/0 to keep everything), "
                f"got {self.keep_last_n}"
            )
        if self.anomaly_window < 1:
            raise ValueError(
                f"anomaly_window must be >= 1, got {self.anomaly_window}"
            )
        if self.max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0 (0 = fail-stop), got {self.max_restarts}"
            )
        if self.stall_timeout_ms < 0:
            raise ValueError(
                f"stall_timeout_ms must be >= 0 (0 disables), got {self.stall_timeout_ms}"
            )
        if self.min_workers < 0:
            raise ValueError(
                f"min_workers must be >= 0 (0 disables resizing), "
                f"got {self.min_workers}"
            )
        if self.rejoin_timeout_s < 0:
            raise ValueError(
                f"rejoin_timeout_s must be >= 0, got {self.rejoin_timeout_s}"
            )
        if self.sync_every < 1:
            raise ValueError(
                f"sync_every must be >= 1 (1 = exchange every step), "
                f"got {self.sync_every}"
            )
        if self.outer_lr is not None and not self.outer_lr > 0:
            raise ValueError(
                f"outer_lr must be > 0 (or None for the worker-count "
                f"default), got {self.outer_lr}"
            )
        if not 0 <= self.outer_momentum < 1:
            raise ValueError(
                f"outer_momentum must be in [0, 1), got {self.outer_momentum}"
            )
        if self.diloco_workers < 0:
            raise ValueError(
                f"diloco_workers must be >= 0 (0 = diloco needs a mesh), "
                f"got {self.diloco_workers}"
            )
        if self.delta_dtype not in (None, "int8", "fp8"):
            raise ValueError(
                f"delta_dtype must be None, 'int8', or 'fp8'; got "
                f"{self.delta_dtype!r}"
            )
        if self.stale_limit < 0:
            raise ValueError(
                f"stale_limit must be >= 0 (0 = same-round deltas only), "
                f"got {self.stale_limit}"
            )
        if (
            self.delta_dtype or self.delta_overlap or self.stale_limit
        ) and self.dp_mode != "diloco":
            # Loud-failure contract (launch.py config_from_env): a
            # scheduler exporting DTF_DELTA_DTYPE/DTF_STALE_LIMIT at a
            # non-diloco job must fail the launch, not silently train
            # full-precision/sync with the lever ignored.
            raise ValueError(
                "delta_dtype/delta_overlap/stale_limit are diloco "
                "outer-loop levers (train/local_sgd.py) and would be "
                f"silently ignored under dp_mode={self.dp_mode!r}; set "
                "dp_mode='diloco'"
            )

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)
