"""MNIST data pipeline (component C6, SURVEY.md §2).

The reference calls the TF tutorial loader
``input_data.read_data_sets("MNIST_data", one_hot=True)`` (reference
tfsingle.py:13-14) and consumes two surfaces: ``mnist.train.next_batch(100)``
in the hot loop and the full ``mnist.test.images/labels`` split for per-epoch
eval (reference tfsingle.py:77,94). This module reproduces that exact API.

Sources, in priority order:

1. **Real MNIST IDX files** if present in ``data_dir`` (the standard
   ``train-images-idx3-ubyte[.gz]`` quartet). Parsed natively — by the C++
   loader in ``runtime/`` when built, else by the pure-numpy parser here.
   No downloading: this environment has zero egress.
2. **Deterministic synthetic MNIST** with identical shapes/splits
   (55000/5000/10000, 784 features in [0,1], 10 one-hot classes). Generated
   from a fixed PRNG: each class has a smooth random prototype; samples are
   spatially-jittered, brightness-scaled, noisy copies. Learnable by the
   reference's 2-layer MLP well past the 0.72 convergence oracle
   (SURVEY.md §4), so the oracle tests run anywhere.

Batching semantics match the tutorial loader: ``next_batch`` walks a
shuffled permutation and reshuffles at each epoch boundary.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct

import numpy as np

NUM_CLASSES = 10
IMAGE_SIZE = 28
IMAGE_PIXELS = IMAGE_SIZE * IMAGE_SIZE

_TRAIN_IMAGES = "train-images-idx3-ubyte"
_TRAIN_LABELS = "train-labels-idx1-ubyte"
_TEST_IMAGES = "t10k-images-idx3-ubyte"
_TEST_LABELS = "t10k-labels-idx1-ubyte"
_VALIDATION_SIZE = 5000  # tutorial loader's split: 55000 train / 5000 val


_native_gather = None  # resolved on first use: fn | False


def _gather(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Row gather for ``next_batch`` — through the C++ runtime's memcpy
    kernel when available (the host side of the reference's feed path,
    C6/SURVEY.md §2a), else numpy fancy indexing. Bit-identical either way."""
    global _native_gather
    if _native_gather is None:
        try:
            from distributed_tensorflow_tpu.runtime import native

            _native_gather = native.gather_rows if native.available() else False
        except Exception:  # pragma: no cover - import breakage → numpy path
            _native_gather = False
    if (
        _native_gather
        and src.ndim == 2
        and src.dtype == np.float32
        and src.flags.c_contiguous
    ):
        return _native_gather(src, idx)
    return src[idx]


def _one_hot(labels: np.ndarray, num_classes: int = NUM_CLASSES) -> np.ndarray:
    out = np.zeros((labels.shape[0], num_classes), dtype=np.float32)
    out[np.arange(labels.shape[0]), labels] = 1.0
    return out


class DataSet:
    """One split with the tutorial loader's ``next_batch`` iteration contract."""

    def __init__(self, images: np.ndarray, labels: np.ndarray, *, seed: int = 0):
        assert images.shape[0] == labels.shape[0]
        self._images = images
        self._labels = labels
        self._rng = np.random.default_rng(seed)
        self._perm = self._rng.permutation(images.shape[0])
        self._index = 0
        self._epochs_completed = 0

    @property
    def images(self) -> np.ndarray:
        return self._images

    @property
    def labels(self) -> np.ndarray:
        return self._labels

    @property
    def num_examples(self) -> int:
        return self._images.shape[0]

    @property
    def epochs_completed(self) -> int:
        return self._epochs_completed

    def next_batch(self, batch_size: int) -> tuple[np.ndarray, np.ndarray]:
        """Next ``batch_size`` examples. Tutorial-loader semantics: when the
        epoch's permutation runs out mid-batch, the leftover tail is served
        concatenated with the head of the next epoch's shuffle — no example
        is ever dropped."""
        if self._index + batch_size > self.num_examples:
            rest = self._perm[self._index :]
            self._epochs_completed += 1
            self._perm = self._rng.permutation(self.num_examples)
            take = batch_size - rest.shape[0]
            idx = np.concatenate([rest, self._perm[:take]])
            self._index = take
        else:
            idx = self._perm[self._index : self._index + batch_size]
            self._index += batch_size
        return _gather(self._images, idx), _gather(self._labels, idx)

    def shard(self, num_shards: int, shard_index: int) -> "DataSet":
        """Static contiguous shard of this split — the data-parallel analog of
        the reference's per-worker independent batch streams."""
        n = self.num_examples // num_shards
        lo = shard_index * n
        return DataSet(
            self._images[lo : lo + n],
            self._labels[lo : lo + n],
            seed=1000 + shard_index,
        )


@dataclasses.dataclass(frozen=True)
class Datasets:
    train: DataSet
    validation: DataSet
    test: DataSet


# ---------------------------------------------------------------------------
# Source 1: real MNIST IDX files
# ---------------------------------------------------------------------------


def _open_maybe_gz(path: str):
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    return open(path, "rb")


def _read_idx_images(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"bad IDX image magic {magic} in {path}")
        data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
    # Multiply by the f32-rounded reciprocal (not divide by 255.0): the C++
    # parser does `buf[i] * (1.0f/255.0f)`, and the two paths must produce
    # bit-identical arrays (tests/test_data.py parser-agreement check).
    return data.reshape(n, rows * cols).astype(np.float32) * np.float32(1.0 / 255.0)


def _read_idx_labels(path: str) -> np.ndarray:
    with _open_maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"bad IDX label magic {magic} in {path}")
        data = np.frombuffer(f.read(n), dtype=np.uint8)
    return data.astype(np.int64)


def _idx_files_present(data_dir: str) -> bool:
    return all(
        os.path.exists(os.path.join(data_dir, name))
        or os.path.exists(os.path.join(data_dir, name + ".gz"))
        for name in (_TRAIN_IMAGES, _TRAIN_LABELS, _TEST_IMAGES, _TEST_LABELS)
    )


def _load_idx(data_dir: str):
    train_x = _read_idx_images(os.path.join(data_dir, _TRAIN_IMAGES))
    train_y = _read_idx_labels(os.path.join(data_dir, _TRAIN_LABELS))
    test_x = _read_idx_images(os.path.join(data_dir, _TEST_IMAGES))
    test_y = _read_idx_labels(os.path.join(data_dir, _TEST_LABELS))
    return train_x, train_y, test_x, test_y


# ---------------------------------------------------------------------------
# Source 2: deterministic synthetic MNIST
# ---------------------------------------------------------------------------


def _smooth(field: np.ndarray, passes: int = 2) -> np.ndarray:
    """Cheap separable box blur to turn white noise into digit-like blobs."""
    for _ in range(passes):
        field = (
            field
            + np.roll(field, 1, -1)
            + np.roll(field, -1, -1)
            + np.roll(field, 1, -2)
            + np.roll(field, -1, -2)
        ) / 5.0
    return field


def _synthetic_split(
    n: int, rng: np.random.Generator, prototypes: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    labels = rng.integers(0, NUM_CLASSES, size=n)
    protos = prototypes[labels]  # [n, 28, 28]
    # Per-sample spatial jitter (±3 px) — vectorized via index arithmetic.
    dx = rng.integers(-3, 4, size=n)
    dy = rng.integers(-3, 4, size=n)
    rows = (np.arange(IMAGE_SIZE)[None, :, None] + dy[:, None, None]) % IMAGE_SIZE
    cols = (np.arange(IMAGE_SIZE)[None, None, :] + dx[:, None, None]) % IMAGE_SIZE
    imgs = protos[np.arange(n)[:, None, None], rows, cols]
    brightness = rng.uniform(0.7, 1.3, size=(n, 1, 1))
    noise = rng.normal(0.0, 0.15, size=imgs.shape)
    imgs = np.clip(imgs * brightness + noise, 0.0, 1.0).astype(np.float32)
    return imgs.reshape(n, IMAGE_PIXELS), labels


def _load_synthetic(seed: int = 0):
    rng = np.random.default_rng(seed)
    raw = rng.random((NUM_CLASSES, IMAGE_SIZE, IMAGE_SIZE))
    prototypes = _smooth(raw, passes=3)
    # Normalize each prototype to [0, 1] with a dark background like MNIST.
    prototypes -= prototypes.min(axis=(1, 2), keepdims=True)
    prototypes /= prototypes.max(axis=(1, 2), keepdims=True)
    prototypes = np.where(prototypes > 0.55, prototypes, 0.0)
    train_x, train_y = _synthetic_split(60000, rng, prototypes)
    test_x, test_y = _synthetic_split(10000, rng, prototypes)
    return train_x, train_y, test_x, test_y


# ---------------------------------------------------------------------------
# Public entry point (API parity with the tutorial loader)
# ---------------------------------------------------------------------------


def read_data_sets(
    data_dir: str = "MNIST_data",
    one_hot: bool = True,
    *,
    seed: int = 0,
    synthetic: bool | None = None,
) -> Datasets:
    """Load MNIST with the reference's loader API (reference tfsingle.py:13-14).

    ``synthetic=None`` auto-detects: real IDX files in ``data_dir`` win,
    otherwise the deterministic synthetic dataset is generated in-memory.
    """
    if synthetic is None:
        synthetic = not _idx_files_present(data_dir)
    if synthetic:
        train_x, train_y, test_x, test_y = _load_synthetic(seed)
    else:
        try:
            from distributed_tensorflow_tpu.runtime import native_loader

            train_x, train_y, test_x, test_y = native_loader.load_idx_dir(data_dir)
        except (ImportError, OSError):
            train_x, train_y, test_x, test_y = _load_idx(data_dir)

    if one_hot:
        train_yy: np.ndarray = _one_hot(train_y)
        test_yy: np.ndarray = _one_hot(test_y)
    else:
        train_yy, test_yy = train_y, test_y

    val_x, val_y = train_x[:_VALIDATION_SIZE], train_yy[:_VALIDATION_SIZE]
    trn_x, trn_y = train_x[_VALIDATION_SIZE:], train_yy[_VALIDATION_SIZE:]
    return Datasets(
        train=DataSet(trn_x, trn_y, seed=seed + 1),
        validation=DataSet(val_x, val_y, seed=seed + 2),
        test=DataSet(test_x, test_yy, seed=seed + 3),
    )
