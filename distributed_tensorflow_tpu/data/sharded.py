"""Multi-host batch assembly.

On a multi-process mesh each process holds only its own slice of the batch
(the reference's per-worker ``next_batch`` streams, tfdist_between.py:91) —
but jit'd computations consume *global* arrays. This module assembles global
device arrays from process-local numpy data via
``jax.make_array_from_process_local_data``, the TPU-native replacement for
feeding per-worker ``feed_dict``s against a shared PS graph.

Single-process meshes degrade to a plain ``device_put`` — the same call
works in both worlds, so training code is topology-agnostic.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def global_batch(
    mesh: Mesh, local_x: np.ndarray, local_y: np.ndarray, axis: str = "data"
):
    """Assemble (x, y) global arrays batch-sharded over ``axis`` from this
    process's local rows. Every process must contribute the same local row
    count; the global batch is the sum."""
    sharding = NamedSharding(mesh, P(axis))
    n_proc = jax.process_count()
    gx = (local_x.shape[0] * n_proc,) + local_x.shape[1:]
    gy = (local_y.shape[0] * n_proc,) + local_y.shape[1:]
    if n_proc == 1:
        return (
            jax.device_put(local_x, sharding),
            jax.device_put(local_y, sharding),
        )
    return (
        jax.make_array_from_process_local_data(sharding, local_x, gx),
        jax.make_array_from_process_local_data(sharding, local_y, gy),
    )


def local_shard_for_process(dataset, mesh=None) -> "object":
    """This process's static shard of a DataSet (data/mnist.py) — the
    multi-host analog of the reference's independent per-worker batch
    streams. Returns the dataset unchanged for single-process runs."""
    n = jax.process_count()
    if n == 1:
        return dataset
    return dataset.shard(n, jax.process_index())
