from distributed_tensorflow_tpu.data.mnist import (  # noqa: F401
    DataSet,
    Datasets,
    read_data_sets,
)
