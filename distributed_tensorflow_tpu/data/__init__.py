from distributed_tensorflow_tpu.data.mnist import (  # noqa: F401
    DataSet,
    Datasets,
    read_data_sets,
)
from distributed_tensorflow_tpu.data.tokens import (  # noqa: F401
    TokenDataset,
    TokenDatasets,
    copy_corpus,
    markov_corpus,
)
from distributed_tensorflow_tpu.data.text import (  # noqa: F401
    BPETokenizer,
    ByteTokenizer,
    pack_documents,
    synthetic_documents,
    text_corpus,
)
