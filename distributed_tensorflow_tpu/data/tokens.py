"""Token data pipeline for the LM family (the C6 contract over sequences).

The reference's data layer is the MNIST tutorial loader with two surfaces —
``next_batch`` in the hot loop and a full held-out split for per-epoch eval
(reference tfsingle.py:13-14,77,94; component C6, SURVEY.md §2). The LM
family needs the same contract over token sequences, so this module
reproduces it: a :class:`TokenDataset` with identical shuffled-permutation /
tail-carry ``next_batch`` semantics (data/mnist.py:105-120), grouped into
train/validation/test :class:`TokenDatasets` splits.

Corpora (zero egress — deterministic synthetic, same philosophy as the
synthetic MNIST):

- :func:`copy_corpus` — sequences ``x · x``: the model must attend back and
  reproduce the first half. Learnability has a sharp observable signature
  (loss plateaus near ``(H−1)/(2H−1) · log V`` when the copy is learned),
  making it the LM analog of the 0.72 accuracy oracle.
- :func:`markov_corpus` — sequences from a fixed random first-order Markov
  chain: a smooth language-like objective whose held-out perplexity sits
  well below uniform (the chain's conditional entropy), for eval-metric
  tests that need a nontrivial generalization gap.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class TokenDataset:
    """One split of token sequences with the tutorial loader's ``next_batch``
    iteration contract (shuffled permutation, tail-carry across epoch
    boundaries — no sequence ever dropped). ``lengths`` is optional [N]
    int32 for ragged (right-padded) corpora; when present, ``next_batch``
    returns (tokens, lengths) pairs."""

    def __init__(
        self,
        tokens: np.ndarray,
        lengths: np.ndarray | None = None,
        *,
        seed: int = 0,
    ):
        tokens = np.asarray(tokens, np.int32)
        assert tokens.ndim == 2, tokens.shape
        if lengths is not None:
            lengths = np.asarray(lengths, np.int32)
            assert lengths.shape == (tokens.shape[0],)
        self._tokens = tokens
        self._lengths = lengths
        self._rng = np.random.default_rng(seed)
        self._perm = self._rng.permutation(tokens.shape[0])
        self._index = 0
        self._epochs_completed = 0

    @property
    def tokens(self) -> np.ndarray:
        return self._tokens

    @property
    def lengths(self) -> np.ndarray | None:
        return self._lengths

    @property
    def num_examples(self) -> int:
        return self._tokens.shape[0]

    @property
    def seq_len(self) -> int:
        return self._tokens.shape[1]

    @property
    def epochs_completed(self) -> int:
        return self._epochs_completed

    def next_indices(self, batch_size: int) -> np.ndarray:
        """The index stream behind ``next_batch`` — exposed so the scanned
        epoch path can draw the identical batch sequence as device-side
        gathers (the Trainer's indexed-scan trick, train/scan.py)."""
        if self._index + batch_size > self.num_examples:
            rest = self._perm[self._index :]
            self._epochs_completed += 1
            self._perm = self._rng.permutation(self.num_examples)
            take = batch_size - rest.shape[0]
            idx = np.concatenate([rest, self._perm[:take]])
            self._index = take
        else:
            idx = self._perm[self._index : self._index + batch_size]
            self._index += batch_size
        return idx

    def next_batch(self, batch_size: int):
        idx = self.next_indices(batch_size)
        if self._lengths is None:
            return self._tokens[idx]
        return self._tokens[idx], self._lengths[idx]


class TokenDatasets(NamedTuple):
    train: TokenDataset
    validation: TokenDataset
    test: TokenDataset


def _split(tokens: np.ndarray, lengths, n_val: int, n_test: int, seed: int):
    n = tokens.shape[0]
    n_train = n - n_val - n_test
    assert n_train > 0, (n, n_val, n_test)

    def ds(lo, hi, s):
        return TokenDataset(
            tokens[lo:hi],
            None if lengths is None else lengths[lo:hi],
            seed=s,
        )

    return TokenDatasets(
        train=ds(0, n_train, seed),
        validation=ds(n_train, n_train + n_val, seed + 1),
        test=ds(n_train + n_val, n, seed + 2),
    )


def copy_corpus(
    num: int = 4096,
    half_len: int = 8,
    vocab: int = 61,
    *,
    n_val: int = 256,
    n_test: int = 256,
    seed: int = 0,
) -> TokenDatasets:
    """Sequences ``x · x`` with x uniform over the vocabulary. A model that
    learns the copy reaches mean next-token CE ≈ (H−1)/(2H−1) · log V
    (first-half targets stay at chance, copied-half targets go to ~0)."""
    rng = np.random.default_rng(seed)
    half = rng.integers(0, vocab, size=(num, half_len))
    tokens = np.concatenate([half, half], axis=1).astype(np.int32)
    return _split(tokens, None, n_val, n_test, seed)


def markov_corpus(
    num: int = 4096,
    seq_len: int = 32,
    vocab: int = 32,
    *,
    concentration: float = 0.25,
    n_val: int = 256,
    n_test: int = 256,
    seed: int = 0,
) -> TokenDatasets:
    """Sequences from one fixed random first-order Markov chain (Dirichlet
    rows, low ``concentration`` → peaky transitions). Held-out perplexity of
    a trained LM approaches the chain's conditional entropy — well below
    vocab-uniform — so eval metrics have something real to measure."""
    rng = np.random.default_rng(seed)
    trans = rng.dirichlet(np.full(vocab, concentration), size=vocab)
    tokens = np.empty((num, seq_len), np.int32)
    tokens[:, 0] = rng.integers(0, vocab, size=num)
    # Vectorized over the batch: one inverse-CDF draw per position.
    cdf = np.cumsum(trans, axis=1)
    for t in range(1, seq_len):
        u = rng.random(num)
        tokens[:, t] = (cdf[tokens[:, t - 1]] < u[:, None]).sum(axis=1)
    np.clip(tokens, 0, vocab - 1, out=tokens)
    return _split(tokens, None, n_val, n_test, seed)
