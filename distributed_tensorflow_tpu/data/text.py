"""Text → token pipeline for the LM family: byte-level tokenizer, EOS
document packing, and deterministic text corpora.

The reference has no text path at all (its one dataset is MNIST images,
reference tfsingle.py:13-14); this module gives the GPT family
(models/gpt.py) a real text-in/text-out story with zero external
dependencies and zero egress:

- :class:`ByteTokenizer` — the identity tokenizer over UTF-8 bytes
  (vocab 256 + one EOS id). No merges to train or ship, no OOV by
  construction, and exact round-trip for any string — the same baseline
  real frameworks offer as ``byte``-level fallback.
- :func:`pack_documents` — standard LM packing: each document's bytes
  followed by EOS, all documents concatenated, the stream chunked into
  fixed [N, seq_len] rows (static shapes for XLA; the ragged path is the
  ``lengths`` machinery in data/tokens.py, this is the dense one).
- :func:`text_corpus` — deterministic synthetic English-like text from a
  seeded word-Markov chain, packed and split like every corpus here
  (data/tokens.py conventions), so text-LM tests run identically in the
  zero-egress environment and on a laptop.
"""

from __future__ import annotations

import heapq
import json
import os

import numpy as np

from distributed_tensorflow_tpu.data.tokens import TokenDatasets, _split


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0..255 are the bytes, ``eos_id`` (=256)
    terminates documents. ``vocab_size`` (=257) is what the LM should be
    built with. Round-trip exact for every string; ``decode`` drops EOS
    and any (never-emitted-by-``encode``) out-of-range ids, and replaces
    invalid UTF-8 so decoding model samples never raises."""

    eos_id: int = 256
    vocab_size: int = 257

    def encode(self, text: str, *, eos: bool = False) -> np.ndarray:
        ids = np.frombuffer(text.encode("utf-8"), np.uint8).astype(np.int32)
        if eos:
            ids = np.concatenate([ids, np.array([self.eos_id], np.int32)])
        return ids

    def decode(self, ids) -> str:
        arr = np.asarray(ids).reshape(-1)
        arr = arr[(arr >= 0) & (arr < 256)]
        return arr.astype(np.uint8).tobytes().decode("utf-8", errors="replace")

    def decode_batch(self, batches) -> list[str]:
        """Decode many id sequences — the read half of the batch round-trip
        the serving layer uses (``encode`` → generate → ``decode_batch``)."""
        return [self.decode(ids) for ids in batches]


class BPETokenizer:
    """Byte-level BPE trained on a corpus: ids 0..255 are bytes, 256 is
    EOS, 257.. are learned merges (GPT-2's scheme minus the regex
    pre-tokenizer — merges run over the raw byte stream, which keeps the
    implementation exact and dependency-free). Deterministic training
    (ties broken by smallest pair) and exact round-trip for ANY string —
    unseen bytes simply stay unmerged (the byte fallback real BPE vocabs
    rely on).

    ``BPETokenizer.train(docs, num_merges=K)`` learns K merges; build the
    LM with ``vocab_size=tok.vocab_size`` (= 257 + K). ``encode`` applies
    merges in rank order (lowest rank first, all occurrences left to
    right); ``decode`` expands each id back to its bytes.

    Ship-grade costs (round 5): training maintains pair counts
    *incrementally* over a linked-list corpus — O(total merge operations),
    not O(num_merges × corpus) — and ``encode`` is a single heap pass,
    O(n log n) in the input length. Both have a native C++ fast path
    (runtime/csrc/dtf_runtime.cc ``dtf_bpe_train``/``dtf_bpe_encode``,
    bit-identical to the pure-Python fallback): measured 8k merges over a
    10.1MB corpus in 3.3s and a whole-corpus batch encode in 2.3s (the
    naive recount algorithm took minutes at a tenth of the size).
    ``save``/``load`` round-trip the learned merges as JSON so the
    tokenizer can ship alongside a checkpoint (LMTrainer writes it into
    ``checkpoint_dir``)."""

    eos_id: int = 256

    def __init__(self, merges: list[tuple[int, int]]):
        self.merges = [tuple(m) for m in merges]
        self._ranks = {pair: i for i, pair in enumerate(self.merges)}
        # id → bytes expansion table.
        table = [bytes([i]) for i in range(256)] + [b""]  # EOS → empty
        for a, b in self.merges:
            table.append(table[a] + table[b])
        self._bytes = table
        self.vocab_size = len(table)
        # Flat [2K] int32 view for the native encoder — built once, not
        # per encode() call (the per-call conversion dominated encode cost
        # at 8k merges).
        self._merges_arr = (
            np.asarray(self.merges, np.int32).reshape(-1)
            if self.merges
            else np.zeros(0, np.int32)
        )

    @classmethod
    def train(cls, docs: list[str], *, num_merges: int) -> "BPETokenizer":
        try:
            from distributed_tensorflow_tpu.runtime import native

            return cls(native.bpe_train(docs, num_merges))
        except ImportError:
            return cls(_bpe_train_py(docs, num_merges))

    def encode(self, text: str, *, eos: bool = False) -> np.ndarray:
        data = text.encode("utf-8")
        if len(data) > 1 and self._ranks:
            try:
                from distributed_tensorflow_tpu.runtime import native

                ids = native.bpe_encode(self._merges_arr, data).tolist()
            except ImportError:
                ids = _bpe_encode_py(self._ranks, data)
        else:
            ids = list(data)
        if eos:
            ids = ids + [self.eos_id]
        return np.asarray(ids, np.int32)

    def encode_batch(
        self, texts: list[str], *, eos: bool = False
    ) -> list[np.ndarray]:
        """Encode many documents at once — the native path builds its
        ranks table a single time instead of per ``encode`` call (the
        per-call setup dominated corpus encoding at 8k merges)."""
        blobs = [t.encode("utf-8") for t in texts]
        try:
            from distributed_tensorflow_tpu.runtime import native

            pieces = native.bpe_encode_batch(self._merges_arr, blobs)
        except ImportError:
            pieces = [
                np.asarray(_bpe_encode_py(self._ranks, b), np.int32)
                for b in blobs
            ]
        if eos:
            tail = np.array([self.eos_id], np.int32)
            pieces = [np.concatenate([p, tail]) for p in pieces]
        return [np.asarray(p, np.int32) for p in pieces]

    def decode(self, ids) -> str:
        arr = np.asarray(ids).reshape(-1)
        out = b"".join(
            self._bytes[i] for i in arr if 0 <= i < self.vocab_size
        )
        return out.decode("utf-8", errors="replace")

    def decode_batch(self, batches) -> list[str]:
        """Decode many id sequences (inverse of :meth:`encode_batch` for
        any round-trippable input; the serving layer's read half)."""
        return [self.decode(ids) for ids in batches]

    # -- serialization (the vocab file that ships with a checkpoint) ------

    def save(self, path: str) -> None:
        """Write the learned merges as JSON (atomic rename so a reader
        never sees a partial vocab file)."""
        payload = {"format": "dtf-bpe-v1", "merges": [list(m) for m in self.merges]}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            payload = json.load(f)
        if payload.get("format") != "dtf-bpe-v1":
            raise ValueError(
                f"not a dtf-bpe-v1 vocab file: {path!r} "
                f"(format={payload.get('format')!r})"
            )
        return cls([(int(a), int(b)) for a, b in payload["merges"]])


def _merge_pair(ids, pair, new_id):
    """One BPE merge pass: every non-overlapping occurrence of ``pair``
    (left to right) becomes ``new_id``."""
    out = []
    i = 0
    n = len(ids)
    while i < n:
        if i + 1 < n and ids[i] == pair[0] and ids[i + 1] == pair[1]:
            out.append(new_id)
            i += 2
        else:
            out.append(int(ids[i]))
            i += 1
    return out


def _bpe_train_py(docs: list[str], num_merges: int) -> list[tuple[int, int]]:
    """Incremental BPE training over a linked-list corpus.

    Semantics are exactly the naive recount-per-round algorithm (pick the
    most frequent adjacent pair, ties to the smallest pair; merge every
    non-overlapping occurrence left to right; never merge across document
    boundaries) — but pair counts are maintained by ±deltas at each merge
    site instead of a full corpus rescan per round, and selection is a
    lazy max-heap. Total work is O(corpus + Σ merge-site updates), so 8k
    merges over megabytes of text is seconds, not hours. Bit-identical to
    the native ``dtf_bpe_train`` (tests/test_text.py pins both against
    the naive reference)."""
    blobs = [np.frombuffer(d.encode("utf-8"), np.uint8) for d in docs]
    total = int(sum(len(s) for s in blobs))
    ids = np.empty(total, np.int32)
    nxt = np.full(total, -1, np.int64)
    prv = np.full(total, -1, np.int64)
    off = 0
    for s in blobs:
        n = len(s)
        if n == 0:
            continue
        ids[off : off + n] = s
        nxt[off : off + n - 1] = np.arange(off + 1, off + n)
        prv[off + 1 : off + n] = np.arange(off, off + n - 1)
        off += n

    # Initial counts + occurrence lists in one vectorized pass: positions
    # grouped per pair, ascending (stable argsort of the position-ordered
    # code vector).
    left = np.nonzero(nxt >= 0)[0]
    counts: dict[tuple[int, int], int] = {}
    occ0: dict[tuple[int, int], np.ndarray] = {}
    occ_new: dict[tuple[int, int], list[int]] = {}
    if len(left):
        codes = (ids[left].astype(np.int64) << 32) | ids[left + 1]
        order = np.argsort(codes, kind="stable")
        sorted_codes = codes[order]
        uniq, starts = np.unique(sorted_codes, return_index=True)
        bounds = np.append(starts, len(sorted_codes))
        for k in range(len(uniq)):
            pair = (int(uniq[k] >> 32), int(uniq[k] & 0xFFFFFFFF))
            counts[pair] = int(bounds[k + 1] - bounds[k])
            occ0[pair] = left[order[bounds[k] : bounds[k + 1]]]

    heap = [(-c, pair) for pair, c in counts.items()]
    heapq.heapify(heap)

    merges: list[tuple[int, int]] = []
    while len(merges) < num_merges and heap:
        negc, pair = heap[0]
        c = counts.get(pair)
        if c is None or -negc != c:
            heapq.heappop(heap)  # stale entry
            continue
        heapq.heappop(heap)
        new_id = 257 + len(merges)
        merges.append(pair)
        a, b = pair
        parts = []
        if pair in occ0:
            parts.append(occ0.pop(pair))
        if pair in occ_new:
            parts.append(np.asarray(occ_new.pop(pair), np.int64))
        positions = np.sort(np.concatenate(parts)) if parts else ()
        # Count deltas accumulate per ROUND and apply once per distinct
        # changed pair (one heap push each) — per-occurrence pushes drown
        # the heap in stale entries on repetitive corpora.
        delta: dict[tuple[int, int], int] = {}
        for i in positions:
            i = int(i)
            if ids[i] != a:
                continue  # stale occurrence (node merged/killed since)
            j = int(nxt[i])
            if j < 0 or ids[j] != b:
                continue
            p = int(prv[i])
            q = int(nxt[j])
            # Read neighbor ids BEFORE rewriting the nodes (overlap chains
            # like [a,a,a] with pair (a,a) depend on it).
            if p >= 0:
                k = (int(ids[p]), a)
                delta[k] = delta.get(k, 0) - 1
            if q >= 0:
                k = (b, int(ids[q]))
                delta[k] = delta.get(k, 0) - 1
            ids[i] = new_id
            ids[j] = -2  # dead node
            nxt[i] = q
            if q >= 0:
                prv[q] = i
                k = (new_id, int(ids[q]))
                delta[k] = delta.get(k, 0) + 1
                occ_new.setdefault(k, []).append(i)
            if p >= 0:
                k = (int(ids[p]), new_id)
                delta[k] = delta.get(k, 0) + 1
                occ_new.setdefault(k, []).append(p)
        for k, d in delta.items():
            if k == pair or d == 0:
                continue
            c2 = counts.get(k, 0) + d
            if c2 <= 0:
                counts.pop(k, None)
            else:
                counts[k] = c2
                heapq.heappush(heap, (-c2, k))
        counts.pop(pair, None)
    return merges


def _bpe_encode_py(
    ranks: dict[tuple[int, int], int], data: bytes
) -> list[int]:
    """Single-heap BPE encode: pop (rank, position) ascending, merge, push
    the two newly-created neighbor pairs. Equivalent to applying merges in
    rank order with all occurrences left to right (a pair created by a
    rank-r merge always has rank > r, so the heap drains rank levels in
    order), O(n log n) in the input length."""
    ids = list(data)
    n = len(ids)
    nxt = list(range(1, n)) + [-1]
    prv = [-1] + list(range(n - 1))
    heap = []
    for i in range(n - 1):
        r = ranks.get((ids[i], ids[i + 1]))
        if r is not None:
            heap.append((r, i))
    heapq.heapify(heap)
    while heap:
        r, i = heapq.heappop(heap)
        if ids[i] < 0:
            continue
        j = nxt[i]
        if j < 0:
            continue
        if ranks.get((ids[i], ids[j])) != r:
            continue  # stale entry
        ids[i] = 257 + r
        ids[j] = -1
        q = nxt[j]
        nxt[i] = q
        if q >= 0:
            prv[q] = i
            r2 = ranks.get((ids[i], ids[q]))
            if r2 is not None:
                heapq.heappush(heap, (r2, i))
        p = prv[i]
        if p >= 0:
            r2 = ranks.get((ids[p], ids[i]))
            if r2 is not None:
                heapq.heappush(heap, (r2, p))
    return [t for t in ids if t >= 0]


def pack_documents(
    docs: list[str] | list[np.ndarray],
    seq_len: int,
    tokenizer: "ByteTokenizer | BPETokenizer | None" = None,
) -> np.ndarray:
    """Concatenate ``doc₀ EOS doc₁ EOS ...`` and chunk the stream into
    [N, seq_len] int32 rows (the tail that doesn't fill a row is
    dropped — standard LM packing; no padding, every kept position is a
    real training target). ``docs`` may be strings (encoded with
    ``tokenizer``, default :class:`ByteTokenizer`) or pre-tokenized id
    arrays (used verbatim, EOS appended)."""
    tok = tokenizer or ByteTokenizer()
    batch_encode = getattr(tok, "encode_batch", None)
    if batch_encode is not None and docs and all(isinstance(d, str) for d in docs):
        parts = batch_encode(list(docs), eos=True)
    else:
        parts = []
        for d in docs:
            if isinstance(d, str):
                parts.append(tok.encode(d, eos=True))
            else:
                parts.append(
                    np.concatenate(
                        [np.asarray(d, np.int32), np.array([tok.eos_id], np.int32)]
                    )
                )
    stream = np.concatenate(parts) if parts else np.zeros((0,), np.int32)
    n = len(stream) // seq_len
    if n == 0:
        raise ValueError(
            f"packed stream ({len(stream)} tokens) shorter than one "
            f"seq_len={seq_len} row"
        )
    return stream[: n * seq_len].reshape(n, seq_len).astype(np.int32)


_WORDS = (
    "the a one this that model data train step loss grad mesh chip ring "
    "token batch epoch scan shard sum small fast slow deep wide new old "
    "red blue green node host core wire pipe gate fuse"
).split()


def synthetic_documents(
    num_docs: int, *, seed: int = 0, min_words: int = 8, max_words: int = 40
) -> list[str]:
    """Deterministic English-like documents from a seeded word-Markov
    chain (first-order over a fixed 40-word vocabulary, transition rows
    drawn once from a Dirichlet). Same seed → same corpus, everywhere."""
    rng = np.random.default_rng(seed)
    w = len(_WORDS)
    trans = rng.dirichlet(np.full(w, 0.3), size=w)
    start = rng.dirichlet(np.full(w, 0.5))
    docs = []
    for _ in range(num_docs):
        length = int(rng.integers(min_words, max_words + 1))
        idx = int(rng.choice(w, p=start))
        words = [_WORDS[idx]]
        for _ in range(length - 1):
            idx = int(rng.choice(w, p=trans[idx]))
            words.append(_WORDS[idx])
        docs.append(" ".join(words) + ".")
    return docs


def text_corpus(
    *,
    num_docs: int = 512,
    seq_len: int = 128,
    n_val: int = 32,
    n_test: int = 32,
    seed: int = 0,
    tokenizer: ByteTokenizer | BPETokenizer | None = None,
) -> TokenDatasets:
    """LM corpus over :func:`synthetic_documents` — byte-level by
    default, subword with a trained :class:`BPETokenizer` — packed with
    :func:`pack_documents` and split train/validation/test contiguously
    (data/tokens.py ``_split`` — the packed rows are draws from one
    stationary chain, so contiguous splits are i.i.d.-equivalent). Build
    the model with ``vocab_size=tokenizer.vocab_size`` (257 for the
    default :class:`ByteTokenizer`; a corpus-trained BPE vocabulary packs
    the same documents into fewer tokens per document)."""
    docs = synthetic_documents(num_docs, seed=seed)
    tokens = pack_documents(docs, seq_len, tokenizer)
    if len(tokens) <= n_val + n_test:
        raise ValueError(
            f"only {len(tokens)} packed rows; need > n_val+n_test "
            f"({n_val}+{n_test}) — more docs or a smaller seq_len"
        )
    return _split(tokens, None, n_val, n_test, seed)
