"""Text → token pipeline for the LM family: byte-level tokenizer, EOS
document packing, and deterministic text corpora.

The reference has no text path at all (its one dataset is MNIST images,
reference tfsingle.py:13-14); this module gives the GPT family
(models/gpt.py) a real text-in/text-out story with zero external
dependencies and zero egress:

- :class:`ByteTokenizer` — the identity tokenizer over UTF-8 bytes
  (vocab 256 + one EOS id). No merges to train or ship, no OOV by
  construction, and exact round-trip for any string — the same baseline
  real frameworks offer as ``byte``-level fallback.
- :func:`pack_documents` — standard LM packing: each document's bytes
  followed by EOS, all documents concatenated, the stream chunked into
  fixed [N, seq_len] rows (static shapes for XLA; the ragged path is the
  ``lengths`` machinery in data/tokens.py, this is the dense one).
- :func:`text_corpus` — deterministic synthetic English-like text from a
  seeded word-Markov chain, packed and split like every corpus here
  (data/tokens.py conventions), so text-LM tests run identically in the
  zero-egress environment and on a laptop.
"""

from __future__ import annotations

import numpy as np

from distributed_tensorflow_tpu.data.tokens import TokenDatasets, _split


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0..255 are the bytes, ``eos_id`` (=256)
    terminates documents. ``vocab_size`` (=257) is what the LM should be
    built with. Round-trip exact for every string; ``decode`` drops EOS
    and any (never-emitted-by-``encode``) out-of-range ids, and replaces
    invalid UTF-8 so decoding model samples never raises."""

    eos_id: int = 256
    vocab_size: int = 257

    def encode(self, text: str, *, eos: bool = False) -> np.ndarray:
        ids = np.frombuffer(text.encode("utf-8"), np.uint8).astype(np.int32)
        if eos:
            ids = np.concatenate([ids, np.array([self.eos_id], np.int32)])
        return ids

    def decode(self, ids) -> str:
        arr = np.asarray(ids).reshape(-1)
        arr = arr[(arr >= 0) & (arr < 256)]
        return arr.astype(np.uint8).tobytes().decode("utf-8", errors="replace")


class BPETokenizer:
    """Byte-level BPE trained on a corpus: ids 0..255 are bytes, 256 is
    EOS, 257.. are learned merges (GPT-2's scheme minus the regex
    pre-tokenizer — merges run over the raw byte stream, which keeps the
    implementation exact and dependency-free). Deterministic training
    (ties broken by smallest pair) and exact round-trip for ANY string —
    unseen bytes simply stay unmerged (the byte fallback real BPE vocabs
    rely on).

    ``BPETokenizer.train(docs, num_merges=K)`` learns K merges; build the
    LM with ``vocab_size=tok.vocab_size`` (= 257 + K). ``encode`` applies
    merges in rank order (lowest rank first, all occurrences left to
    right); ``decode`` expands each id back to its bytes."""

    eos_id: int = 256

    def __init__(self, merges: list[tuple[int, int]]):
        self.merges = [tuple(m) for m in merges]
        self._ranks = {pair: i for i, pair in enumerate(self.merges)}
        # id → bytes expansion table.
        table = [bytes([i]) for i in range(256)] + [b""]  # EOS → empty
        for a, b in self.merges:
            table.append(table[a] + table[b])
        self._bytes = table
        self.vocab_size = len(table)

    @classmethod
    def train(cls, docs: list[str], *, num_merges: int) -> "BPETokenizer":
        from collections import Counter

        seqs = [
            list(np.frombuffer(d.encode("utf-8"), np.uint8)) for d in docs
        ]
        merges: list[tuple[int, int]] = []
        for new_id in range(257, 257 + num_merges):
            counts = Counter()
            for s in seqs:
                counts.update(zip(s, s[1:]))
            if not counts:
                break
            best_n = max(counts.values())
            pair = min(p for p, n in counts.items() if n == best_n)
            merges.append((int(pair[0]), int(pair[1])))
            seqs = [_merge_pair(s, pair, new_id) for s in seqs]
        return cls(merges)

    def encode(self, text: str, *, eos: bool = False) -> np.ndarray:
        ids = list(np.frombuffer(text.encode("utf-8"), np.uint8))
        while len(ids) > 1:
            pairs = set(zip(ids, ids[1:]))
            ranked = [p for p in pairs if p in self._ranks]
            if not ranked:
                break
            pair = min(ranked, key=self._ranks.__getitem__)
            ids = _merge_pair(ids, pair, 257 + self._ranks[pair])
        if eos:
            ids = ids + [self.eos_id]
        return np.asarray(ids, np.int32)

    def decode(self, ids) -> str:
        arr = np.asarray(ids).reshape(-1)
        out = b"".join(
            self._bytes[i] for i in arr if 0 <= i < self.vocab_size
        )
        return out.decode("utf-8", errors="replace")


def _merge_pair(ids, pair, new_id):
    """One BPE merge pass: every non-overlapping occurrence of ``pair``
    (left to right) becomes ``new_id``."""
    out = []
    i = 0
    n = len(ids)
    while i < n:
        if i + 1 < n and ids[i] == pair[0] and ids[i + 1] == pair[1]:
            out.append(new_id)
            i += 2
        else:
            out.append(int(ids[i]))
            i += 1
    return out


def pack_documents(
    docs: list[str] | list[np.ndarray],
    seq_len: int,
    tokenizer: "ByteTokenizer | BPETokenizer | None" = None,
) -> np.ndarray:
    """Concatenate ``doc₀ EOS doc₁ EOS ...`` and chunk the stream into
    [N, seq_len] int32 rows (the tail that doesn't fill a row is
    dropped — standard LM packing; no padding, every kept position is a
    real training target). ``docs`` may be strings (encoded with
    ``tokenizer``, default :class:`ByteTokenizer`) or pre-tokenized id
    arrays (used verbatim, EOS appended)."""
    tok = tokenizer or ByteTokenizer()
    parts = []
    for d in docs:
        if isinstance(d, str):
            parts.append(tok.encode(d, eos=True))
        else:
            parts.append(
                np.concatenate(
                    [np.asarray(d, np.int32), np.array([tok.eos_id], np.int32)]
                )
            )
    stream = np.concatenate(parts) if parts else np.zeros((0,), np.int32)
    n = len(stream) // seq_len
    if n == 0:
        raise ValueError(
            f"packed stream ({len(stream)} tokens) shorter than one "
            f"seq_len={seq_len} row"
        )
    return stream[: n * seq_len].reshape(n, seq_len).astype(np.int32)


_WORDS = (
    "the a one this that model data train step loss grad mesh chip ring "
    "token batch epoch scan shard sum small fast slow deep wide new old "
    "red blue green node host core wire pipe gate fuse"
).split()


def synthetic_documents(
    num_docs: int, *, seed: int = 0, min_words: int = 8, max_words: int = 40
) -> list[str]:
    """Deterministic English-like documents from a seeded word-Markov
    chain (first-order over a fixed 40-word vocabulary, transition rows
    drawn once from a Dirichlet). Same seed → same corpus, everywhere."""
    rng = np.random.default_rng(seed)
    w = len(_WORDS)
    trans = rng.dirichlet(np.full(w, 0.3), size=w)
    start = rng.dirichlet(np.full(w, 0.5))
    docs = []
    for _ in range(num_docs):
        length = int(rng.integers(min_words, max_words + 1))
        idx = int(rng.choice(w, p=start))
        words = [_WORDS[idx]]
        for _ in range(length - 1):
            idx = int(rng.choice(w, p=trans[idx]))
            words.append(_WORDS[idx])
        docs.append(" ".join(words) + ".")
    return docs


def text_corpus(
    *,
    num_docs: int = 512,
    seq_len: int = 128,
    n_val: int = 32,
    n_test: int = 32,
    seed: int = 0,
    tokenizer: ByteTokenizer | BPETokenizer | None = None,
) -> TokenDatasets:
    """LM corpus over :func:`synthetic_documents` — byte-level by
    default, subword with a trained :class:`BPETokenizer` — packed with
    :func:`pack_documents` and split train/validation/test contiguously
    (data/tokens.py ``_split`` — the packed rows are draws from one
    stationary chain, so contiguous splits are i.i.d.-equivalent). Build
    the model with ``vocab_size=tokenizer.vocab_size`` (257 for the
    default :class:`ByteTokenizer`; a corpus-trained BPE vocabulary packs
    the same documents into fewer tokens per document)."""
    docs = synthetic_documents(num_docs, seed=seed)
    tokens = pack_documents(docs, seq_len, tokenizer)
    if len(tokens) <= n_val + n_test:
        raise ValueError(
            f"only {len(tokens)} packed rows; need > n_val+n_test "
            f"({n_val}+{n_test}) — more docs or a smaller seq_len"
        )
    return _split(tokens, None, n_val, n_test, seed)
