"""Host→device batch prefetching (double buffering).

The reference's hot loop fed each batch synchronously: ``sess.run(...,
feed_dict={x: batch_xs, ...})`` blocks on the host→device copy before the
step can start (reference tfdist_between.py:91-94) — the README's measured
gRPC/feed overhead is exactly this boundary (reference README.md:38-40). On
TPU the same hazard is the PCIe/host transfer of the next batch.

``jax.device_put`` is asynchronous: it returns a placeholder array while the
transfer proceeds in the background. Prefetching therefore needs no threads —
keeping ``depth`` batches in flight means batch ``i+1``'s transfer overlaps
step ``i``'s compute, and the dispatch-ahead queue never stalls on the host.

(The ``scan_epoch`` path stages the whole epoch in device memory up front and
doesn't need this; prefetching serves the eager per-batch loop — the mode
whose loop contract matches the reference's — and any strategy, since
placement is delegated to ``strategy.prepare_batch``.)
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Iterator


def prefetch_batches(
    next_batch: Callable[[int], tuple],
    batch_size: int,
    steps: int,
    place: Callable[..., tuple],
    depth: int = 2,
) -> Iterator[tuple]:
    """Yield ``steps`` device-placed batches with ``depth`` in flight.

    ``next_batch(batch_size)`` produces host arrays (the tutorial iterator's
    API, reference tfdist_between.py:91); ``place(*batch)`` device-places one
    batch with the strategy's sharding (async). Batch order is identical to
    the unprefetched loop — only the placement timing changes.
    """
    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")
    queue: deque[tuple] = deque()
    for _ in range(min(depth, steps)):
        queue.append(place(*next_batch(batch_size)))
    for i in range(steps):
        batch = queue.popleft()
        if i + depth < steps:
            queue.append(place(*next_batch(batch_size)))
        yield batch
