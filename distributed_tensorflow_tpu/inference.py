"""Inference surface: compiled batched prediction from trained parameters.

The reference had no inference path beyond the in-loop eval fetch — the same
``sess.run(accuracy, feed_dict=test_set)`` graph used during training
(reference tfsingle.py:94, tfdist_between.py:108). This module is the
framework's serving-shaped answer: take parameters (from a live training
state or a checkpoint), compile the forward pass ONCE at a fixed batch shape,
and stream arbitrary-sized inputs through it.

TPU-first details:

- **Static shapes**: XLA compiles per input shape. Arbitrary request sizes
  are chunked to a fixed ``batch_size`` and the tail chunk zero-padded, so
  every dispatch hits the same compiled executable — no recompiles, no
  dynamic-shape fallbacks.
- **Effective params**: under async DP the training state holds per-chip
  parameter copies; ``Strategy.effective_params`` collapses them (mean) the
  way the reference's eval read "the" parameters off the PS.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.ops import losses as losses_lib


class Predictor:
    """Fixed-shape compiled prediction over a trained parameter set."""

    def __init__(self, model, params, *, batch_size: int = 1024):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.model = model
        self.params = params
        self.batch_size = batch_size
        self._fn = jax.jit(model.apply)

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_state(cls, model, state, *, strategy=None, **kw) -> "Predictor":
        """Build from a live training state. Pass the training ``strategy``
        so async states collapse their per-chip copies correctly."""
        if strategy is not None:
            params = strategy.effective_params(state)
        else:
            # Async states are detectable: their step counter is a per-chip
            # vector (strategy.py AsyncDataParallel.init_state), and serving
            # stacked per-chip params would silently yield garbage shapes.
            if getattr(state.step, "ndim", 0):
                raise ValueError(
                    "state holds stacked per-chip parameter copies (async DP);"
                    " pass strategy= so effective_params can collapse them"
                )
            params = state.params
        return cls(model, params, **kw)

    @classmethod
    def from_checkpoint(
        cls, model, checkpoint_dir: str, *, optimizer=None, seed: int = 1, **kw
    ) -> "Predictor":
        """Restore the latest checkpoint in ``checkpoint_dir`` (written by
        train/supervisor.py) and serve its parameters.

        ``optimizer`` must match the one used in training (the checkpoint
        holds its slots too); defaults to the reference's SGD, whose slot
        state is empty.

        Round 5: the ``step_N.layout.json`` sidecar makes non-dense
        checkpoint layouts servable too — an async checkpoint's stacked
        per-chip copies restore in their own shapes and collapse at the
        mean (the same parameters async evaluates at), so any mode's
        checkpoint serves without its training strategy in hand.
        """
        from distributed_tensorflow_tpu.ops import optim as optim_lib
        from distributed_tensorflow_tpu.parallel.strategy import TrainState
        from distributed_tensorflow_tpu.train.supervisor import (
            Supervisor,
            latest_checkpoint_step,
        )

        # Probe before constructing a Supervisor: a read path must not mkdir
        # a typo'd checkpoint_dir as a side effect.
        step = latest_checkpoint_step(checkpoint_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {checkpoint_dir}")
        from distributed_tensorflow_tpu.train import supervisor as _sup

        if not _sup._HAVE_ORBAX:
            # Without orbax prepare_or_restore would hand back the fresh
            # seed-init state; a checkpoint exists, so serving it silently
            # untrained must be an error, not a fallback.
            raise RuntimeError(
                f"checkpoint found under {checkpoint_dir} but orbax is not"
                " importable; cannot restore"
            )
        optimizer = optimizer or optim_lib.sgd(0.001)
        params = model.init(seed)
        fresh = TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))
        sup = Supervisor(checkpoint_dir=checkpoint_dir)
        meta = sup.saved_layout(step) or {}
        if meta.get("mode") == "async":
            n = int(meta["replicas"])
            abstract = TrainState(
                *jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct((n,) + x.shape, x.dtype),
                    (fresh.params, fresh.opt_state),
                ),
                jax.ShapeDtypeStruct((n,), jnp.int32),
            )
            stacked = sup.restore_raw(step, abstract)
            served = jax.tree.map(lambda x: jnp.mean(x, axis=0), stacked.params)
            return cls(model, served, **kw)
        state, _ = sup.prepare_or_restore(fresh)
        return cls(model, state.params, **kw)

    # -- prediction --------------------------------------------------------

    def predict_proba(self, images) -> np.ndarray:
        """[N, ...] host array → [N, num_classes] float32 probabilities.
        Chunked to ``batch_size`` with a zero-padded tail — one compiled
        shape regardless of N."""
        images = np.asarray(images, dtype=np.float32)
        n = images.shape[0]
        if n == 0:
            raise ValueError("predict_proba called with an empty batch")
        bs = self.batch_size
        out = []
        for lo in range(0, n, bs):
            chunk = images[lo : lo + bs]
            pad = bs - chunk.shape[0]
            if pad:
                chunk = np.concatenate([chunk, np.zeros((pad,) + chunk.shape[1:], chunk.dtype)])
            probs = self._fn(self.params, jnp.asarray(chunk))
            out.append(np.asarray(probs[: bs - pad] if pad else probs))
        return np.concatenate(out)

    def predict(self, images) -> np.ndarray:
        """[N, ...] → [N] int64 predicted class ids."""
        return self.predict_proba(images).argmax(axis=-1)

    def accuracy(self, images, labels_one_hot) -> float:
        """Full-split accuracy, matching the trainer's eval metric."""
        probs = self.predict_proba(images)
        return float(losses_lib.accuracy(jnp.asarray(probs), jnp.asarray(labels_one_hot)))
