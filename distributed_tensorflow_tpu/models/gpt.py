"""Autoregressive LM family: GPT-style causal decoder with KV-cache decoding.

The reference has no sequence models and no generative path at all (its one
model is the fixed-feature MLP classifier, SURVEY.md §2 C8; its only
"inference" is the in-loop accuracy fetch, reference tfsingle.py:94). This
family completes the framework's long-context story on the *generation*
side: the training forward is the same causal-attention machinery the
transformer classifier proves (dense or Pallas flash), and decoding is the
idiomatic TPU inference shape —

- **static shapes everywhere**: the KV cache is allocated at ``max_len`` up
  front and written with ``dynamic_update_slice``; the growing sequence
  never changes a compiled shape, so one executable serves every step;
- **layers as a scanned stack**: block parameters carry a leading
  ``num_layers`` axis and the forward is one ``lax.scan`` over it — one
  trace and one HLO body regardless of depth (no Python-unrolled layers);
- **decode loop as ``lax.scan``**: greedy generation compiles into a single
  dispatch, token round-trips never touch the host.

Architecture: token embed → +learned positions → N pre-LN blocks
(causal attention + GELU MLP, residuals) → final LN → logits through the
tied embedding (lm_head = embedᵀ). All matmuls in ``compute_dtype`` with
f32 accumulation; layernorm/softmax/loss f32.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributed_tensorflow_tpu.models.base import layernorm as _layernorm
from distributed_tensorflow_tpu.ops.collectives import to_varying
from distributed_tensorflow_tpu.ops.quantized import (
    QuantizedLinear,
    dequantize_kv,
    kv_storage_dtype,
    quantize_kv,
    wo_dot,
)
from distributed_tensorflow_tpu.ops.ring_attention import dense_attention


# Decode-path implementations (rounds 18+20): see GPTLM.__init__'s
# decode_engine comment and ops/pallas_decode.py.
DECODE_ENGINES = ("auto", "pallas", "pallas-layer", "xla")

# Per-LAYER VMEM budget for the decode kernels' weights (~10·d² +
# 2·d·Hkv·Dh elements at compute dtype). Under the round-20 megakernel
# weights are STREAMED layer by layer, so this caps the one layer
# resident at a time — the same per-layer arithmetic also bounds the
# "pallas-layer" kernel, whose single launch holds exactly one block.
# 8 MiB keeps serving widths (d ≤ ~512 bf16) fused and refuses widths
# whose FFN pair alone would blow the ~16 MiB VMEM — "auto" silently
# falls back to XLA there, an explicit pallas variant raises (the
# message states this cap AND the config's actual per-layer bytes).
# PROVISIONAL until the chip session measures where the fused win stops
# (the _FUSED_DQ_CAP_BYTES convention, ops/pallas_attention.py).
_DECODE_VMEM_WEIGHT_CAP = 8 << 20


def _rope(x, positions, base: float = 10000.0):
    """Rotary position embedding on [B, L, H, Dh] at absolute ``positions``
    [L] (shared across the batch) or [B, L] (per-row — the slot-decode
    path, where every serving slot sits at its own sequence position):
    pairs (x_i, x_{i+Dh/2}) rotate by pos·base^(−2i/Dh). Computed in
    f32, cast back — relative-position attention without any learned table,
    the modern LM default (absent from the reference, which has no sequence
    models at all)."""
    b, l, h, dh = x.shape
    half = dh // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    # [L, half] or [B, L, half]; the head axis slots in before `half`, and
    # leading-batch broadcasting aligns both layouts against [B, L, H, half].
    ang = positions.astype(jnp.float32)[..., :, None] * freqs
    cos = jnp.expand_dims(jnp.cos(ang), -2)
    sin = jnp.expand_dims(jnp.sin(ang), -2)
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., :half], x32[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


class GPTBlockParams(NamedTuple):
    """One decoder block; every leaf carries a leading [num_layers] axis in
    ``GPTLMParams.blocks`` so the forward can scan over the stack."""

    ln1_scale: jax.Array
    ln1_bias: jax.Array
    wq: jax.Array  # [d, d]
    wk: jax.Array
    wv: jax.Array
    wo: jax.Array
    ln2_scale: jax.Array
    ln2_bias: jax.Array
    w_up: jax.Array  # [d, 4d]
    b_up: jax.Array
    w_down: jax.Array  # [4d, d]
    b_down: jax.Array


class GPTMoEBlockParams(NamedTuple):
    """Decoder block whose FFN is a Switch-style top-1 MoE
    (ops/moe.py): attention fields as in :class:`GPTBlockParams`, FFN
    weights stacked over experts (axis 1; axis 0 remains num_layers)."""

    ln1_scale: jax.Array
    ln1_bias: jax.Array
    wq: jax.Array
    wk: jax.Array
    wv: jax.Array
    wo: jax.Array
    ln2_scale: jax.Array
    ln2_bias: jax.Array
    wg: jax.Array  # [n, d, E] gate
    w_up: jax.Array  # [n, E, d, 4d]
    b_up: jax.Array  # [n, E, 4d]
    w_down: jax.Array  # [n, E, 4d, d]
    b_down: jax.Array  # [n, E, d]


class GPTLMParams(NamedTuple):
    embed: jax.Array  # [vocab, d] (also the tied LM head)
    pos: jax.Array  # [max_len, d]
    blocks: GPTBlockParams  # leaves stacked over num_layers
    lnf_scale: jax.Array
    lnf_bias: jax.Array


class SlotKVCache(NamedTuple):
    """Serving-side decode state over a fixed bank of request SLOTS: like
    :class:`KVCache` but with a PER-SLOT length — every batch row is an
    independent request at its own sequence position, which is what
    continuous batching needs (slots free and refill at different times;
    a shared scalar length would drain the whole bank to the longest
    request). Written by :meth:`GPTLM.prefill_slots` /
    :meth:`GPTLM.decode_slots`; the text layer on top is ``serve.py``.

    ``kv_dtype="int8"|"fp8"`` (round 15) stores the payload in 1-byte
    elements with the per-row symmetric scales riding as the
    ``k_scale``/``v_scale`` side tensors (``ops/quantized.quantize_kv``
    granularity: one f32 per written position per KV head). Quantization
    happens ON WRITE and dequantization ON READ inside the attention
    math, so the contract stays "same math, fewer bytes" up to the
    committed rounding; ``kv_dtype="bf16"`` (the default) keeps scales
    ``None`` and is bitwise the round-9/11 layout."""

    k: jax.Array  # [num_layers, S, cache_len, Hkv, Dh]
    v: jax.Array  # [num_layers, S, cache_len, Hkv, Dh]
    lengths: jax.Array  # [S] int32 — tokens written into each slot's cache
    k_scale: jax.Array | None = None  # [num_layers, S, cache_len, Hkv] f32
    v_scale: jax.Array | None = None


class PagedKVCache(NamedTuple):
    """Serving-side decode state over a shared BLOCK POOL (vLLM's
    PagedAttention layout): K/V for every slot live in one pool of
    fixed-size blocks, and each slot maps logical position ``p`` to
    ``pool[block_tables[s, p // bs], p % bs]``. Occupancy scales with
    blocks actually held, not ``slots × max_len`` slabs — the paged
    engine (``serve.py paged=True``) admits by free blocks, and two
    slots may map the SAME physical block for a shared prompt prefix
    (copy-on-write via the host-side refcounts in ``serve_pool.py``;
    shared blocks are immutable full prompt blocks, so no copy ever
    happens). Written by :meth:`GPTLM.extend_paged` /
    :meth:`GPTLM.decode_paged`; device primitives in
    ``ops/paged_attention.py``. Unused table entries read garbage that
    the validity masks keep out of every softmax (the stale-bytes-
    unreachable stance of :class:`SlotKVCache`).

    ``kv_dtype="int8"|"fp8"`` (round 15): payload blocks shrink to
    1-byte elements and the per-row scales ride as ``k_scale``/
    ``v_scale`` side pools indexed by the SAME (block, position, head)
    coordinates — the block-table gather/scatter index math applies to
    them unchanged, and COW prefix sharing shares a block's scales with
    the block (one refcount covers both; scales are never packed into
    the payload). ``kv_dtype="bf16"`` keeps scales ``None``: the
    round-11 bitwise path."""

    k: jax.Array  # [num_layers, num_blocks, block_size, Hkv, Dh]
    v: jax.Array  # [num_layers, num_blocks, block_size, Hkv, Dh]
    block_tables: jax.Array  # [S, max_blocks] int32 — physical block ids
    lengths: jax.Array  # [S] int32 — tokens written for each slot
    k_scale: jax.Array | None = None  # [num_layers, num_blocks, bs, Hkv] f32
    v_scale: jax.Array | None = None


class KVCache(NamedTuple):
    """Decode state: per-layer keys/values at a static cache length, plus
    the number of tokens decoded so far (``length`` is ABSOLUTE — it keeps
    counting past the cache size on the rolling path).

    Cache length is ``max_len`` for full-attention models; for windowed
    models it is only ``min(window, max_len)`` — slots are written mod W
    (a rolling buffer), because a sliding-window query can never attend
    anything older. Decode memory and per-step attention are O(W), not
    O(max_len)."""

    k: jax.Array  # [num_layers, B, cache_len, Hkv, Dh]
    v: jax.Array  # [num_layers, B, cache_len, Hkv, Dh]
    length: jax.Array  # scalar int32


class GPTLM:
    """tokens [B, L] int32 → next-token logits [B, L, vocab]."""

    def __init__(
        self,
        vocab_size: int = 256,
        max_len: int = 128,
        model_dim: int = 64,
        num_heads: int = 4,
        num_kv_heads: int | None = None,
        num_layers: int = 2,
        compute_dtype: jnp.dtype = jnp.bfloat16,
        attention_impl: str = "xla",
        window: int | None = None,
        moe_experts: int | None = None,
        moe_capacity_factor: float = 2.0,
        moe_balance_coef: float = 1e-2,
        moe_z_coef: float = 1e-3,
        moe_top_k: int = 1,
        pos_embedding: str = "learned",
        remat: bool | str = False,
        flash_min_len: int | None = None,
        matmul_dtype: str | None = None,
        decode_engine: str = "auto",
    ):
        assert model_dim % num_heads == 0
        if attention_impl not in ("xla", "flash"):
            raise ValueError(
                f"unknown attention_impl {attention_impl!r}; xla|flash"
            )
        if window is not None and window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if moe_experts is not None and moe_experts < 2:
            raise ValueError(f"moe_experts must be >= 2, got {moe_experts}")
        if moe_top_k < 1 or (
            moe_experts is not None and moe_top_k > moe_experts
        ):
            raise ValueError(
                f"moe_top_k {moe_top_k} must be in [1, moe_experts"
                f"={moe_experts}]"
            )
        if moe_top_k > 1 and moe_experts is None:
            raise ValueError(
                f"moe_top_k={moe_top_k} requires a MoE model "
                "(set moe_experts)"
            )
        if pos_embedding not in ("learned", "rope"):
            raise ValueError(
                f"unknown pos_embedding {pos_embedding!r}; learned|rope"
            )
        if pos_embedding == "rope" and (model_dim // num_heads) % 2:
            raise ValueError(
                f"rope needs an even head_dim, got {model_dim // num_heads}"
            )
        if num_kv_heads is None:
            num_kv_heads = num_heads
        if num_kv_heads < 1:
            raise ValueError(f"num_kv_heads must be >= 1, got {num_kv_heads}")
        if num_heads % num_kv_heads:
            raise ValueError(
                f"num_heads {num_heads} must be a multiple of num_kv_heads "
                f"{num_kv_heads}"
            )
        self.vocab_size = vocab_size
        self.max_len = max_len
        self.model_dim = model_dim
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads
        self.head_dim = model_dim // num_heads
        self.num_layers = num_layers
        self.compute_dtype = compute_dtype
        self.attention_impl = attention_impl
        self.window = window
        self.moe_experts = moe_experts
        self.moe_capacity_factor = moe_capacity_factor
        # Top-k routing width (ops/moe._route): 1 = Switch (raw-prob
        # combine), ≥2 = standard top-k (probs renormalized over the
        # chosen experts, GShard choice-major capacity priority).
        self.moe_top_k = moe_top_k
        # Switch load-balance + ST-MoE router-z coefficients (ops/moe.MoEAux);
        # both enter the training loss via loss_and_metrics. The defaults are
        # the papers' standard settings (1e-2 balance, 1e-3 z).
        self.moe_balance_coef = moe_balance_coef
        self.moe_z_coef = moe_z_coef
        self.pos_embedding = pos_embedding
        # attention_impl="flash" applies the kernel only at
        # L >= flash_min_len and falls back to the mathematically
        # identical dense path below. None → the ONE measured crossover
        # shared by every model (ops/pallas_attention.FLASH_MIN_LEN — its
        # comment has the numbers and the re-measure tool), resolved
        # LAZILY at forward time (models/base.resolve_flash_min_len) so
        # xla models never import Pallas; 0 forces the kernel at every
        # length (tests do, to exercise it at toy L).
        self.flash_min_len = flash_min_len
        # jax.checkpoint around each scanned block: activation memory drops
        # from O(num_layers · L · d) to O(L · d) + one block's recompute per
        # layer in the backward — the standard long-context memory/FLOPs
        # trade (the reference never needed it: 784-feature MLP).
        #
        # Round 13 widens the knob into a POLICY surface:
        #   True        — plain jax.checkpoint (recompute everything);
        #   "selective" — jax.checkpoint with save_only_these_names over
        #                 the flash-attention out+lse (O(B·L·d) to store
        #                 vs the O(L²)-work kernel recompute); only the
        #                 layernorm/QKV/MLP half of each block replays.
        #                 Grad-identical to True (pinned in test_gpt.py).
        #                 WHEN IT WINS: MXU-sized rows with the flash
        #                 kernel engaged (d≈2048, L ≥ flash_min_len),
        #                 where the measured backward is three near-equal
        #                 forwards and the recompute third is mostly
        #                 attention (docs/benchmarks/lm_phases.md). Toy
        #                 widths — and any config on the dense-attention
        #                 fallback — should keep remat=True: there the
        #                 saved tensors cost more HBM than the recompute
        #                 costs FLOPs (the round-4 dots-saveable probe
        #                 lost to plain remat the same way).
        #   callable    — passed straight to jax.checkpoint(policy=...).
        # Every forward path (scanned stack, sp/ep bodies, pipeline
        # stages) routes through _remat_wrap, so the policy reaches every
        # dp_mode. The shard_map sp ring does not thread the save names —
        # "selective" there degrades to plain remat semantics (correct,
        # no savings).
        if not (
            isinstance(remat, bool)
            or remat == "selective"
            or callable(remat)
        ):
            raise ValueError(
                f"remat must be False, True, 'selective', or a "
                f"jax.checkpoint policy callable; got {remat!r}"
            )
        self.remat = remat
        # Opt-in low-precision projection matmuls (ops/quantized.py):
        # None | "int8" | "fp8". Covers the block QKV/out projections and
        # the dense FFN pair wherever the model runs (training forward,
        # prefill, decode) — NOT the logits head (tied embedding, kept at
        # compute_dtype) and NOT MoE expert matmuls (ops/moe keeps its
        # own dtype discipline). Forward in the reduced dtype with
        # dynamic symmetric scales, backward straight-through at full
        # precision; the contract is the synthetic-corpus loss-parity
        # guard in tests/test_quantized.py. TUNNEL-TPU claim until the
        # chip rerun: int8 is the v5e MXU's native double-rate regime.
        if matmul_dtype is not None:
            from distributed_tensorflow_tpu.ops.quantized import (
                MATMUL_DTYPES,
            )

            if matmul_dtype not in MATMUL_DTYPES:
                raise ValueError(
                    f"unknown matmul_dtype {matmul_dtype!r}; None or one "
                    f"of {MATMUL_DTYPES}"
                )
        self.matmul_dtype = matmul_dtype
        # Rounds 18-19: which implementation serves the single-token
        # decode paths (decode_step / decode_slots / decode_paged) and,
        # with spec_draft, the verify extend (verify_paged).
        #   "xla"    — the unrolled per-op path (rounds 5-15, bitwise
        #              unchanged; the default everywhere off-TPU).
        #   "pallas" — the round-20 megakernel tier
        #              (ops/pallas_decode.py decode_token_* /
        #              verify_tokens_paged): ONE Pallas launch per
        #              token across ALL layers, per-layer weights
        #              streamed through index maps, the KV commit done
        #              in-kernel via aliased cache operands, and the
        #              speculation verify fused for paged decode.
        #   "pallas-layer" — the round-18 per-layer kernel: one launch
        #              per block per token, weights VMEM-resident,
        #              commit via the external XLA scatter. The escape
        #              hatch + parity oracle for "pallas" (the
        #              round-13 fused-vs-split pattern); verify stays
        #              on XLA.
        #   Both pallas variants are refused LOUDLY at construction/
        #   call time for unsupported configs (MoE FFNs, quantized
        #   projection weights, layers too wide for VMEM) instead of
        #   silently degrading.
        #   "auto"   — the megakernel on TPU when the config is
        #              supported, else xla (off-TPU auto is ALWAYS xla:
        #              the interpreter kernels are correctness tools,
        #              not serving paths).
        # Per-call override: decode_*(..., engine=) — TextServer threads
        # its own knob through the chunk scan this way.
        if decode_engine not in DECODE_ENGINES:
            raise ValueError(
                f"unknown decode_engine {decode_engine!r}; one of "
                f"{DECODE_ENGINES}"
            )
        self.decode_engine = decode_engine
        if decode_engine in ("pallas", "pallas-layer"):
            reason = self._decode_unsupported_reason()
            if reason is not None:
                raise ValueError(
                    f"decode_engine={decode_engine!r} unsupported: "
                    f"{reason}"
                )

    # -- init --------------------------------------------------------------

    def init(self, seed: int = 1) -> GPTLMParams:
        d = self.model_dim
        n = self.num_layers
        keys = jax.random.split(jax.random.key(seed), 7)

        def dense_init(key, shape):
            # fan-in scaled; leading num_layers axis gets independent draws
            return jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(
                shape[-2]
            )

        attn = dict(
            ln1_scale=jnp.ones((n, d), jnp.float32),
            ln1_bias=jnp.zeros((n, d), jnp.float32),
            wq=dense_init(keys[2], (n, d, d)),
            # GQA: k/v project to num_kv_heads·head_dim (≤ d); query head
            # groups share KV heads in the attention kernels, and the
            # decode cache shrinks by the same factor.
            wk=dense_init(keys[3], (n, d, self.num_kv_heads * self.head_dim)),
            wv=dense_init(keys[4], (n, d, self.num_kv_heads * self.head_dim)),
            # residual-path projections start at zero: the depth-N stack
            # begins as the identity, a stable start at any depth.
            wo=jnp.zeros((n, d, d), jnp.float32),
            ln2_scale=jnp.ones((n, d), jnp.float32),
            ln2_bias=jnp.zeros((n, d), jnp.float32),
        )
        if self.moe_experts is None:
            blocks = GPTBlockParams(
                **attn,
                w_up=dense_init(keys[5], (n, d, 4 * d)),
                b_up=jnp.zeros((n, 4 * d), jnp.float32),
                w_down=jnp.zeros((n, 4 * d, d), jnp.float32),
                b_down=jnp.zeros((n, d), jnp.float32),
            )
        else:
            e = self.moe_experts
            blocks = GPTMoEBlockParams(
                **attn,
                wg=dense_init(keys[6], (n, d, e)),
                w_up=dense_init(keys[5], (n, e, d, 4 * d)),
                b_up=jnp.zeros((n, e, 4 * d), jnp.float32),
                w_down=jnp.zeros((n, e, 4 * d, d), jnp.float32),
                b_down=jnp.zeros((n, e, d), jnp.float32),
            )
        return GPTLMParams(
            embed=0.02
            * jax.random.normal(keys[0], (self.vocab_size, d), jnp.float32),
            # under rope the table is unused (kept zero so the params
            # pytree, TP specs, and checkpoints are layout-identical
            # across both position schemes)
            pos=(
                0.02
                * jax.random.normal(keys[1], (self.max_len, d), jnp.float32)
                if self.pos_embedding == "learned"
                else jnp.zeros((self.max_len, d), jnp.float32)
            ),
            blocks=blocks,
            lnf_scale=jnp.ones((d,), jnp.float32),
            lnf_bias=jnp.zeros((d,), jnp.float32),
        )

    def partition_specs(self, model_axis: str = "model") -> GPTLMParams:
        """Megatron-style tensor-parallel layout over ``model_axis`` (same
        convention as ``MLP.partition_specs``; every block leaf keeps its
        leading num_layers axis unsharded).

        Attention: wq/wk/wv column-split on their output dim — the split
        lands on whole heads as long as the axis size divides num_heads
        (and, under GQA, num_kv_heads: wk/wv only have num_kv_heads·head_dim
        columns; a mid-KV-head split stays numerically correct under GSPMD
        but loses the whole-head one-all-reduce layout) —
        and wo row-split, so attention computes on local head groups with
        one all-reduce after the output projection. MLP: w_up column-split,
        w_down row-split (all-reduce after). Embeddings, positions, norms,
        and biases on the residual stream stay replicated. Apply by placing
        params with ``NamedSharding(mesh, spec)`` and calling the ordinary
        jitted step — GSPMD inserts the collectives."""
        if self.moe_experts is not None:
            raise NotImplementedError(
                "tensor parallelism is not defined for the MoE blocks; "
                "use expert parallelism (apply_expert_parallel)"
            )
        from jax.sharding import PartitionSpec as P

        return GPTLMParams(
            embed=P(),
            pos=P(),
            blocks=GPTBlockParams(
                ln1_scale=P(),
                ln1_bias=P(),
                wq=P(None, None, model_axis),
                wk=P(None, None, model_axis),
                wv=P(None, None, model_axis),
                wo=P(None, model_axis, None),
                ln2_scale=P(),
                ln2_bias=P(),
                w_up=P(None, None, model_axis),
                b_up=P(None, model_axis),
                w_down=P(None, model_axis, None),
                b_down=P(),
            ),
            lnf_scale=P(),
            lnf_bias=P(),
        )

    # -- shared pieces -----------------------------------------------------

    def _dot_full(self, x, w):
        """compute_dtype matmul with f32 accumulation — the always-full-
        precision dot (the logits/tied-embedding head, and every
        projection when ``matmul_dtype`` is unset)."""
        cd = self.compute_dtype
        return jnp.dot(
            x.astype(cd), w.astype(cd), preferred_element_type=jnp.float32
        )

    def _dot(self, x, w):
        """Block-projection matmul (QKV/out and the dense-FFN pair,
        training AND decode): ``matmul_dtype`` reroutes it through
        :func:`~ops.quantized.quantized_dot` — int8/fp8 forward on the
        MXU's native low-precision path, exact full-precision backward
        (straight-through). The logits head stays on :meth:`_dot_full`
        (quantizing the tied-embedding head measurably hurts loss), and
        MoE expert matmuls stay at compute_dtype (``_moe_block_ffn``
        routes through ops/moe, which the ``matmul_dtype`` contract
        deliberately excludes — see __init__).

        Round 15: a :class:`~ops.quantized.QuantizedLinear` leaf (the
        pre-quantized weight-only serving params from
        :meth:`decode_weights`) routes through
        :func:`~ops.quantized.wo_dot` instead — full-precision
        activations against 1-byte weights, forward-only, the same
        exclusion rule (logits head and MoE experts never carry
        QuantizedLinear leaves)."""
        if isinstance(w, QuantizedLinear):
            return wo_dot(x, w.qw, w.scale, self.compute_dtype)
        if self.matmul_dtype is None:
            return self._dot_full(x, w)
        from distributed_tensorflow_tpu.ops.quantized import quantized_dot

        return quantized_dot(self.matmul_dtype, x, w)

    def decode_weights(self, params: GPTLMParams, dtype: str) -> GPTLMParams:
        """Pre-quantize the decode projection weights ONCE (at restore):
        the block QKV/out projections and — for dense blocks — the FFN
        pair become :class:`~ops.quantized.QuantizedLinear` leaves
        (int8/fp8 payload + per-output-column f32 scales), which
        :meth:`_dot` routes through ``wo_dot`` wherever the returned
        params run. The round-13 exclusion rule holds: the logits head
        (tied embedding) and MoE expert matmuls stay full-precision —
        MoE blocks quantize only their attention projections. Decode
        reads every projection weight per token, so this halves (int8)
        the weight half of decode's HBM traffic; the returned tree is a
        SERVING artifact — it is not trainable (``wo_dot`` is
        forward-only) and not checkpoint-compatible (quantize at restore
        from the full-precision checkpoint, never persist)."""
        from distributed_tensorflow_tpu.ops.quantized import (
            MATMUL_DTYPES,
            quantize_linear_columns,
        )

        if dtype not in MATMUL_DTYPES:
            raise ValueError(
                f"unknown decode weight dtype {dtype!r}; one of "
                f"{MATMUL_DTYPES}"
            )
        names = ("wq", "wk", "wv", "wo")
        if self.moe_experts is None:
            names += ("w_up", "w_down")
        repl = {
            nm: quantize_linear_columns(getattr(params.blocks, nm), dtype)
            for nm in names
        }
        return params._replace(blocks=params.blocks._replace(**repl))

    def _kv_quant_dtype(self, cache) -> str | None:
        """The serving cache's quantized-dtype name ("int8"/"fp8"), or
        None for the bf16 identity layout — derived from the cache
        itself (payload dtype + scale presence), so one model instance
        serves every layout and the default path stays byte-identical
        to round 11."""
        if getattr(cache, "k_scale", None) is None:
            return None
        return "int8" if cache.k.dtype == jnp.int8 else "fp8"

    @property
    def _policy_remat(self) -> bool:
        """Whether ``remat`` is a POLICY mode ("selective" or a callable)
        rather than the plain boolean — the modes under which ``_attend``
        tags the flash forward with checkpoint names."""
        return bool(self.remat) and self.remat is not True

    def _remat_policy(self):
        """The jax.checkpoint policy for the current ``remat`` value, or
        None for the plain (save-nothing) checkpoint."""
        if self.remat == "selective":
            from distributed_tensorflow_tpu.ops.pallas_attention import (
                REMAT_SAVE_NAMES,
            )

            return jax.checkpoint_policies.save_only_these_names(
                *REMAT_SAVE_NAMES
            )
        if callable(self.remat):
            return self.remat
        return None

    def _remat_wrap(self, body):
        """``jax.checkpoint`` around a scanned-block (or pipeline-stage)
        body per the ``remat`` knob — the ONE wrapper every forward path
        uses, so a policy mode reaches dense/sp/ep/pp identically."""
        if not self.remat:
            return body
        policy = self._remat_policy()
        if policy is None:
            return jax.checkpoint(body)
        return jax.checkpoint(body, policy=policy)

    def _attend(self, q, k, v, kv_lens=None):
        from distributed_tensorflow_tpu.models.base import (
            resolve_flash_min_len,
        )

        if self.attention_impl == "flash" and q.shape[1] >= (
            resolve_flash_min_len(self.flash_min_len)
        ):
            from distributed_tensorflow_tpu.ops.pallas_attention import (
                REMAT_SAVE_NAMES,
                flash_attention,
                flash_attention_with_lse,
            )

            if self._policy_remat:
                # Selective remat: name out+lse so the enclosing
                # checkpoint policy saves them and the backward recompute
                # skips the O(L²)-work forward kernel (the rebuild
                # composition — see flash_attention_with_lse). Inert
                # without an enclosing policy (eval/prefill paths).
                out, _ = flash_attention_with_lse(
                    q, k, v, causal=True, window=self.window,
                    kv_lens=kv_lens, save_names=REMAT_SAVE_NAMES,
                )
                return out
            return flash_attention(
                q, k, v, causal=True, window=self.window, kv_lens=kv_lens
            )
        return dense_attention(
            q, k, v, causal=True, window=self.window, kv_lens=kv_lens
        )

    def _embed_tokens(self, params, tokens, positions):
        """Token embedding, plus the learned position table when that
        scheme is active (rope instead rotates q/k inside the blocks).
        Over-length sequences fail loudly here: jnp.take clamps by default,
        which would silently reuse the last table row (the SP path's guard
        comment depends on the dense path raising)."""
        if tokens.ndim > 1 and tokens.shape[1] > self.max_len:
            raise ValueError(
                f"sequence length {tokens.shape[1]} exceeds max_len "
                f"{self.max_len}"
            )
        h = params.embed[tokens]
        if self.pos_embedding == "learned":
            h = h + jnp.take(params.pos, positions, axis=0)
        return h

    def _moe_capacity(self, tokens: int) -> int:
        """Static per-expert capacity for a call with ``tokens`` routable
        tokens (GShard convention: factor × k × tokens/experts, min 1 —
        top-k routes k·tokens dispatches, so capacity scales with k to
        keep ``moe_capacity_factor`` meaning the same headroom at any k)."""
        import math

        return max(
            1,
            math.ceil(
                self.moe_capacity_factor
                * self.moe_top_k
                * tokens
                / self.moe_experts
            ),
        )

    def _moe_block_ffn(self, blk, hn2, moe_call, token_mask=None):
        """Shared MoE-FFN scaffold for the dense and expert-parallel paths:
        token flattening, compute_dtype casting (expert matmuls ride the
        MXU at one bf16 pass like every other matmul here; the gate
        *weights* stay f32 — the activations it sees are compute_dtype like
        everywhere else), and the capacity policy. ``moe_call(mp, x2d, capacity)`` is the only difference
        between the two paths — keeping ep==dense pinned by construction.
        Returns ``(out, aux)`` with the router's :class:`~ops.moe.MoEAux`.

        Capacity: training applies the Switch convention
        (``moe_capacity_factor`` × tokens/experts, drops beyond). Single-
        token calls (the KV-cache decode step, L==1) never drop — capacity
        drops are a training-time load-balancing device, and a decode-time
        drop would make generation diverge from the training forward at the
        default factor (B tokens routed per step vs B·L in training)."""
        cd = self.compute_dtype
        from distributed_tensorflow_tpu.ops.moe import MoEParams

        b, l, d = hn2.shape
        t = b * l
        capacity = t if l == 1 else self._moe_capacity(t)
        mp = MoEParams(
            blk.wg,
            blk.w_up.astype(cd),
            blk.b_up.astype(cd),
            blk.w_down.astype(cd),
            blk.b_down.astype(cd),
        )
        flat_mask = None if token_mask is None else token_mask.reshape(t)
        out, aux = moe_call(
            mp, hn2.reshape(t, d).astype(cd), capacity, flat_mask
        )
        return out.astype(jnp.float32).reshape(b, l, d), aux

    def _ffn(self, blk, hn2, token_mask=None):
        """Dense-FFN or (for MoE blocks) locally-computed switch MoE on
        [B, L, d]; includes the output bias. Returns ``(out, aux)`` —
        aux is the router's MoEAux for MoE blocks, zeros for dense ones
        (so the layer scan carries a uniform pytree either way).
        ``token_mask`` [B, L] bool (ragged batches): pad tokens are
        excluded from MoE routing, capacity, and aux statistics."""
        from distributed_tensorflow_tpu.ops.moe import MoEAux

        if isinstance(blk, GPTMoEBlockParams):
            # moe_ffn_local: E·capacity token-FFNs (the sparse cost MoE
            # exists for); moe_ffn_dense would compute all E experts on all
            # T tokens. Same semantics, proven in tests/test_moe.py.
            from distributed_tensorflow_tpu.ops.moe import moe_ffn_local

            return self._moe_block_ffn(
                blk,
                hn2,
                lambda mp, x, c, m: moe_ffn_local(
                    mp, x, capacity=c, with_aux=True, token_mask=m,
                    k=self.moe_top_k,
                ),
                token_mask,
            )
        out = (
            self._dot(
                jax.nn.gelu(self._dot(hn2, blk.w_up) + blk.b_up), blk.w_down
            )
            + blk.b_down
        )
        return out, MoEAux.zero()

    def _block(self, blk, h, attend=None, ffn=None, positions=None,
               token_mask=None):
        """Block forward; also returns this block's k/v for cache prefill
        and the FFN's router aux (zeros for dense blocks).
        h: [B, L, d]. ``attend``/``ffn`` swap the attention algorithm (the
        sequence-parallel path passes the ring) or the FFN (the
        expert-parallel path passes the all-to-all MoE) without duplicating
        the surrounding layernorm/projection/residual math — one source of
        truth for the block, so sp==dense and ep==dense stay pinned by
        construction."""
        b, l, d = h.shape
        hn = _layernorm(h, blk.ln1_scale, blk.ln1_bias)
        kv_shape = (b, l, self.num_kv_heads, self.head_dim)
        q = self._dot(hn, blk.wq).reshape(b, l, self.num_heads, self.head_dim)
        k = self._dot(hn, blk.wk).reshape(kv_shape)
        v = self._dot(hn, blk.wv).reshape(kv_shape)
        if self.pos_embedding == "rope":
            q = _rope(q, positions)
            k = _rope(k, positions)
        attn = (attend or self._attend)(q, k, v)
        h = h + self._dot(attn.reshape(b, l, d), blk.wo)
        hn2 = _layernorm(h, blk.ln2_scale, blk.ln2_bias)
        if ffn is not None:
            ffn_out, aux = ffn(blk, hn2)
        else:
            ffn_out, aux = self._ffn(blk, hn2, token_mask)
        return h + ffn_out, (k, v), aux

    def _logits(self, p: GPTLMParams, h):
        hf = _layernorm(h, p.lnf_scale, p.lnf_bias)
        return self._dot_full(hf, p.embed.T)

    # -- training forward --------------------------------------------------

    def apply(self, params: GPTLMParams, tokens: jax.Array) -> jax.Array:
        """tokens [B, L] int32 → logits [B, L, vocab], causal."""
        return self.apply_with_aux(params, tokens)[0]

    def apply_with_aux(
        self,
        params: GPTLMParams,
        tokens: jax.Array,
        lengths: jax.Array | None = None,
    ):
        """:meth:`apply` that also returns the per-layer router statistics
        (:class:`~ops.moe.MoEAux` with [num_layers] leaves; all zeros for
        dense models) — the observability surface the training loss and the
        drop-rate metric are built from. ``lengths`` [B] int32 (ragged
        right-padded batches) keeps pad tokens out of MoE routing/capacity
        and the aux statistics, making the MoE forward at real positions —
        and therefore the masked loss — exactly pad-content-independent."""
        l = tokens.shape[1]
        positions = jnp.arange(l)
        token_mask = (
            None
            if lengths is None
            else positions[None, :] < lengths[:, None]  # [B, L]
        )
        h = self._embed_tokens(params, tokens, positions)

        def body(h, blk):
            h, _, aux = self._block(
                blk, h, positions=positions, token_mask=token_mask
            )
            return h, aux

        body = self._remat_wrap(body)
        h, auxs = lax.scan(body, h, params.blocks)
        return self._logits(params, h), auxs

    def apply_sequence_parallel(
        self,
        params: GPTLMParams,
        tokens: jax.Array,
        axis_name: str = "seq",
        *,
        attention: str | None = None,
    ) -> jax.Array:
        """Sequence-parallel causal forward *body*: call inside
        ``jax.shard_map`` with tokens sharded [B, L/n] per device and params
        replicated; returns this device's logits shard [B, L/n, vocab] —
        identical to the matching slice of :meth:`apply` on the gathered
        sequence. ``attention`` is ``"ring"``, ``"ring_flash"`` or
        ``"ulysses"`` (default follows ``attention_impl``, like the
        transformer classifier — whose SP menu this matches; the flash
        variant needs ``check_vma=False`` in the enclosing shard_map
        off-TPU). This is how the LM trains past one device's activation
        memory: L/n tokens of activations per device, KV blocks riding the
        ring — at ``num_kv_heads`` width under GQA (the repeat to Hq never
        crosses a device), and for windowed models only
        ``ceil((W−1)/L_loc)+1`` hops of it (out-of-band blocks never
        move). ``"ulysses"`` instead trades sequence shards for head
        shards in one all-to-all and runs full-sequence attention locally
        per head group (windowed models apply the band mask there); it
        needs the axis size to divide ``num_heads`` AND
        ``num_kv_heads``."""
        if self.moe_experts is not None:
            # Per-shard capacity/routing order would silently diverge from
            # the dense forward under drops (window+SP, by contrast, is
            # implemented exactly — the bounded ring); expert parallelism
            # is the MoE sharding.
            raise NotImplementedError(
                "MoE blocks are not supported on the sequence-parallel "
                "path; use apply_expert_parallel"
            )
        from distributed_tensorflow_tpu.ops.ring_attention import (
            ring_attention,
            ring_flash_attention,
            ulysses_attention,
        )

        if attention is not None and attention not in (
            "ring", "ring_flash", "ulysses"
        ):
            raise ValueError(
                f"unknown attention {attention!r}; ring|ring_flash|ulysses"
            )

        n = lax.axis_size(axis_name)
        my = lax.axis_index(axis_name)
        b, l_loc = tokens.shape
        if attention is None:
            # Default follows attention_impl, honoring the flash_min_len
            # crossover at the PER-SHARD length (the flash ring runs the
            # kernel on l_loc-sized blocks each hop, so l_loc is the
            # length that decides kernel-vs-dense — an explicit
            # attention="ring_flash" still forces the kernel).
            from distributed_tensorflow_tpu.models.base import (
                resolve_flash_min_len,
            )

            attention = (
                "ring_flash"
                if self.attention_impl == "flash"
                and l_loc >= resolve_flash_min_len(self.flash_min_len)
                else "ring"
            )
        if n * l_loc > self.max_len:
            # dynamic_slice would silently CLAMP the positional slice for
            # the last devices (duplicating other shards' positions) where
            # the dense path fails loudly — so fail loudly here too.
            raise ValueError(
                f"global sequence {n * l_loc} exceeds max_len {self.max_len}"
            )
        if attention == "ulysses" and (
            self.num_heads % n or self.num_kv_heads % n
        ):
            raise ValueError(
                f"ulysses needs heads ({self.num_heads}) and kv heads "
                f"({self.num_kv_heads}) divisible by the axis size {n}"
            )
        positions = my * l_loc + jnp.arange(l_loc)  # absolute, so rope and
        h = self._embed_tokens(params, tokens, positions)  # learned agree

        if attention == "ulysses":

            def sp_attend(q, k, v):
                return ulysses_attention(
                    q, k, v, axis_name, causal=True, window=self.window
                )

        else:
            ring = (
                ring_attention if attention == "ring" else ring_flash_attention
            )

            def sp_attend(q, k, v):
                # KV circulates at num_kv_heads width; the ring repeats
                # (XLA ring) or grid-maps (flash ring) locally after each
                # receive.
                return ring(
                    q, k, v, axis_name, causal=True, window=self.window
                )

        def body(h, blk):
            h, _, _ = self._block(blk, h, attend=sp_attend, positions=positions)
            return h, None

        body = self._remat_wrap(body)
        h, _ = lax.scan(body, h, params.blocks)
        return self._logits(params, h)

    def apply_expert_parallel(
        self,
        params: GPTLMParams,
        tokens: jax.Array,
        axis_name: str = "expert",
        *,
        with_aux: bool = False,
        lengths: jax.Array | None = None,
    ) -> jax.Array:
        """Expert-parallel causal forward *body* (MoE models): call inside
        ``jax.shard_map`` with tokens sharded on the BATCH dim [B/n, L] and
        the blocks' expert dims sharded over ``axis_name`` (one expert's
        FFN weights per device; gate and attention weights replicated).
        Attention runs locally on the batch shard; each block's FFN is the
        all-to-all token exchange (``ops/moe.moe_ffn``). Routing (top-1)
        is identical to :meth:`apply`; capacity is applied per
        (expert, source device) here vs per expert globally there, so the
        two are exactly equal whenever no token overflows capacity (ample
        ``moe_capacity_factor``) and may drop different tokens under
        overflow — drops are a training-time load-balancing device, not a
        semantic guarantee. ``with_aux=True`` also returns per-layer
        :class:`~ops.moe.MoEAux` over this device's local tokens — its
        ``drop_fraction`` is the observable guard on the no-drop-regime
        claim above (pmean it over ``axis_name`` for the global rate).
        ``lengths`` [B/n] int32 (this shard's rows of a ragged right-padded
        batch) keeps pad tokens out of MoE routing/capacity and the aux
        statistics, exactly as :meth:`apply_with_aux` does in the dense
        path — EP ragged training is pad-content-independent too."""
        if self.moe_experts is None:
            raise ValueError("apply_expert_parallel requires moe_experts")
        n = lax.axis_size(axis_name)
        if n != self.moe_experts:
            raise ValueError(
                f"{axis_name!r} axis size {n} != moe_experts "
                f"{self.moe_experts}"
            )
        from distributed_tensorflow_tpu.ops.moe import moe_ffn

        l = tokens.shape[1]
        positions = jnp.arange(l)
        token_mask = (
            None
            if lengths is None
            else positions[None, :] < lengths[:, None]  # [B/n, L]
        )

        def ep_ffn(blk, hn2):
            return self._moe_block_ffn(
                blk,
                hn2,
                lambda mp, x, c, m: moe_ffn(
                    mp, x, axis_name, capacity=c, with_aux=True,
                    token_mask=m, k=self.moe_top_k,
                ),
                token_mask,
            )

        h = self._embed_tokens(params, tokens, positions)

        def body(h, blk):
            h, _, aux = self._block(blk, h, ffn=ep_ffn, positions=positions)
            return h, aux

        body = self._remat_wrap(body)
        h, auxs = lax.scan(body, h, params.blocks)
        logits = self._logits(params, h)
        return (logits, auxs) if with_aux else logits

    def pipeline_stage_blocks(self, blocks, num_stages: int):
        """Reshape the scanned [num_layers, ...] block stack into
        [num_stages, layers_per_stage, ...] for stage-sharding (leading dim
        over the ``stage`` mesh axis) — the layout
        :meth:`apply_pipeline_parallel` consumes."""
        if self.num_layers % num_stages:
            raise ValueError(
                f"num_layers {self.num_layers} not divisible by "
                f"num_stages {num_stages}"
            )
        lps = self.num_layers // num_stages
        return jax.tree.map(
            lambda a: a.reshape((num_stages, lps) + a.shape[1:]), blocks
        )

    def _pp_stage_fn(self):
        """One pipeline stage's forward — the ONE stage body shared by
        :meth:`apply_pipeline_parallel` and :func:`make_lm_pp_train_step`
        (a divergence would silently break their proven forward equality):
        the stage's contiguous layer group ([1, layers_per_stage, ...]
        leaves) scanned exactly like :meth:`apply`, ``jax.checkpoint``-ed
        when ``remat`` (backward recomputes one stage group per tick
        instead of stashing every tick's activations)."""

        def stage_fn(blk_stack, x):
            positions = jnp.arange(x.shape[1])

            def body(h, blk):
                h, _, _ = self._block(blk, h, positions=positions)
                return h, None

            h, _ = lax.scan(body, x, jax.tree.map(lambda a: a[0], blk_stack))
            return h

        return self._remat_wrap(stage_fn)

    def apply_pipeline_parallel(
        self,
        params: GPTLMParams,
        tokens: jax.Array,
        axis_name: str = "stage",
        *,
        num_microbatches: int = 4,
    ) -> jax.Array:
        """Pipeline-parallel causal forward *body*: call inside
        ``jax.shard_map`` over the ``stage`` axis with ``params.blocks`` in
        :meth:`pipeline_stage_blocks` layout sharded on its leading dim
        (each device holds one stage's contiguous layer group [1, n/S, ...])
        and everything else — embed/pos/lnf and tokens [B, L] — replicated.
        Embedding and the LM head are computed on every stage (cheap,
        replicated); the block stack runs as a GPipe-microbatched pipeline
        (``parallel/pipeline.py``): activations flow stage-to-stage over
        single ppermute hops, ``num_microbatches`` microbatches keep all
        stages busy after the fill. Returns logits [B, L, vocab], identical
        to :meth:`apply` — the flagship-model composition PARITY.md §2b's
        PP row promises (the reference has no stages at all, SURVEY.md
        §2b)."""
        if self.moe_experts is not None:
            raise NotImplementedError(
                "pipeline parallelism is not defined for MoE blocks; use "
                "expert parallelism (apply_expert_parallel)"
            )
        from distributed_tensorflow_tpu.parallel.pipeline import (
            microbatch,
            pipeline_apply,
        )

        b, l = tokens.shape
        h = self._embed_tokens(params, tokens, jnp.arange(l))
        hm = microbatch(h, num_microbatches)  # [M, B/M, L, d]
        out = pipeline_apply(self._pp_stage_fn(), params.blocks, hm, axis_name)
        return self._logits(params, out.reshape(b, l, -1))

    def loss(
        self,
        params: GPTLMParams,
        tokens: jax.Array,
        lengths: jax.Array | None = None,
    ) -> jax.Array:
        """Training loss: mean next-token cross-entropy (positions 0..L-2
        predict 1..L-1, f32 log-softmax), plus — for MoE models — the
        Switch load-balance and router-z auxiliary terms behind
        ``moe_balance_coef`` / ``moe_z_coef``. Dense models: exactly CE.

        ``lengths`` [B] int32 (each ≥ 1) makes the CE a *masked* mean for
        right-padded ragged batches: only targets at positions < lengths[b]
        count. Causal attention keeps pad tokens out of real positions'
        logits, and ``lengths`` is also threaded into MoE routing (pads
        never consume expert capacity or enter the aux statistics) — so
        ragged-batch training is exactly pad-content-independent for dense
        AND MoE models (proven in test_gpt.py); the attention ops
        additionally accept ``kv_lens`` for non-causal uses."""
        return self.loss_and_metrics(params, tokens, lengths)[0]

    def loss_and_metrics(
        self,
        params: GPTLMParams,
        tokens: jax.Array,
        lengths: jax.Array | None = None,
    ) -> tuple[jax.Array, dict]:
        """(total loss, metrics dict). Metrics always include ``ce``; MoE
        models add ``balance_loss`` / ``z_loss`` (layer means entering the
        total) and ``drop_fraction`` (pure metric, NOT in the loss — the
        observable no-drop-regime guard)."""
        logits, auxs = self.apply_with_aux(params, tokens, lengths)
        ce = _ce_from_logits(logits, tokens, lengths)
        metrics = {"ce": ce}
        if self.moe_experts is None:
            return ce, metrics
        balance = jnp.mean(auxs.balance_loss)
        z = jnp.mean(auxs.z_loss)
        metrics.update(
            balance_loss=balance,
            z_loss=z,
            drop_fraction=jnp.mean(auxs.drop_fraction),
            # [E]: dispatch distribution averaged over layers — the direct
            # utilization readout (uniform = 1/E everywhere).
            expert_fraction=jnp.mean(auxs.expert_fraction, axis=0),
        )
        total = ce + self.moe_balance_coef * balance + self.moe_z_coef * z
        return total, metrics

    # -- KV-cache decoding -------------------------------------------------

    def _decode_unsupported_reason(self) -> str | None:
        """Why the fused Pallas decode kernel cannot serve this model
        CONFIG, or None when it can. Static (config-only) half of the
        support check; the params half (weight-only quantized trees) is
        :meth:`_resolve_decode_engine`'s, because params arrive at call
        time. Supported: dense FFN blocks, MHA/GQA, full or sliding
        window (rolling slab and absolute paged layouts), learned or
        rope positions, bf16/int8/fp8 KV caches."""
        if self.moe_experts is not None:
            return (
                "MoE blocks route through ops/moe (expert dispatch is not "
                "a single-launch shape); serve MoE models on the XLA "
                "engine"
            )
        if self.matmul_dtype is not None:
            return (
                "matmul_dtype projections route through "
                "ops/quantized.quantized_dot; the fused kernel runs "
                "compute-dtype weights only"
            )
        d = self.model_dim
        elem = jnp.dtype(self.compute_dtype).itemsize
        attn_bytes = (
            d * d + 2 * d * self.num_kv_heads * self.head_dim + d * d
        ) * elem
        ffn_bytes = 8 * d * d * elem
        weight_bytes = attn_bytes + ffn_bytes
        if weight_bytes > _DECODE_VMEM_WEIGHT_CAP:
            return (
                f"one layer's weights ({weight_bytes} B at compute dtype: "
                f"attention {attn_bytes} B + FFN {ffn_bytes} B) exceed the "
                f"fused kernels' per-layer VMEM cap "
                f"({_DECODE_VMEM_WEIGHT_CAP} B = "
                f"{_DECODE_VMEM_WEIGHT_CAP >> 20} MiB) — the megakernel "
                "streams one layer at a time and the per-layer kernel "
                "holds one block, so the bound is per LAYER either way; "
                "the XLA engine streams weights from HBM instead"
            )
        return None

    def _resolve_decode_engine(self, engine: str | None, params) -> str:
        """Resolve the per-call ``engine`` override (None → the model's
        ``decode_engine`` knob) to one of the three CONCRETE engines
        "pallas" (megakernel tier) / "pallas-layer" (per-layer kernel)
        / "xla". Either pallas variant with an unsupported config/params
        RAISES (a serving deployment must not silently run a different
        engine than it asked for); "auto" is the megakernel only on a
        real TPU backend with a supported config — off-TPU auto always
        resolves to xla (pinned in tests/test_pallas_decode.py)."""
        e = self.decode_engine if engine is None else engine
        if e not in DECODE_ENGINES:
            raise ValueError(
                f"unknown decode engine {e!r}; one of {DECODE_ENGINES}"
            )
        if e == "xla":
            return "xla"
        reason = self._decode_unsupported_reason()
        if reason is None and any(
            isinstance(getattr(params.blocks, nm, None), QuantizedLinear)
            for nm in ("wq", "wk", "wv", "wo", "w_up", "w_down")
        ):
            reason = (
                "weight-only quantized decode params (QuantizedLinear "
                "leaves from decode_weights) route through wo_dot; the "
                "fused kernels run compute-dtype weights only"
            )
        if e in ("pallas", "pallas-layer"):
            if reason is not None:
                raise ValueError(
                    f"decode_engine={e!r} unsupported: {reason}"
                )
            return e
        # auto
        if reason is not None or jax.default_backend() != "tpu":
            return "xla"
        return "pallas"

    def _commit_slot_rows(
        self, ck0, cv0, ks0, vs0, kq, vq, ksc, vsc, lengths, act
    ):
        """The ONE slab fresh-row commit (per-row scatter at
        ``lengths % C`` / ``lengths``; inactive rows write their old
        value back — a no-op) — shared by the XLA engine
        (``_decode_block_slots``) and the fused Pallas engine
        (``_decode_slots_pallas``), so the two engines write identical
        caches BY CONSTRUCTION, not by copy discipline. ``kq``/``vq``
        [S, Hkv, Dh] storage-dtype rows, ``ksc``/``vsc`` [S, Hkv] f32
        scales or None (bf16 layout). Returns (ck, cv, nks, nvs)."""
        rows = jnp.arange(ck0.shape[0])
        c = self.cache_len
        slot = lengths % c if self.window is not None else lengths
        kw = jnp.where(act[:, None, None], kq, ck0[rows, slot])
        vw = jnp.where(act[:, None, None], vq, cv0[rows, slot])
        ck = ck0.at[rows, slot].set(kw)
        cv = cv0.at[rows, slot].set(vw)
        if ks0 is None:
            return ck, cv, None, None
        nks = ks0.at[rows, slot].set(
            jnp.where(act[:, None], ksc, ks0[rows, slot])
        )
        nvs = vs0.at[rows, slot].set(
            jnp.where(act[:, None], vsc, vs0[rows, slot])
        )
        return ck, cv, nks, nvs

    def _commit_paged_rows(
        self, pk, pv, pks, pvs, kq, vq, ksc, vsc, tables, lengths, act
    ):
        """The ONE paged fresh-row commit (scatter through the block
        tables at position ``lengths[s]``; inactive rows drop at the
        sentinel) — shared by the XLA engine (``_decode_block_paged``)
        and the fused Pallas engine (``_decode_paged_pallas``), same
        by-construction guarantee as :meth:`_commit_slot_rows`.
        Row/scale shapes as there. Returns (nk, nv, nks, nvs)."""
        from distributed_tensorflow_tpu.ops import paged_attention as paged

        pos = lengths[:, None]
        valid = act[:, None]
        nk = paged.scatter_token_kv(pk, kq[:, None], tables, pos, valid)
        nv = paged.scatter_token_kv(pv, vq[:, None], tables, pos, valid)
        if pks is None:
            return nk, nv, None, None
        nks = paged.scatter_token_kv(pks, ksc[:, None], tables, pos, valid)
        nvs = paged.scatter_token_kv(pvs, vsc[:, None], tables, pos, valid)
        return nk, nv, nks, nvs

    def _decode_kernel_weights(self, blk) -> dict:
        """One layer's raw (f32) block weights as the plain dict
        ops/pallas_decode consumes (cast + layout happen inside the
        launch builder)."""
        return {
            nm: getattr(blk, nm)
            for nm in (
                "ln1_scale", "ln1_bias", "wq", "wk", "wv", "wo",
                "ln2_scale", "ln2_bias", "w_up", "b_up", "w_down", "b_down",
            )
        }

    @property
    def cache_len(self) -> int:
        """Static KV-cache length per layer: ``min(window, max_len)`` for
        windowed models (rolling buffer — older keys are unreachable by the
        sliding-window mask), else ``max_len``."""
        if self.window is not None:
            return min(self.window, self.max_len)
        return self.max_len

    def prefill(self, params: GPTLMParams, tokens: jax.Array):
        """Run the prompt once, returning (last-position logits [B, vocab],
        cache holding every layer's prompt k/v). Windowed models keep only
        the last ``cache_len`` prompt positions, each at slot ``pos mod
        cache_len`` — the rolling layout :meth:`decode_step` writes."""
        b, l = tokens.shape
        positions = jnp.arange(l)
        h = self._embed_tokens(params, tokens, positions)

        def body(h, blk):
            h, kv, _ = self._block(blk, h, positions=positions)
            return h, kv

        h, (ks, vs) = lax.scan(body, h, params.blocks)
        ks = ks.astype(self.compute_dtype)
        vs = vs.astype(self.compute_dtype)
        c = self.cache_len
        if l <= c:
            pad = [(0, 0), (0, 0), (0, c - l), (0, 0), (0, 0)]
            # Positions land at slot pos % c = pos (l <= c): plain pad.
            ck, cv = jnp.pad(ks, pad), jnp.pad(vs, pad)
        else:
            # Rolling: keep the last c positions at slots pos % c (static
            # index arrays — l and c are compile-time).
            ps = np.arange(l - c, l)
            slots = ps % c
            shape = ks.shape[:2] + (c,) + ks.shape[3:]
            ck = jnp.zeros(shape, ks.dtype).at[:, :, slots].set(ks[:, :, ps])
            cv = jnp.zeros(shape, vs.dtype).at[:, :, slots].set(vs[:, :, ps])
        cache = KVCache(k=ck, v=cv, length=jnp.asarray(l, jnp.int32))
        return self._logits(params, h)[:, -1], cache

    def _decode_block(self, blk: GPTBlockParams, h, ck, cv, length):
        """Single-token block step. h: [B, 1, d]; ck/cv: [B, cache_len, Hkv,
        Dh] (this layer's cache). Returns (h, updated ck, updated cv)."""
        b = h.shape[0]
        c = self.cache_len
        hn = _layernorm(h, blk.ln1_scale, blk.ln1_bias)
        kv_shape = (b, 1, self.num_kv_heads, self.head_dim)
        q = self._dot(hn, blk.wq).reshape(b, 1, self.num_heads, self.head_dim)
        k = self._dot(hn, blk.wk).reshape(kv_shape)
        v = self._dot(hn, blk.wv).reshape(kv_shape)
        if self.pos_embedding == "rope":
            pos1 = jnp.reshape(length, (1,))
            q = _rope(q, pos1)
            k = _rope(k, pos1)
        k = k.astype(ck.dtype)
        v = v.astype(cv.dtype)
        slot = length % c if self.window is not None else length
        ck = lax.dynamic_update_slice(ck, k, (0, slot, 0, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, slot, 0, 0))
        # Attend the one query against the whole static-length cache,
        # masking invalid slots. GQA runs WITHOUT materializing the head
        # repeat: q groups to [B, Hkv, g, Dh] (group_query_heads — the one
        # canonical q-head→KV-head mapping, shared with repeat_kv and the
        # flash grid maps) and both einsums contract against the Hkv-head
        # cache directly — per-step temporaries stay at KV width, the same
        # factor the cache itself saves (round-2 weak spot: the old path
        # repeated the cache to Hq every step).
        from distributed_tensorflow_tpu.ops.ring_attention import (
            group_query_heads,
        )

        qg = group_query_heads(q[:, 0], self.num_kv_heads)
        scores = jnp.einsum(
            "bhgd,bkhd->bhgk", qg, ck, preferred_element_type=jnp.float32
        ) / jnp.sqrt(jnp.asarray(self.head_dim, jnp.float32))
        idx = jnp.arange(c)
        if self.window is not None:
            # Rolling buffer: slot i holds absolute position
            # length − ((slot − i) mod c) ∈ (length − c, length] — by
            # construction exactly the window (self included), so the only
            # invalid slots are the not-yet-written ones (negative
            # position). No ≤ length or > length − W test needed.
            slot_pos = length - jnp.mod(slot - idx, c)
            valid = slot_pos >= 0
        else:
            valid = idx <= length  # [cache_len]
        scores = jnp.where(valid[None, None, None, :], scores, -1e30)
        w = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum(
            "bhgk,bkhd->bhgd",
            w.astype(cv.dtype),
            cv,
            preferred_element_type=jnp.float32,
        ).reshape(b, 1, self.num_heads, self.head_dim)
        h = h + self._dot(attn.reshape(b, 1, self.model_dim), blk.wo)
        hn2 = _layernorm(h, blk.ln2_scale, blk.ln2_bias)
        ffn_out, _ = self._ffn(blk, hn2)  # aux unused: decode never drops
        return h + ffn_out, ck, cv

    def decode_step(
        self,
        params: GPTLMParams,
        token: jax.Array,
        cache: KVCache,
        *,
        engine: str | None = None,
    ):
        """Append one token [B] int32; returns (logits [B, vocab], cache).

        The cache is full at ``length == max_len``; stepping past it would
        silently clamp (``dynamic_update_slice`` semantics) and corrupt the
        last slot, so eager calls raise instead. Under a trace the length is
        abstract — loop drivers must bound their own trip count the way
        :meth:`greedy_decode` does.

        The layer loop is UNROLLED, not a ``lax.scan`` (round-5 decode
        fix): with the stacked cache as scan xs/ys, XLA double-buffers the
        whole cache every token instead of updating one slot in place —
        measured 939 µs/token vs 306 unrolled for an MHA cache at c=1024,
        and 2311 vs 191 at c=4096 (tools/lm_bench.py decode table; the old
        "15× decode-full cliff" was this, not physics — unrolled, config
        gaps match their cache-traffic ratios). Decode graphs are tiny
        (~20 ops/layer, forward-only), so unrolling costs no meaningful
        compile time; :meth:`prefill` and training keep their scans.

        ``engine`` (rounds 18+20, default: the model's ``decode_engine``
        knob): "pallas" runs the WHOLE step as ONE megakernel launch
        (weights streamed per layer, KV commit in-kernel);
        "pallas-layer" runs each block as one fused launch with the
        external scatter commit — same math either way."""
        if not isinstance(cache.length, jax.core.Tracer):
            if int(cache.length) >= self.max_len:
                raise ValueError(
                    f"KV cache full: length {int(cache.length)} == max_len "
                    f"{self.max_len}; increase max_len"
                )
        h = self._embed_tokens(
            params, token[:, None], jnp.reshape(cache.length, (1,))
        )
        eng = self._resolve_decode_engine(engine, params)
        if eng == "pallas":
            from distributed_tensorflow_tpu.ops.pallas_decode import (
                decode_token_slab,
            )

            b = token.shape[0]
            lengths = jnp.broadcast_to(
                jnp.asarray(cache.length, jnp.int32), (b,)
            )
            hr, nk, nv, _, _ = decode_token_slab(
                h[:, 0], self._decode_kernel_weights(params.blocks),
                cache.k, cache.v, None, None, lengths,
                jnp.ones((b,), jnp.int32),
                num_heads=self.num_heads, window=self.window,
                kv_dtype="bf16", compute_dtype=self.compute_dtype,
                rope=self.pos_embedding == "rope",
            )
            new_cache = KVCache(k=nk, v=nv, length=cache.length + 1)
            return self._logits(params, hr[:, None])[:, 0], new_cache
        if eng == "pallas-layer":
            from distributed_tensorflow_tpu.ops.pallas_decode import (
                decode_block_slab,
            )

            b = token.shape[0]
            c = self.cache_len
            lengths = jnp.broadcast_to(
                jnp.asarray(cache.length, jnp.int32), (b,)
            )
            slot = cache.length % c if self.window is not None else cache.length
            hr = h[:, 0]
            nks, nvs = [], []
            for i in range(self.num_layers):
                blk = jax.tree.map(lambda x: x[i], params.blocks)
                hr, kq, vq, _, _ = decode_block_slab(
                    hr, self._decode_kernel_weights(blk),
                    cache.k[i], cache.v[i], None, None, lengths,
                    num_heads=self.num_heads, window=self.window,
                    kv_dtype="bf16", compute_dtype=self.compute_dtype,
                    rope=self.pos_embedding == "rope",
                )
                # Commit with the XLA engine's exact index math (the
                # scalar-slot dynamic_update_slice of _decode_block).
                nks.append(
                    lax.dynamic_update_slice(
                        cache.k[i], kq[:, None], (0, slot, 0, 0)
                    )
                )
                nvs.append(
                    lax.dynamic_update_slice(
                        cache.v[i], vq[:, None], (0, slot, 0, 0)
                    )
                )
            new_cache = KVCache(
                k=jnp.stack(nks), v=jnp.stack(nvs), length=cache.length + 1
            )
            return self._logits(params, hr[:, None])[:, 0], new_cache
        nks, nvs = [], []
        for i in range(self.num_layers):
            blk = jax.tree.map(lambda x: x[i], params.blocks)
            h, ck, cv = self._decode_block(
                blk, h, cache.k[i], cache.v[i], cache.length
            )
            nks.append(ck)
            nvs.append(cv)
        new_cache = KVCache(
            k=jnp.stack(nks), v=jnp.stack(nvs), length=cache.length + 1
        )
        return self._logits(params, h)[:, 0], new_cache

    # -- slot-wise decoding (the serving surface, serve.py) ----------------

    def empty_slot_cache(
        self, slots: int, kv_dtype: str = "bf16"
    ) -> SlotKVCache:
        """A vacant ``slots``-row :class:`SlotKVCache` (lengths all zero —
        a zero-length slot is FREE; the decode mask treats only written
        positions as attendable, so vacant rows compute well-defined
        garbage that the scheduler never reads). ``kv_dtype`` picks the
        storage layout: "bf16" stores compute_dtype with no scales (the
        default, bitwise round-9); int8/fp8 store 1-byte payloads plus
        the per-row scale side tensors."""
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        shape = (
            self.num_layers,
            slots,
            self.cache_len,
            self.num_kv_heads,
            self.head_dim,
        )
        z = jnp.zeros(shape, kv_storage_dtype(kv_dtype, self.compute_dtype))
        sc = (
            None
            if kv_dtype == "bf16"
            else jnp.zeros(shape[:-1], jnp.float32)
        )
        return SlotKVCache(
            k=z,
            v=z,
            lengths=jnp.zeros((slots,), jnp.int32),
            k_scale=sc,
            v_scale=sc,
        )

    def reset_slots(self, cache: SlotKVCache, free: jax.Array) -> SlotKVCache:
        """Mark slots FREE (``free`` [S] bool): their lengths drop to 0.
        K/V content is left in place — stale bytes are unreachable because
        the decode validity mask ignores everything past ``lengths``, and
        a :meth:`prefill_slots` admit overwrites the row wholesale.
        ``serve.py``'s scheduler tracks vacancy host-side (its ``finished``
        flag) and re-arms through the admit merge alone; this is the
        explicit in-graph vacancy op for external schedulers that keep
        slot state on device (pinned content-independent in
        tests/test_serve.py)."""
        return cache._replace(
            lengths=jnp.where(free, 0, cache.lengths)
        )

    def prefill_slots(
        self,
        params: GPTLMParams,
        cache: SlotKVCache,
        tokens: jax.Array,
        lengths: jax.Array,
        admit: jax.Array,
    ):
        """Batched ragged prefill INTO slots: run the prompt block [S, L]
        (right-padded rows, real lengths in ``lengths`` [S]) once, and for
        every row with ``admit[s]`` True replace slot s's cache with the
        prompt's K/V and its length — rows with ``admit`` False keep their
        existing state bit-for-bit (they are mid-generation in other
        slots' requests). Returns (per-row logits at each row's LAST REAL
        position [S, vocab], updated cache).

        Pad positions are kept out of everything that could leak into real
        rows: attention masks keys ≥ lengths (``kv_lens``, both attention
        impls), MoE routing/capacity sees only real tokens (``lengths``
        threading, as in :meth:`apply_with_aux`), and the returned logits
        are gathered at ``lengths-1``. For a prompt at exactly L the masks
        are no-ops and the math is :meth:`prefill`'s — the serving parity
        contract (pinned in tests/test_serve.py). One compiled executable
        per (S, L) shape: serve.py pads prompts to a small set of length
        BUCKETS so the compile count stays bounded."""
        s, l = tokens.shape
        c = self.cache_len
        positions = jnp.arange(l)
        token_mask = positions[None, :] < lengths[:, None]  # [S, L]
        qd = self._kv_quant_dtype(cache)

        def attend(q, k, v):
            if qd is not None:
                # Uniform quantized-cache rule (see extend_paged): the
                # prompt's own K/V are round-tripped before the softmax
                # so the prefill scores over exactly the values the
                # cache write below stores — decode re-reading these
                # positions sees the same math this pick saw.
                k = dequantize_kv(*quantize_kv(k, qd), self.compute_dtype)
                v = dequantize_kv(*quantize_kv(v, qd), self.compute_dtype)
            return self._attend(q, k, v, kv_lens=lengths)

        h = self._embed_tokens(params, tokens, positions)

        def body(h, blk):
            h, kv, _ = self._block(
                blk,
                h,
                attend=attend,
                positions=positions,
                token_mask=token_mask,
            )
            return h, kv

        h, (ks, vs) = lax.scan(body, h, params.blocks)
        if qd is None:
            ks = ks.astype(self.compute_dtype)  # [n, S, L, Hkv, Dh]
            vs = vs.astype(self.compute_dtype)
            ksc = vsc = None
        else:
            # Quantize-on-write (round 15): payload rows plus the per-
            # (position, head) scale side tensors, which follow the same
            # pad/rolling relayout minus the lane axis.
            ks, ksc = quantize_kv(ks, qd)  # [n,S,L,Hkv,Dh] + [n,S,L,Hkv]
            vs, vsc = quantize_kv(vs, qd)
        if l <= c:
            # Every prompt position p < lengths[s] <= c lands at slot
            # p % c = p: plain pad (the same layout prefill() writes).
            pad = [(0, 0), (0, 0), (0, c - l), (0, 0), (0, 0)]
            nk, nv = jnp.pad(ks, pad), jnp.pad(vs, pad)
            if qd is not None:
                nksc = jnp.pad(ksc, pad[:-1])
                nvsc = jnp.pad(vsc, pad[:-1])
        else:
            # Rolling window (c < L): per ROW, keep that row's last
            # min(c, len) real positions at slots p % c. Cache slot j
            # holds the largest prompt position p < len with p ≡ j
            # (mod c): p = j + c·⌊(len−1−j)/c⌋ — per-row dynamic, unlike
            # prefill()'s static arrays, because each row has its own len.
            idx = jnp.arange(c)[None, :]  # [1, c]
            p = idx + c * ((lengths[:, None] - 1 - idx) // c)  # [S, c]
            gather = jnp.clip(p, 0, l - 1)[None, :, :, None, None]
            nk = jnp.take_along_axis(ks, gather, axis=2)
            nv = jnp.take_along_axis(vs, gather, axis=2)
            if qd is not None:
                nksc = jnp.take_along_axis(ksc, gather[..., 0], axis=2)
                nvsc = jnp.take_along_axis(vsc, gather[..., 0], axis=2)
            # p < 0 rows (len <= j and no earlier wrap) hold garbage —
            # unreachable: the decode mask derives validity from lengths.
        m = admit[None, :, None, None, None]
        new_cache = SlotKVCache(
            k=jnp.where(m, nk, cache.k),
            v=jnp.where(m, nv, cache.v),
            lengths=jnp.where(admit, lengths, cache.lengths),
            k_scale=(
                None
                if qd is None
                else jnp.where(m[..., 0], nksc, cache.k_scale)
            ),
            v_scale=(
                None
                if qd is None
                else jnp.where(m[..., 0], nvsc, cache.v_scale)
            ),
        )
        h_last = jnp.take_along_axis(
            h, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1
        )  # [S, 1, d]
        return self._logits(params, h_last)[:, 0], new_cache

    def _decode_block_step(self, blk, h, lengths, cache_update):
        """Shared per-slot single-token block math (layernorm / QKV /
        rope / GQA attention / FFN) for BOTH single-token decode cache
        layouts. ``cache_update(k, v)`` owns everything layout-specific:
        it commits the fresh K/V row ([S, 1, Hkv, Dh]) to its cache,
        returns the per-slot contiguous K/V to attend over
        ([S, C, Hkv, Dh] each), the validity mask [S, C], and the
        updated cache state threaded back to the caller. Keeping the
        math in ONE body is what keeps the slab and paged paths in
        lockstep (their bitwise equality is pinned by test_gpt.py /
        test_serve.py parity tests)."""
        from distributed_tensorflow_tpu.ops.ring_attention import (
            group_query_heads,
        )

        s = h.shape[0]
        hn = _layernorm(h, blk.ln1_scale, blk.ln1_bias)
        kv_shape = (s, 1, self.num_kv_heads, self.head_dim)
        q = self._dot(hn, blk.wq).reshape(s, 1, self.num_heads, self.head_dim)
        k = self._dot(hn, blk.wk).reshape(kv_shape)
        v = self._dot(hn, blk.wv).reshape(kv_shape)
        if self.pos_embedding == "rope":
            pos = lengths[:, None]  # [S, 1] — per-row absolute position
            q = _rope(q, pos)
            k = _rope(k, pos)
        ck, cv, valid, state = cache_update(k, v)
        qg = group_query_heads(q[:, 0], self.num_kv_heads)
        scores = jnp.einsum(
            "shgd,skhd->shgk", qg, ck, preferred_element_type=jnp.float32
        ) / jnp.sqrt(jnp.asarray(self.head_dim, jnp.float32))
        scores = jnp.where(valid[:, None, None, :], scores, -1e30)
        w = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum(
            "shgk,skhd->shgd",
            w.astype(cv.dtype),
            cv,
            preferred_element_type=jnp.float32,
        ).reshape(s, 1, self.num_heads, self.head_dim)
        h = h + self._dot(attn.reshape(s, 1, self.model_dim), blk.wo)
        hn2 = _layernorm(h, blk.ln2_scale, blk.ln2_bias)
        ffn_out, _ = self._ffn(blk, hn2)  # aux unused: decode never drops
        return h + ffn_out, state

    def _decode_block_slots(
        self, blk, h, ck0, cv0, lengths, act, ks0=None, vs0=None, qd=None
    ):
        """Per-slot single-token block step — :meth:`_decode_block` with a
        VECTOR of positions: h [S, 1, d], ck0/cv0 [S, cache_len, Hkv, Dh],
        ``lengths`` [S] (each row's write position), ``act`` [S] bool
        (inactive rows write their old K/V back — a no-op — and their
        outputs are garbage the caller discards). Row-wise math is
        _decode_block's exactly (pinned by test_serve.py's token-parity
        tests); the scalar ``dynamic_update_slice`` becomes a per-row
        scatter and the validity mask broadcasts per row. Quantized
        caches (``qd`` + ks0/vs0 scale rows) quantize the fresh row on
        write and attend the dequantized view — same math, fewer bytes
        resident."""
        c = self.cache_len

        def cache_update(k, v):
            slot = lengths % c if self.window is not None else lengths
            if qd is None:
                kq, vq = k.astype(ck0.dtype)[:, 0], v.astype(cv0.dtype)[:, 0]
                ksc = vsc = None
            else:
                kq, ksc = quantize_kv(k[:, 0], qd)  # [S,Hkv,Dh] + [S,Hkv]
                vq, vsc = quantize_kv(v[:, 0], qd)
            # The shared commit (round 18: also the Pallas engine's) —
            # per-row scatter, inactive rows writing their old value
            # back.
            ck, cv, nks, nvs = self._commit_slot_rows(
                ck0, cv0, ks0, vs0, kq, vq, ksc, vsc, lengths, act
            )
            state = (ck, cv, nks, nvs)
            if qd is None:
                ck_att, cv_att = ck, cv
            else:
                # Dequantize to compute_dtype, NOT f32: a f32 view would
                # double the compute-side intermediate and push the MXU
                # onto its multi-pass f32 path — the bandwidth win this
                # cache exists for (int8's |q| ≤ 127 and every e4m3
                # value upcast to bf16 exactly, so the pow2 equality
                # oracles survive the narrower view).
                ck_att = dequantize_kv(ck, nks, self.compute_dtype)
                cv_att = dequantize_kv(cv, nvs, self.compute_dtype)
            idx = jnp.arange(c)[None, :]  # [1, c]
            if self.window is not None:
                # Same rolling-buffer identity as _decode_block, per row.
                slot_pos = lengths[:, None] - jnp.mod(slot[:, None] - idx, c)
                valid = slot_pos >= 0  # [S, c]
            else:
                valid = idx <= lengths[:, None]  # [S, c]
            return ck_att, cv_att, valid, state

        h, state = self._decode_block_step(blk, h, lengths, cache_update)
        return h, state

    def decode_slots(
        self,
        params: GPTLMParams,
        token: jax.Array,
        cache: SlotKVCache,
        active: jax.Array | None = None,
        *,
        engine: str | None = None,
    ):
        """Append one token per SLOT: token [S] int32 at each slot's own
        position. Returns (logits [S, vocab], cache with ``lengths``
        advanced where active). ``active`` [S] bool masks rows out of the
        update entirely (their cache row and length are untouched and
        their logits are garbage to discard) — finished/vacant slots ride
        along at full batch shape, which is what keeps ONE compiled
        executable serving every occupancy pattern. Layer loop UNROLLED
        for the same cache-double-buffering reason as :meth:`decode_step`.

        Stepping an ACTIVE row past ``max_len`` would corrupt its newest
        cache slot (scatter clamp semantics), so eager calls raise, as in
        :meth:`decode_step`; traced callers bound their own trip count
        (serve.py budgets every admit so prompt+generation fits)."""
        act = (
            jnp.ones((token.shape[0],), bool) if active is None else active
        )
        if not isinstance(cache.lengths, jax.core.Tracer) and not isinstance(
            act, jax.core.Tracer
        ):
            worst = int(jnp.max(jnp.where(act, cache.lengths, 0)))
            if bool(jnp.any(act)) and worst >= self.max_len:
                raise ValueError(
                    f"KV cache full: an active slot is at length {worst} == "
                    f"max_len {self.max_len}; increase max_len"
                )
        h = self._embed_tokens(
            params, token[:, None], cache.lengths[:, None]
        )
        qd = self._kv_quant_dtype(cache)
        eng = self._resolve_decode_engine(engine, params)
        if eng == "pallas":
            return self._decode_slots_mega(params, h, cache, act, qd)
        if eng == "pallas-layer":
            return self._decode_slots_pallas(params, h, cache, act, qd)
        nks, nvs, nksc, nvsc = [], [], [], []
        for i in range(self.num_layers):
            blk = jax.tree.map(lambda x: x[i], params.blocks)
            h, (ck, cv, ksc, vsc) = self._decode_block_slots(
                blk, h, cache.k[i], cache.v[i], cache.lengths, act,
                None if qd is None else cache.k_scale[i],
                None if qd is None else cache.v_scale[i],
                qd,
            )
            nks.append(ck)
            nvs.append(cv)
            nksc.append(ksc)
            nvsc.append(vsc)
        new_cache = SlotKVCache(
            k=jnp.stack(nks),
            v=jnp.stack(nvs),
            lengths=cache.lengths + act.astype(jnp.int32),
            k_scale=None if qd is None else jnp.stack(nksc),
            v_scale=None if qd is None else jnp.stack(nvsc),
        )
        return self._logits(params, h)[:, 0], new_cache

    def _decode_slots_pallas(self, params, h, cache, act, qd):
        """Fused-kernel half of :meth:`decode_slots`: one
        ``ops/pallas_decode.decode_block_slab`` launch per layer, then
        the fresh row committed through :meth:`_commit_slot_rows` — the
        SAME helper the XLA engine's ``cache_update`` calls, so the two
        engines' caches (and therefore their token streams) stay in
        step by construction."""
        from distributed_tensorflow_tpu.ops.pallas_decode import (
            decode_block_slab,
        )

        lengths = cache.lengths
        hr = h[:, 0]  # [S, d]
        nks, nvs, nksc, nvsc = [], [], [], []
        for i in range(self.num_layers):
            blk = jax.tree.map(lambda x: x[i], params.blocks)
            ck0, cv0 = cache.k[i], cache.v[i]
            ks0 = None if qd is None else cache.k_scale[i]
            vs0 = None if qd is None else cache.v_scale[i]
            hr, kq, vq, ksc, vsc = decode_block_slab(
                hr, self._decode_kernel_weights(blk), ck0, cv0, ks0, vs0,
                lengths,
                num_heads=self.num_heads, window=self.window,
                kv_dtype=qd or "bf16", compute_dtype=self.compute_dtype,
                rope=self.pos_embedding == "rope",
            )
            ck, cv, ksn, vsn = self._commit_slot_rows(
                ck0, cv0, ks0, vs0, kq, vq, ksc, vsc, lengths, act
            )
            nks.append(ck)
            nvs.append(cv)
            nksc.append(ksn)
            nvsc.append(vsn)
        new_cache = SlotKVCache(
            k=jnp.stack(nks),
            v=jnp.stack(nvs),
            lengths=lengths + act.astype(jnp.int32),
            k_scale=None if qd is None else jnp.stack(nksc),
            v_scale=None if qd is None else jnp.stack(nvsc),
        )
        return self._logits(params, hr[:, None])[:, 0], new_cache

    def _decode_slots_mega(self, params, h, cache, act, qd):
        """Megakernel half of :meth:`decode_slots` (round 20): ONE
        ``ops/pallas_decode.decode_token_slab`` launch covers every
        layer AND the fresh-row commit — the cache arrays come back
        written at the same indices :meth:`_commit_slot_rows` scatters
        to (inactive rows skip in-kernel, the scatter's no-op,
        bit-for-bit); only the logits head stays XLA (round-13 rule)."""
        from distributed_tensorflow_tpu.ops.pallas_decode import (
            decode_token_slab,
        )

        hr, nk, nv, nks, nvs = decode_token_slab(
            h[:, 0], self._decode_kernel_weights(params.blocks),
            cache.k, cache.v,
            None if qd is None else cache.k_scale,
            None if qd is None else cache.v_scale,
            cache.lengths, act.astype(jnp.int32),
            num_heads=self.num_heads, window=self.window,
            kv_dtype=qd or "bf16", compute_dtype=self.compute_dtype,
            rope=self.pos_embedding == "rope",
        )
        new_cache = SlotKVCache(
            k=nk, v=nv,
            lengths=cache.lengths + act.astype(jnp.int32),
            k_scale=nks, v_scale=nvs,
        )
        return self._logits(params, hr[:, None])[:, 0], new_cache

    # -- paged decoding (block-table cache, serve.py paged=True) -----------

    def paged_blocks_per_slot(self, block_size: int) -> int:
        """Static block-table width: blocks to address ``max_len``
        positions (the table is sized for the worst request; the POOL is
        what paging shrinks)."""
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        return -(-self.max_len // block_size)

    def empty_paged_cache(
        self,
        slots: int,
        num_blocks: int,
        block_size: int = 16,
        kv_dtype: str = "bf16",
    ) -> PagedKVCache:
        """A vacant :class:`PagedKVCache`: ``num_blocks`` pool blocks of
        ``block_size`` positions each (the HBM actually reserved —
        compare the slab's ``slots × cache_len``), all-zero block tables
        (garbage mappings, unreachable while lengths are 0). Windowed
        models keep FULL history here — the paged layout addresses
        absolutely and windows by mask, trading the rolling buffer's
        O(W) bound for block sharing (``serve_pool.PrefixCache``).
        ``kv_dtype="int8"|"fp8"`` shrinks every pool block to 1-byte
        elements with per-row scale side pools — the serving engine
        derives MORE blocks from the same HBM budget
        (``serve_pool.blocks_for_hbm_bytes``)."""
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        nb_slot = self.paged_blocks_per_slot(block_size)
        shape = (
            self.num_layers,
            num_blocks,
            block_size,
            self.num_kv_heads,
            self.head_dim,
        )
        z = jnp.zeros(shape, kv_storage_dtype(kv_dtype, self.compute_dtype))
        sc = (
            None
            if kv_dtype == "bf16"
            else jnp.zeros(shape[:-1], jnp.float32)
        )
        return PagedKVCache(
            k=z,
            v=z,
            block_tables=jnp.zeros((slots, nb_slot), jnp.int32),
            lengths=jnp.zeros((slots,), jnp.int32),
            k_scale=sc,
            v_scale=sc,
        )

    def extend_paged(
        self,
        params: GPTLMParams,
        cache: PagedKVCache,
        tokens: jax.Array,
        suffix_lens: jax.Array,
        prefix_lens: jax.Array,
        admit: jax.Array,
    ):
        """Batched ragged EXTEND through the block tables: run suffix
        block ``tokens`` [S, L] (right-padded rows, real lengths
        ``suffix_lens`` [S]) at absolute positions
        ``prefix_lens[s] + 0..L-1``, attending each suffix query over the
        slot's cached prefix (read through its block table) plus the
        suffix itself causally, and scatter the suffix K/V into the pool
        where ``admit``. Returns (per-position logits [S, L, vocab],
        cache with K/V written). ``lengths``/``block_tables`` are NOT
        touched — the caller owns commit semantics, because the two
        callers commit differently: admission prefill commits
        ``prefix + suffix`` wholesale, the speculative verify graph
        commits only ``accepted + 1`` tokens (rejected drafts' K/V stay
        as unreachable garbage past ``lengths`` and are overwritten by
        the next write at that position).

        ``prefix_lens = 0`` is plain ragged prefill (the paged analog of
        :meth:`prefill_slots`); block-aligned nonzero prefixes are the
        prefix-cache hit path — the shared system prompt's K/V is read,
        never recomputed. The caller guarantees every written position
        ``< prefix + suffix ≤`` the slot's reserved table extent (the
        engine budgets ``prompt + max_new`` blocks at admission)."""
        from distributed_tensorflow_tpu.ops import paged_attention as paged

        s, l = tokens.shape
        positions = prefix_lens[:, None] + jnp.arange(l)[None, :]  # [S, L]
        token_mask = jnp.arange(l)[None, :] < suffix_lens[:, None]
        h = self._embed_tokens(params, tokens, positions)
        qd = self._kv_quant_dtype(cache)

        def make_attend(pk, pv, pks, pvs):
            def attend(q, k, v):
                kview = paged.gather_block_view(pk, cache.block_tables)
                vview = paged.gather_block_view(pv, cache.block_tables)
                if qd is not None:
                    # Dequantize-on-read: the scale side pools gather
                    # through the SAME tables (identical index math,
                    # one fewer axis), so cached-prefix K/V arrive as
                    # values. The suffix's own fresh k/v are ROUND-
                    # TRIPPED through the same quantizer before the
                    # softmax — attention must see exactly the values
                    # the scatter below will store, or a token scored
                    # here (the speculative verify, a prefill pick)
                    # could differ from the same position re-scored by
                    # decode_paged reading the cache; the uniform rule
                    # "a quantized cache attends quantized values
                    # EVERYWHERE" is what keeps spec == non-spec and
                    # paged == slab token-identical.
                    kview = dequantize_kv(
                        kview,
                        paged.gather_block_view(pks, cache.block_tables),
                        self.compute_dtype,
                    )
                    vview = dequantize_kv(
                        vview,
                        paged.gather_block_view(pvs, cache.block_tables),
                        self.compute_dtype,
                    )
                    k = dequantize_kv(*quantize_kv(k, qd), self.compute_dtype)
                    v = dequantize_kv(*quantize_kv(v, qd), self.compute_dtype)
                return paged.paged_extend_attention(
                    q, k, v, kview, vview, positions, prefix_lens,
                    suffix_lens, window=self.window,
                )

            return attend

        def body(h, xs):
            blk, pk, pv = xs[0], xs[1], xs[2]
            pks, pvs = (xs[3], xs[4]) if qd is not None else (None, None)
            h, kv, _ = self._block(
                blk, h, attend=make_attend(pk, pv, pks, pvs),
                positions=positions, token_mask=token_mask,
            )
            return h, kv

        xs_all = (params.blocks, cache.k, cache.v)
        if qd is not None:
            xs_all += (cache.k_scale, cache.v_scale)
        h, (ks, vs) = lax.scan(body, h, xs_all)
        valid = token_mask & admit[:, None]
        if qd is None:
            ks = ks.astype(cache.k.dtype)  # [n, S, L, Hkv, Dh]
            vs = vs.astype(cache.v.dtype)
            nksc, nvsc = cache.k_scale, cache.v_scale
        else:
            ks, ksc = quantize_kv(ks, qd)  # + [n, S, L, Hkv] scales
            vs, vsc = quantize_kv(vs, qd)
            nksc = paged.scatter_token_kv_all_layers(
                cache.k_scale, ksc, cache.block_tables, positions, valid
            )
            nvsc = paged.scatter_token_kv_all_layers(
                cache.v_scale, vsc, cache.block_tables, positions, valid
            )
        nk = paged.scatter_token_kv_all_layers(
            cache.k, ks, cache.block_tables, positions, valid
        )
        nv = paged.scatter_token_kv_all_layers(
            cache.v, vs, cache.block_tables, positions, valid
        )
        return self._logits(params, h), cache._replace(
            k=nk, v=nv, k_scale=nksc, v_scale=nvsc
        )

    def verify_paged(
        self,
        params: GPTLMParams,
        cache: PagedKVCache,
        tokens: jax.Array,
        suffix_lens: jax.Array,
        prefix_lens: jax.Array,
        admit: jax.Array,
        *,
        engine: str | None = None,
    ):
        """The speculation-verify EXTEND (round 20): exactly
        :meth:`extend_paged`'s contract — (per-position logits
        [S, L, vocab], cache with K/V written, lengths/tables
        caller-owned) — but engine-dispatched the way the decode paths
        are. "pallas" runs ``ops/pallas_decode.verify_tokens_paged``:
        ONE launch across all layers with the suffix causal block
        folded into the online softmax and the valid rows committed
        in-kernel (logits head stays XLA, round-13 rule). "xla" and
        "pallas-layer" delegate to :meth:`extend_paged` verbatim (the
        per-layer kernel has no multi-row step — XLA verify is its
        pairing, and the parity oracle for the fused one). Greedy-exact
        acceptance rides on the shared round-15 round-trip rule: both
        engines attend exactly the values the cache stores."""
        eng = self._resolve_decode_engine(engine, params)
        if eng != "pallas":
            return self.extend_paged(
                params, cache, tokens, suffix_lens, prefix_lens, admit
            )
        from distributed_tensorflow_tpu.ops.pallas_decode import (
            verify_tokens_paged,
        )

        s, l = tokens.shape
        positions = prefix_lens[:, None] + jnp.arange(l)[None, :]
        h = self._embed_tokens(params, tokens, positions)
        qd = self._kv_quant_dtype(cache)
        hr, nk, nv, nks, nvs = verify_tokens_paged(
            h, self._decode_kernel_weights(params.blocks),
            cache.k, cache.v,
            None if qd is None else cache.k_scale,
            None if qd is None else cache.v_scale,
            cache.block_tables, prefix_lens, suffix_lens,
            admit.astype(jnp.int32),
            num_heads=self.num_heads, window=self.window,
            kv_dtype=qd or "bf16", compute_dtype=self.compute_dtype,
            rope=self.pos_embedding == "rope",
        )
        return self._logits(params, hr), cache._replace(
            k=nk, v=nv, k_scale=nks, v_scale=nvs
        )

    def _decode_block_paged(self, blk, h, pk, pv, block_tables, lengths,
                            act, pks=None, pvs=None, qd=None):
        """Per-slot single-token block step against the BLOCK POOL —
        :meth:`_decode_block_slots` with the slab row replaced by a
        scatter-then-gather through the block tables: the fresh K/V row
        lands at ``(table[s, len // bs], len % bs)`` (inactive rows drop
        at the sentinel), then the slot's contiguous view is gathered
        back and attended with the same ``idx <= lengths`` validity.
        Windowed models band by mask (``idx > lengths − W``) — absolute
        addressing, no rolling arithmetic. Quantized pools (``qd`` +
        pks/pvs scale pools) quantize the fresh row before its scatter
        and dequantize the gathered view before the softmax — the scale
        pools ride the same scatter/gather index math."""
        from distributed_tensorflow_tpu.ops import paged_attention as paged

        def cache_update(k, v):
            if qd is None:
                kq = k.astype(pk.dtype)[:, 0]
                vq = v.astype(pv.dtype)[:, 0]
                ksc = vsc = None
            else:
                kq, ksc = quantize_kv(k[:, 0], qd)  # [S,Hkv,Dh] + [S,Hkv]
                vq, vsc = quantize_kv(v[:, 0], qd)
            # The shared commit (round 18: also the Pallas engine's) —
            # scatter through the block tables, inactive rows dropping
            # at the sentinel.
            nk, nv, nks, nvs = self._commit_paged_rows(
                pk, pv, pks, pvs, kq, vq, ksc, vsc, block_tables,
                lengths, act,
            )
            state = (nk, nv, nks, nvs)
            ck = paged.gather_block_view(nk, block_tables)  # [S, C, Hkv, Dh]
            cv = paged.gather_block_view(nv, block_tables)
            if qd is not None:
                # compute_dtype view, not f32 (see _decode_block_slots).
                ck = dequantize_kv(
                    ck,
                    paged.gather_block_view(nks, block_tables),
                    self.compute_dtype,
                )
                cv = dequantize_kv(
                    cv,
                    paged.gather_block_view(nvs, block_tables),
                    self.compute_dtype,
                )
            idx = jnp.arange(ck.shape[1])[None, :]  # [1, C] absolute
            valid = idx <= lengths[:, None]  # [S, C]
            if self.window is not None:
                valid &= idx > lengths[:, None] - self.window
            return ck, cv, valid, state

        h, state = self._decode_block_step(blk, h, lengths, cache_update)
        return h, state

    def decode_paged(
        self,
        params: GPTLMParams,
        token: jax.Array,
        cache: PagedKVCache,
        active: jax.Array | None = None,
        *,
        engine: str | None = None,
    ):
        """Append one token per slot through the block tables — the
        paged counterpart of :meth:`decode_slots` (same masking
        contract: inactive rows untouched, garbage logits to discard;
        layer loop UNROLLED for the same double-buffering reason).
        The caller guarantees each active slot's table covers position
        ``lengths[s]`` (the engine reserves ``prompt + max_new`` blocks
        at admission, so generation never outgrows the table)."""
        act = (
            jnp.ones((token.shape[0],), bool) if active is None else active
        )
        if not isinstance(cache.lengths, jax.core.Tracer) and not isinstance(
            act, jax.core.Tracer
        ):
            worst = int(jnp.max(jnp.where(act, cache.lengths, 0)))
            if bool(jnp.any(act)) and worst >= self.max_len:
                raise ValueError(
                    f"KV cache full: an active slot is at length {worst} == "
                    f"max_len {self.max_len}; increase max_len"
                )
        h = self._embed_tokens(
            params, token[:, None], cache.lengths[:, None]
        )
        qd = self._kv_quant_dtype(cache)
        eng = self._resolve_decode_engine(engine, params)
        if eng == "pallas":
            return self._decode_paged_mega(params, h, cache, act, qd)
        if eng == "pallas-layer":
            return self._decode_paged_pallas(params, h, cache, act, qd)
        nks, nvs, nksc, nvsc = [], [], [], []
        for i in range(self.num_layers):
            blk = jax.tree.map(lambda x: x[i], params.blocks)
            h, (pk, pv, pks, pvs) = self._decode_block_paged(
                blk, h, cache.k[i], cache.v[i], cache.block_tables,
                cache.lengths, act,
                None if qd is None else cache.k_scale[i],
                None if qd is None else cache.v_scale[i],
                qd,
            )
            nks.append(pk)
            nvs.append(pv)
            nksc.append(pks)
            nvsc.append(pvs)
        new_cache = cache._replace(
            k=jnp.stack(nks),
            v=jnp.stack(nvs),
            lengths=cache.lengths + act.astype(jnp.int32),
            k_scale=None if qd is None else jnp.stack(nksc),
            v_scale=None if qd is None else jnp.stack(nvsc),
        )
        return self._logits(params, h)[:, 0], new_cache

    def _decode_paged_pallas(self, params, h, cache, act, qd):
        """Fused-kernel half of :meth:`decode_paged`: one
        ``ops/pallas_decode.decode_block_paged`` launch per layer (the
        block tables ride as scalar-prefetch args — the pool is read
        block-by-block in the grid, no contiguous ``gather_block_view``
        copy), then the fresh row committed through
        :meth:`_commit_paged_rows` — the SAME helper the XLA engine's
        ``cache_update`` calls, so both engines write identical pools
        by construction."""
        from distributed_tensorflow_tpu.ops.pallas_decode import (
            decode_block_paged,
        )

        lengths = cache.lengths
        tables = cache.block_tables
        hr = h[:, 0]  # [S, d]
        nks, nvs, nksc, nvsc = [], [], [], []
        for i in range(self.num_layers):
            blk = jax.tree.map(lambda x: x[i], params.blocks)
            pk, pv = cache.k[i], cache.v[i]
            pks = None if qd is None else cache.k_scale[i]
            pvs = None if qd is None else cache.v_scale[i]
            hr, kq, vq, ksc, vsc = decode_block_paged(
                hr, self._decode_kernel_weights(blk), pk, pv, pks, pvs,
                tables, lengths,
                num_heads=self.num_heads, window=self.window,
                kv_dtype=qd or "bf16", compute_dtype=self.compute_dtype,
                rope=self.pos_embedding == "rope",
            )
            nk, nv, ksn, vsn = self._commit_paged_rows(
                pk, pv, pks, pvs, kq, vq, ksc, vsc, tables, lengths, act
            )
            nks.append(nk)
            nvs.append(nv)
            nksc.append(ksn)
            nvsc.append(vsn)
        new_cache = cache._replace(
            k=jnp.stack(nks),
            v=jnp.stack(nvs),
            lengths=lengths + act.astype(jnp.int32),
            k_scale=None if qd is None else jnp.stack(nksc),
            v_scale=None if qd is None else jnp.stack(nvsc),
        )
        return self._logits(params, hr[:, None])[:, 0], new_cache

    def _decode_paged_mega(self, params, h, cache, act, qd):
        """Megakernel half of :meth:`decode_paged` (round 20): ONE
        ``ops/pallas_decode.decode_token_paged`` launch covers every
        layer and commits the fresh rows through the block tables
        in-kernel (inactive rows issue no DMA — the
        ``scatter_token_kv`` sentinel-drop, bit-for-bit; the sentinel
        itself never materializes)."""
        from distributed_tensorflow_tpu.ops.pallas_decode import (
            decode_token_paged,
        )

        hr, nk, nv, nks, nvs = decode_token_paged(
            h[:, 0], self._decode_kernel_weights(params.blocks),
            cache.k, cache.v,
            None if qd is None else cache.k_scale,
            None if qd is None else cache.v_scale,
            cache.block_tables, cache.lengths, act.astype(jnp.int32),
            num_heads=self.num_heads, window=self.window,
            kv_dtype=qd or "bf16", compute_dtype=self.compute_dtype,
            rope=self.pos_embedding == "rope",
        )
        new_cache = cache._replace(
            k=nk, v=nv,
            lengths=cache.lengths + act.astype(jnp.int32),
            k_scale=nks, v_scale=nvs,
        )
        return self._logits(params, hr[:, None])[:, 0], new_cache

    def _check_decode_bounds(self, prompt, max_new):
        """Shared generation-length validation (every decode entry point:
        greedy / sampled / beam)."""
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        if prompt.shape[1] + max_new > self.max_len:
            raise ValueError(
                f"prompt {prompt.shape[1]} + max_new {max_new} exceeds "
                f"max_len {self.max_len}"
            )

    def _decode_loop(self, params, prompt, max_new, pick, key):
        """Shared generation scaffold: prefill, then one ``lax.scan`` of
        decode steps, each choosing the next token via ``pick(logits, key)``
        (greedy ignores the key). Returns [B, L0 + max_new]."""
        self._check_decode_bounds(prompt, max_new)
        logits, cache = self.prefill(params, prompt)
        key, sub = jax.random.split(key)
        first = pick(logits, sub)

        def body(carry, _):
            tok, cache, key = carry
            logits, cache = self.decode_step(params, tok, cache)
            key, sub = jax.random.split(key)
            nxt = pick(logits, sub)
            return (nxt, cache, key), nxt

        if max_new > 1:
            _, rest = lax.scan(
                body, (first, cache, key), None, length=max_new - 1
            )
            generated = jnp.concatenate([first[None], rest], axis=0).swapaxes(
                0, 1
            )
        else:
            generated = first[:, None]
        return jnp.concatenate([prompt, generated], axis=1)

    def greedy_decode(
        self, params: GPTLMParams, prompt: jax.Array, max_new: int
    ) -> jax.Array:
        """[B, L0] prompt → [B, L0 + max_new] (``max_new`` ≥ 1); the whole
        generation loop is one ``lax.scan`` (jit it once, no host
        round-trips per token)."""

        def pick(logits, _key):
            return jnp.argmax(logits, axis=-1).astype(prompt.dtype)

        return self._decode_loop(
            params, prompt, max_new, pick, jax.random.key(0)
        )

    def sample_decode(
        self,
        params: GPTLMParams,
        prompt: jax.Array,
        max_new: int,
        key: jax.Array,
        *,
        temperature: float = 1.0,
        top_k: int | None = None,
        top_p: float | None = None,
    ) -> jax.Array:
        """Stochastic counterpart of :meth:`greedy_decode`: categorical
        sampling from ``logits/temperature``, optionally truncated to the
        ``top_k`` highest-probability tokens and/or the ``top_p`` nucleus
        (smallest prefix of the probability-sorted vocabulary whose mass
        reaches p — Holtzman et al.'s nucleus sampling; applied after
        ``top_k`` when both are set, the usual composition). Same
        one-``lax.scan`` shape — the PRNG key rides the carry, so
        generation stays fully on-device and reproducible per key.
        ``top_k=1`` is exactly greedy; ``top_p=1.0`` keeps everything."""
        if temperature <= 0:
            raise ValueError(f"temperature must be > 0, got {temperature}")
        if top_k is not None and not 1 <= top_k <= self.vocab_size:
            raise ValueError(
                f"top_k must be in [1, {self.vocab_size}], got {top_k}"
            )
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")

        def pick(logits, k):
            logits = logits.astype(jnp.float32) / temperature
            if top_k is not None:
                # Scatter the top_k entries onto a -inf canvas: exactly
                # top_k candidates survive even on exact logit ties (a
                # >= kth threshold would keep every token tied with the
                # k-th — plausible at low-entropy bf16 logits).
                vals, idx = lax.top_k(logits, top_k)
                rows = jnp.arange(logits.shape[0])[:, None]
                logits = jnp.full_like(logits, -jnp.inf).at[rows, idx].set(vals)
            if top_p is not None and top_p < 1.0:
                # Keep tokens whose EXCLUSIVE cumulative probability (mass
                # strictly ahead of them in sorted order) is < p: the
                # smallest prefix reaching p mass, never empty (the top
                # token's exclusive mass is 0), and the boundary token
                # that crosses p is kept — the standard nucleus rule.
                # Scatter the keep mask back through the sort order (not a
                # >=-threshold test, which would re-admit tokens exactly
                # tied with the boundary — the same tie hazard the top_k
                # scatter above avoids).
                order = jnp.argsort(logits, axis=-1)[..., ::-1]
                sorted_l = jnp.take_along_axis(logits, order, axis=-1)
                probs = jax.nn.softmax(sorted_l, axis=-1)
                keep_sorted = jnp.cumsum(probs, axis=-1) - probs < top_p
                rows = jnp.arange(logits.shape[0])[:, None]
                keep = (
                    jnp.zeros(logits.shape, bool).at[rows, order]
                    .set(keep_sorted)
                )
                logits = jnp.where(keep, logits, -jnp.inf)
            return jax.random.categorical(k, logits, axis=-1).astype(
                prompt.dtype
            )

        return self._decode_loop(params, prompt, max_new, pick, key)

    def beam_decode(
        self,
        params: GPTLMParams,
        prompt: jax.Array,
        max_new: int,
        beam_size: int,
        *,
        eos_id: int | None = None,
        length_penalty: float = 0.0,
    ) -> jax.Array:
        """Beam search over the KV cache: keep the ``beam_size`` highest
        log-probability continuations at every step, all beams advancing
        in ONE batched decode (the cache runs at batch B·K; beam
        reordering is a gather on its batch dim), the whole search one
        ``lax.scan`` like the samplers. Returns the best beam per row,
        [B, L0 + max_new].

        ``eos_id``: a beam that emits it is finished — it only extends
        with further ``eos_id`` tokens at zero cost (its score freezes),
        so the returned row is the sequence followed by EOS padding.
        ``length_penalty`` α ranks final beams by ``score / len_gen**α``
        (α=0 — the default — is pure summed log-probability; α>0 favors
        longer finished sequences, the usual normalization); ``len_gen``
        counts generated tokens up to and including the first EOS.

        ``beam_size=1`` is exactly :meth:`greedy_decode`. The first
        expansion seeds at most ``vocab_size`` distinct beams (top-k of
        one distribution), so ``beam_size`` must be ≤ ``vocab_size``."""
        b, l0 = prompt.shape
        kbeams = beam_size
        self._check_decode_bounds(prompt, max_new)
        if not 1 <= kbeams <= self.vocab_size:
            raise ValueError(
                f"beam_size must be in [1, {self.vocab_size}], got {kbeams}"
            )
        if eos_id is not None and not 0 <= eos_id < self.vocab_size:
            raise ValueError(
                f"eos_id must be in [0, {self.vocab_size}), got {eos_id}"
            )
        v = self.vocab_size

        logits, cache = self.prefill(params, prompt)
        logp0 = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        scores, tok = lax.top_k(logp0, kbeams)  # [B, K]
        tok = tok.astype(prompt.dtype)
        cache = KVCache(
            k=jnp.repeat(cache.k, kbeams, axis=1),
            v=jnp.repeat(cache.v, kbeams, axis=1),
            length=cache.length,
        )
        seqs = jnp.zeros((b, kbeams, max_new), prompt.dtype)
        seqs = seqs.at[:, :, 0].set(tok)
        finished = (
            tok == eos_id
            if eos_id is not None
            else jnp.zeros((b, kbeams), bool)
        )

        def body(carry, t):
            seqs, scores, finished, cache, tok = carry
            step_logits, cache = self.decode_step(
                params, tok.reshape(b * kbeams), cache
            )
            logp = jax.nn.log_softmax(
                step_logits.astype(jnp.float32), axis=-1
            ).reshape(b, kbeams, v)
            if eos_id is not None:
                # Finished beams extend only with EOS, at zero cost.
                only_eos = jnp.full((v,), -jnp.inf).at[eos_id].set(0.0)
                logp = jnp.where(finished[..., None], only_eos, logp)
            flat = (scores[..., None] + logp).reshape(b, kbeams * v)
            scores, idx = lax.top_k(flat, kbeams)
            parent = idx // v  # [B, K] — which beam each winner extends
            tok = (idx % v).astype(prompt.dtype)
            flat_parent = (
                jnp.arange(b)[:, None] * kbeams + parent
            ).reshape(b * kbeams)
            cache = KVCache(
                k=jnp.take(cache.k, flat_parent, axis=1),
                v=jnp.take(cache.v, flat_parent, axis=1),
                length=cache.length,
            )
            seqs = jnp.take_along_axis(seqs, parent[..., None], axis=1)
            seqs = lax.dynamic_update_slice(seqs, tok[..., None], (0, 0, t))
            finished = jnp.take_along_axis(finished, parent, axis=1)
            if eos_id is not None:
                finished = finished | (tok == eos_id)
            return (seqs, scores, finished, cache, tok), None

        if max_new > 1:
            (seqs, scores, finished, _, _), _ = lax.scan(
                body,
                (seqs, scores, finished, cache, tok),
                jnp.arange(1, max_new),
            )
        # Rank beams: generated length = up to and including first EOS.
        if eos_id is not None and length_penalty != 0.0:
            is_eos = seqs == eos_id
            first_eos = jnp.argmax(is_eos, axis=-1)  # 0 when none
            has_eos = jnp.any(is_eos, axis=-1)
            gen_len = jnp.where(has_eos, first_eos + 1, max_new)
        else:
            gen_len = jnp.full((b, kbeams), max_new)
        ranked = scores / jnp.maximum(
            gen_len.astype(jnp.float32), 1.0
        ) ** jnp.float32(length_penalty)
        best = jnp.argmax(ranked, axis=-1)  # [B]
        best_seq = jnp.take_along_axis(
            seqs, best[:, None, None], axis=1
        )[:, 0]
        return jnp.concatenate([prompt, best_seq], axis=1)


def export_kv_blocks(cache: PagedKVCache, block_ids) -> dict:
    """Lift the named pool blocks out of a :class:`PagedKVCache` as host
    arrays — the wire half of the round-23 prefill→decode handoff. The
    payload carries the EXACT storage-dtype bytes (bf16, or the int8/fp8
    1-byte elements plus their per-row f32 scale side tensors at the
    same block coordinates), so an import followed by attention
    reproduces the source replica's dequantized values bit-for-bit (the
    round-15 uniform rule is what makes the migrated stream
    token-identical). ``block_ids`` must be valid pool indices — export
    has no sentinel (you cannot export a block you never wrote).

    Returns ``{"k", "v"[, "k_scale", "v_scale"]}`` with payload shape
    ``[num_layers, n, block_size, Hkv, Dh]`` (scales one axis fewer)."""
    ids = jnp.asarray(block_ids, jnp.int32)
    if ids.ndim != 1:
        raise ValueError(f"block_ids must be 1-D, got shape {ids.shape}")
    out = {"k": cache.k[:, ids], "v": cache.v[:, ids]}
    if cache.k_scale is not None:
        out["k_scale"] = cache.k_scale[:, ids]
        out["v_scale"] = cache.v_scale[:, ids]
    return out


def import_kv_blocks(cache: PagedKVCache, block_ids, blocks: dict) -> PagedKVCache:
    """Write exported block payloads into this pool at ``block_ids`` —
    the receiving half of :func:`export_kv_blocks`. Values land verbatim
    in storage dtype (scale side pools ride the same index math, one
    fewer axis), so export→import round-trips bit-exactly.

    Sentinel rule (round 11): an id equal to ``num_blocks`` DROPS that
    payload row instead of writing it — never ``-1``, which JAX wraps to
    the last real block and corrupts it silently. Implemented the way
    the runtime scatters do: the pool is extended by one garbage block
    at index ``num_blocks`` that the final slice discards."""
    ids = jnp.asarray(block_ids, jnp.int32)
    nb = cache.k.shape[1]
    if bool(jnp.any((ids < 0) | (ids > nb))):
        raise ValueError(
            f"block id out of range [0, {nb}] (sentinel={nb} drops; -1 "
            "would wrap and corrupt the last block)"
        )

    def put(pool, payload):
        if payload.shape[1:] != (ids.shape[0],) + pool.shape[2:]:
            raise ValueError(
                f"payload shape {payload.shape} does not match pool "
                f"{pool.shape} over {ids.shape[0]} blocks"
            )
        ext = jnp.concatenate([pool, jnp.zeros_like(pool[:, :1])], axis=1)
        ext = ext.at[:, ids].set(jnp.asarray(payload).astype(pool.dtype))
        return ext[:, :nb]

    has_scale = cache.k_scale is not None
    if has_scale != ("k_scale" in blocks):
        raise ValueError(
            "scale side tensors must travel with a quantized pool and "
            "only with one (pool has scales: %s, payload has: %s)"
            % (has_scale, "k_scale" in blocks)
        )
    return cache._replace(
        k=put(cache.k, blocks["k"]),
        v=put(cache.v, blocks["v"]),
        k_scale=put(cache.k_scale, blocks["k_scale"]) if has_scale else None,
        v_scale=put(cache.v_scale, blocks["v_scale"]) if has_scale else None,
    )


def _picked_nll(logits32, targets):
    """Per-position negative log-likelihood ``logsumexp(x) − x[target]``
    with the pick as a fused compare-and-reduce over the vocab axis, NOT
    a ``take_along_axis`` gather: TPU scalar gathers along the tiled
    minor (vocab) dimension are catastrophically slow — at gpt-l shapes
    ([8, 1023, 8192]) the gather formulation measured 25.2 ms per step
    vs 1.1 ms for this one (23×; the whole full-vocab ``log_softmax``
    materialization also disappears). Same values: the gathered
    log-softmax IS ``x[t] − lse``."""
    lse = jax.scipy.special.logsumexp(logits32, axis=-1)
    vocab = jnp.arange(logits32.shape[-1])
    picked = jnp.sum(
        jnp.where(vocab == targets[..., None], logits32, 0.0), axis=-1
    )
    return lse - picked


def _ce_from_logits(logits, tokens, lengths=None):
    """Mean next-token cross-entropy (positions 0..L-2 predict 1..L-1, f32
    ``logsumexp − picked``), masked over ``lengths`` when given — the ONE
    CE arithmetic shared by :meth:`GPTLM.loss_and_metrics` and every
    parallel train-step factory below (a divergence here would silently
    break their proven equality with the single-device step)."""
    nll = _picked_nll(logits[:, :-1].astype(jnp.float32), tokens[:, 1:])
    if lengths is None:
        return jnp.mean(nll)
    # Target at position i is token i+1 → valid iff i+1 < lengths[b].
    w = (
        jnp.arange(tokens.shape[1] - 1)[None, :] < (lengths[:, None] - 1)
    ).astype(jnp.float32)
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)


def expert_parallel_specs(model: GPTLM, axis_name: str = "expert"):
    """PartitionSpec layout for expert parallelism: every leaf replicated
    except the MoE blocks' expert-stacked FFN weights, sharded on their
    expert dim (axis 1 — axis 0 is num_layers). The layout
    ``apply_expert_parallel`` / ``make_lm_ep_train_step`` consume."""
    from jax.sharding import PartitionSpec as P

    if model.moe_experts is None:
        raise ValueError("expert_parallel_specs requires moe_experts")
    return GPTLMParams(
        embed=P(),
        pos=P(),
        blocks=GPTMoEBlockParams(
            ln1_scale=P(), ln1_bias=P(), wq=P(), wk=P(), wv=P(), wo=P(),
            ln2_scale=P(), ln2_bias=P(), wg=P(),
            w_up=P(None, axis_name),
            b_up=P(None, axis_name),
            w_down=P(None, axis_name),
            b_down=P(None, axis_name),
        ),
        lnf_scale=P(),
        lnf_bias=P(),
    )


# Generic layout utilities, shared with the LM trainer's ZeRO mode and the
# rest of the parallel surface (parallel/specs.py is their home).
from distributed_tensorflow_tpu.parallel.specs import (  # noqa: E402
    as_shardings as _as_shardings,
    pinned_update as _pinned_update,
    slot_specs as _slot_specs,
)


def make_lm_ep_train_step(
    model: GPTLM,
    optimizer,
    mesh,
    axis: str = "expert",
    *,
    data_axis: str | None = None,
):
    """Expert-parallel TRAINING step for the MoE LM: one expert's FFN
    weights (and their optimizer slots) live on each device of ``axis``,
    tokens are sharded on the batch dim, every block's FFN is the
    all-to-all exchange (``ops/moe.moe_ffn``), and gradients flow back
    through the collectives. ``step(params, opt_state, tokens) ->
    (params, opt_state, loss)``, jitted, with params laid out per
    :func:`expert_parallel_specs` (place them with ``jax.device_put``
    before the first call, or let shard_map reshard).

    ``data_axis`` composes data parallelism on top — real MoE training is
    dp×ep on a 2-D ``(data, expert)`` mesh (the reference's only
    composition story is multi-ps × multi-worker, reference README.md:
    166-254; this is its modern form). The batch dim is sharded over BOTH
    axes (data-major), expert weights stay sharded over ``axis`` only
    (replicated across ``data``), and each data row runs its own expert
    all-to-all over ``axis``. The ``axis`` size must still equal
    ``moe_experts`` (that equality is the all-to-all's layout); the data
    axis is free, so the device count scales past the expert count.

    The differentiated loss is the cross-device ``pmean`` (over both axes
    when dp is on) of the local masked CE plus the router aux terms (the
    same total ``loss_and_metrics`` builds): differentiating the *global*
    mean makes shard_map's automatic psum of replicated-leaf cotangents
    produce exactly the global gradient — no manual rescaling — while each
    expert's sharded weights receive their data-summed local gradient
    through the all-to-all transpose.

    Semantics vs the dense step: the CE term equals the dense global-batch
    CE exactly in the no-drop regime (capacity is per source shard, like
    the forward); the aux terms are *per-shard* balance/z-losses averaged
    over shards — standard EP practice (each device regularizes its own
    router view), differing from the dense global-batch aux by the
    product-of-averages gap. tests/test_gpt.py pins the exact semantics
    against a shard-wise dense reference, for 1-D ep and 2-D dp×ep."""
    specs, opt_specs, mapped = make_lm_ep_parts(
        model, optimizer, mesh, axis, data_axis=data_axis
    )

    @jax.jit
    def step(params, opt_state, tokens):
        return mapped(params, opt_state, tokens, None)

    return step


def make_lm_ep_parts(
    model: GPTLM,
    optimizer,
    mesh,
    axis: str = "expert",
    *,
    data_axis: str | None = None,
    ragged: bool = False,
):
    """Building blocks behind :func:`make_lm_ep_train_step`, exposed (like
    :func:`make_lm_async_parts`) so the LM trainer can embed the
    expert-parallel update inside its scanned-epoch / whole-run-compiled
    bodies. Returns ``(specs, opt_specs, mapped)``:

    - ``specs`` / ``opt_specs`` — PartitionSpec pytrees for the params and
      their optimizer slots (:func:`expert_parallel_specs` + slot
      matching); place states with ``NamedSharding(mesh, spec)``;
    - ``mapped(params, opt_state, tokens, lengths) -> (params, opt_state,
      loss)`` — NOT jitted (call inside your own jit/scan); tokens [B, L]
      sharded on the batch dim over ``(data_axis?, axis)``, ``lengths``
      [B] for ragged corpora (masked CE + masked routing per shard, the
      same pad-independence the dense path proves) or None (``ragged`` is
      a factory-time choice — it shapes the shard_map signature).

    Ragged loss convention: the differentiated loss is the pmean of each
    shard's *masked mean* CE — shards weight equally regardless of their
    valid-token counts (the same convention as ``make_lm_async_parts``'s
    per-copy masked CE), equal to the global masked mean exactly when the
    per-shard valid counts are equal."""
    import optax
    from jax.sharding import PartitionSpec as P

    if model.moe_experts is None:
        raise ValueError("make_lm_ep_train_step requires moe_experts")
    n = mesh.shape[axis]
    if n != model.moe_experts:
        raise ValueError(
            f"{axis!r} axis size {n} != moe_experts {model.moe_experts}"
        )
    if data_axis is not None and data_axis not in mesh.shape:
        raise ValueError(f"mesh has no {data_axis!r} axis: {dict(mesh.shape)}")
    if data_axis == axis:
        raise ValueError(
            f"data_axis must differ from the expert axis {axis!r}"
        )
    axes = (axis,) if data_axis is None else (data_axis, axis)
    batch_spec = P(axis) if data_axis is None else P((data_axis, axis))
    specs = expert_parallel_specs(model, axis)
    params_shape = jax.eval_shape(model.init, 1)
    opt_specs = _slot_specs(optimizer, params_shape, specs)

    def ep_loss(params, tokens, lens):
        logits, auxs = model.apply_expert_parallel(
            params, tokens, axis, with_aux=True, lengths=lens
        )
        ce = lax.pmean(_ce_from_logits(logits, tokens, lens), axes)
        balance = lax.pmean(jnp.mean(auxs.balance_loss), axes)
        z = lax.pmean(jnp.mean(auxs.z_loss), axes)
        return (
            ce
            + model.moe_balance_coef * balance
            + model.moe_z_coef * z
        )

    def local(params, opt_state, tokens, lens):
        loss, grads = jax.value_and_grad(ep_loss)(
            params, tokens, lens if ragged else None
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    lens_spec = batch_spec if ragged else P()
    inner = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(specs, opt_specs, batch_spec, lens_spec),
        out_specs=(specs, opt_specs, P()),
    )

    def mapped(params, opt_state, tokens, lens):
        if lens is None:
            lens = _default_lens(tokens, ragged)
        return inner(params, opt_state, tokens, lens)

    return specs, opt_specs, mapped


def _default_lens(tokens, ragged: bool):
    """Placeholder for a factory's ``lens=None`` call. Non-ragged: the
    local body ignores lens and a rank-0 zero matches the P() spec.
    Ragged: the lens spec is rank-1 over the batch axis, so a rank-0
    placeholder would die in shard_map with a confusing spec/operand
    mismatch — synthesize full lengths instead (every position real ==
    the non-ragged loss). Shared by the ep/sp/async factories (advisor
    r4: the original rank-0 bug existed in all three copies at once)."""
    if ragged:
        return jnp.full((tokens.shape[0],), tokens.shape[1], jnp.int32)
    return jnp.zeros((), jnp.int32)


def pipeline_parallel_specs(model: GPTLM, axis_name: str = "stage"):
    """PartitionSpec layout for pipeline parallelism over the
    :meth:`GPTLM.pipeline_stage_blocks` layout: every staged block leaf
    sharded on its leading ``num_stages`` dim (one contiguous layer group
    per device of ``axis_name``); embed/pos/lnf replicated — exactly the
    placement :func:`make_lm_pp_train_step` trains under."""
    from jax.sharding import PartitionSpec as P

    if model.moe_experts is not None:
        raise NotImplementedError(
            "pipeline parallelism is not defined for MoE blocks; use "
            "expert parallelism (make_lm_ep_train_step)"
        )
    params_shape = jax.eval_shape(model.init, 1)
    return GPTLMParams(
        embed=P(),
        pos=P(),
        blocks=jax.tree.map(lambda _: P(axis_name), params_shape.blocks),
        lnf_scale=P(),
        lnf_bias=P(),
    )


def pipeline_stage_params(
    model: GPTLM, params: GPTLMParams, num_stages: int
) -> GPTLMParams:
    """Full params → pipeline layout: blocks reshaped to
    [num_stages, layers_per_stage, ...] (:meth:`GPTLM.pipeline_stage_blocks`),
    everything else untouched. Inverse: merge the two leading block dims."""
    return params._replace(
        blocks=model.pipeline_stage_blocks(params.blocks, num_stages)
    )


def make_lm_pp_train_step(
    model: GPTLM,
    optimizer,
    mesh,
    *,
    axis: str = "stage",
    num_microbatches: int = 4,
    data_axis: str | None = None,
):
    """Pipeline-parallel TRAINING step: the GPipe backward as the scan
    transpose. The reference has no pipeline stages at all (SURVEY.md §2b
    — one tiny MLP per worker); this completes the parallelism matrix on
    the *training* side, the reason GPipe exists.

    Layout: params in :func:`pipeline_stage_params` form — each device of
    ``axis`` owns one contiguous layer group [1, n/S, ...] AND that group's
    optimizer slots (:func:`pipeline_parallel_specs` + slot matching);
    embed/pos/lnf and tokens replicated. The forward is the GPipe
    microbatched pipeline (``parallel/pipeline.py``): M microbatches flow
    stage-to-stage over ``ppermute`` hops, M + S − 1 ticks. The backward is
    **not hand-scheduled**: reverse-mode AD through the tick scan replays
    the ticks in reverse with the transposed hops (``ppermute`` with the
    inverse permutation) — exactly the GPipe backward schedule, derived by
    the compiler rather than written out. Each stage's parameter gradient
    accumulates across its microbatch ticks inside the scan transpose; the
    embedding/head gradients flow once (embed + LM head run under GSPMD
    outside the stage loop, so nothing is double-counted across stages).

    ``model.remat=True`` composes: each stage's layer-group forward is
    ``jax.checkpoint``-ed, so the backward recomputes one stage group per
    tick instead of stashing all M·(M+S−1) tick activations.

    ``data_axis`` composes data parallelism on top — dp×pp on a 2-D
    ``(data, stage)`` mesh: each microbatch's rows are sharded over
    ``data_axis`` (every data row runs the same GPipe schedule on its
    shard of every microbatch), embed/head/CE run under GSPMD on the
    data-sharded batch, and the stage-owned layer groups (replicated
    across ``data``) receive their data-summed gradients through
    shard_map's auto-psum — the same composition form as dp×ep.

    Returns a jitted ``step(params, opt_state, tokens) -> (params,
    opt_state, loss)``; place params/slots with ``jax.device_put`` under
    the :func:`pipeline_parallel_specs` layout first (or let GSPMD
    reshard on the first call). Proven grad-identical to the sequential
    single-device step in tests/test_gpt.py on 4- and 8-stage meshes
    (and 2×4 dp×pp)."""
    specs, opt_specs, pp_loss = make_lm_pp_parts(
        model,
        optimizer,
        mesh,
        axis=axis,
        num_microbatches=num_microbatches,
        data_axis=data_axis,
    )
    shardings = _as_shardings(mesh, specs)
    opt_shardings = _as_shardings(mesh, opt_specs)

    @jax.jit
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(pp_loss)(params, tokens)
        # Pin to the stage-owner layout: the update stays local to each
        # device's layer group.
        params, opt_state = _pinned_update(
            optimizer, params, opt_state, grads, shardings, opt_shardings
        )
        return params, opt_state, loss

    return step


def make_lm_pp_parts(
    model: GPTLM,
    optimizer,
    mesh,
    *,
    axis: str = "stage",
    num_microbatches: int = 4,
    data_axis: str | None = None,
):
    """Building blocks behind :func:`make_lm_pp_train_step`, exposed (like
    :func:`make_lm_ep_parts`) so the LM trainer can embed the pipeline
    step inside its scanned-epoch / whole-run-compiled bodies. Returns
    ``(specs, opt_specs, pp_loss)``:

    - ``specs`` / ``opt_specs`` — PartitionSpec pytrees for params in
      :func:`pipeline_stage_params` layout and their optimizer slots;
    - ``pp_loss(params, tokens, lengths=None) -> loss`` — differentiable
      GPipe forward + next-token CE (masked when ``lengths`` [B] is given:
      ragged right-padded batches train exactly as in :meth:`GPTLM.loss` —
      causal attention already isolates pads, only the CE needs masking
      for dense blocks). Call inside jit; differentiate for the GPipe
      backward (the tick-scan transpose)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.parallel.pipeline import (
        microbatch,
        pipeline_apply,
    )

    s = mesh.shape[axis]
    if model.num_layers % s:
        raise ValueError(
            f"num_layers {model.num_layers} not divisible by {axis!r} axis "
            f"size {s}"
        )
    if data_axis is not None and data_axis not in mesh.shape:
        raise ValueError(f"mesh has no {data_axis!r} axis: {dict(mesh.shape)}")
    if data_axis == axis:
        raise ValueError(
            f"data_axis must differ from the stage axis {axis!r}"
        )
    specs = pipeline_parallel_specs(model, axis)  # raises for MoE blocks
    staged_shape = jax.eval_shape(
        lambda: pipeline_stage_params(model, model.init(1), s)
    )
    opt_specs = _slot_specs(optimizer, staged_shape, specs)
    mb_spec = P() if data_axis is None else P(None, data_axis)

    stage_fn = model._pp_stage_fn()
    pp_body = jax.shard_map(
        lambda blocks, hm: pipeline_apply(stage_fn, blocks, hm, axis),
        mesh=mesh,
        in_specs=(specs.blocks, mb_spec),
        out_specs=mb_spec,
    )

    def pp_loss(params, tokens, lengths=None):
        b, l = tokens.shape
        if data_axis is not None:
            tokens = lax.with_sharding_constraint(
                tokens, NamedSharding(mesh, P(data_axis))
            )
        positions = jnp.arange(l)
        h = model._embed_tokens(params, tokens, positions)
        hm = microbatch(h, num_microbatches)  # [M, B/M, L, d]
        out = pp_body(params.blocks, hm)
        logits = model._logits(params, out.reshape(b, l, -1))
        return _ce_from_logits(logits, tokens, lengths)

    return specs, opt_specs, pp_loss


def make_lm_sp_train_step(
    model: GPTLM,
    optimizer,
    mesh,
    *,
    axis: str = "seq",
    data_axis: str | None = None,
    attention: str | None = None,
):
    """Sequence-parallel TRAINING step: the LM trains past one device's
    activation memory — L/n tokens of activations per device, KV riding
    the causal ring (or the Ulysses all-to-all) exactly as in
    :meth:`GPTLM.apply_sequence_parallel`, gradients back through the
    collectives. ``step(params, opt_state, tokens) -> (params, opt_state,
    loss)``, jitted; tokens [B, L] with L divisible by the ``axis`` size,
    params replicated (no layout to place). ``data_axis`` composes data
    parallelism → dp×sp on a ``('data','seq')`` mesh. Proven equal to the
    single-device step in tests/test_gpt.py."""
    mapped = make_lm_sp_parts(
        model, optimizer, mesh, axis,
        data_axis=data_axis, attention=attention,
    )

    @jax.jit
    def step(params, opt_state, tokens):
        return mapped(params, opt_state, tokens, None)

    return step


def make_lm_sp_parts(
    model: GPTLM,
    optimizer,
    mesh,
    axis: str = "seq",
    *,
    data_axis: str | None = None,
    attention: str | None = None,
    ragged: bool = False,
):
    """Building blocks behind :func:`make_lm_sp_train_step`, exposed (like
    the ep/pp parts) so the LM trainer can embed the sequence-parallel
    update inside its scanned-epoch / whole-run-compiled bodies. Returns
    ``mapped(params, opt_state, tokens, lengths) -> (params, opt_state,
    loss)`` — NOT jitted; tokens [B, L] sharded on the SEQUENCE dim over
    ``axis`` (and the batch dim over ``data_axis`` when given), params
    and optimizer slots replicated.

    The loss is the EXACT global (masked) next-token CE — not a per-shard
    mean: each device scores its l_loc positions, the shard-boundary
    target (position s+l_loc−1 predicts the NEXT shard's first token)
    arrives over one ``ppermute`` hop, and CE·count sums are
    ``psum``-aggregated over all axes before the division. Equal to
    :func:`_ce_from_logits` on the gathered sequence by construction,
    ragged or not — so sp training is bitwise-tolerant equal to the
    single-device step (grads of the replicated params arrive through
    shard_map's auto-psum, already globally summed; no rescaling).

    ``attention`` follows :meth:`GPTLM.apply_sequence_parallel` (ring /
    ring_flash / ulysses; ring_flash needs a TPU or check_vma=False)."""
    import optax
    from jax.sharding import PartitionSpec as P

    if model.moe_experts is not None:
        raise NotImplementedError(
            "MoE blocks are not supported on the sequence-parallel path; "
            "use expert parallelism (make_lm_ep_parts)"
        )
    n = mesh.shape[axis]
    if data_axis is not None and data_axis not in mesh.shape:
        raise ValueError(f"mesh has no {data_axis!r} axis: {dict(mesh.shape)}")
    if data_axis == axis:
        raise ValueError(f"data_axis must differ from the seq axis {axis!r}")
    axes = (axis,) if data_axis is None else (data_axis, axis)
    batch_spec = P(data_axis, axis)  # data_axis=None → replicated batch dim
    lens_spec = P(data_axis)
    # Shard i receives shard (i+1)'s first token — the boundary target.
    perm = [(j, (j - 1) % n) for j in range(n)]

    def sp_loss(params, toks, lens):
        l_loc = toks.shape[1]
        my = lax.axis_index(axis)
        logits = model.apply_sequence_parallel(
            params, toks, axis, attention=attention
        )
        nxt = lax.ppermute(toks[:, 0], axis, perm)
        targets = jnp.concatenate([toks[:, 1:], nxt[:, None]], axis=1)
        nll = _picked_nll(logits.astype(jnp.float32), targets)
        # Absolute index of each local position's target token.
        tpos = my * l_loc + jnp.arange(l_loc) + 1
        valid = tpos[None, :] < n * l_loc  # the last global position has
        if lens is not None:  # no target (wrapped garbage masked here)
            valid = valid & (tpos[None, :] < lens[:, None])
        # Broadcast to [B, l_loc] BEFORE counting: the non-ragged mask is
        # per-position only and the count must include the batch factor.
        w = jnp.broadcast_to(valid, nll.shape).astype(jnp.float32)
        # pvary to the full psum axes first: non-ragged w only varies over
        # the seq axis, and psum rejects axes the operand is invariant of.
        ce = lax.psum(to_varying(jnp.sum(nll * w), axes), axes)
        cnt = lax.psum(to_varying(jnp.sum(w), axes), axes)
        return ce / jnp.maximum(cnt, 1.0)

    def local(params, opt_state, toks, lens):
        loss, grads = jax.value_and_grad(sp_loss)(
            params, toks, lens if ragged else None
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    inner = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(), batch_spec, lens_spec if ragged else P()),
        out_specs=(P(), P(), P()),
    )

    def mapped(params, opt_state, tokens, lens):
        if lens is None:
            lens = _default_lens(tokens, ragged)
        return inner(params, opt_state, tokens, lens)

    return mapped


def make_lm_async_train_step(
    model: GPTLM,
    optimizer,
    mesh,
    *,
    axis: str = "data",
    avg_every: int = 1,
    update_scale: float | None = None,
):
    """Async local-SGD for the LM — the reference's signature training mode
    (HOGWILD applies to PS variables, reference tfdist_between.py:64-66),
    emulated the way ``AsyncDataParallel`` does for the classifiers: each
    device owns a private (params, opt_state) copy advancing on its own
    token stream, and every ``avg_every`` steps all copies jump to the
    cross-device parameter mean (one all-reduce; zero traffic between
    exchanges).

    Returns ``(init_state, step)``:

    - ``init_state(params, opt_state) -> state`` stacks per-device copies
      ([n, ...] leaves, sharded over ``axis``) plus a step counter;
    - ``step(state, tokens) -> (state, loss)`` with tokens [n·B, L] sharded
      on the batch dim; loss is the cross-device mean of the local losses.

    ``update_scale`` defaults to **N (the replica count)** — the ONE
    convention both async APIs share (``AsyncDataParallel``,
    strategy.py): the reference PS applied all N workers' updates
    sequentially, so reproducing its async-table behavior needs N× the
    per-exchange step; parameter averaging alone gives sync-like
    dynamics (tools/parity_converged.py). Pass ``update_scale=1.0``
    explicitly for pure local-SGD averaging — with plain SGD and
    ``avg_every=1`` that is *exactly* the sync data-parallel step (mean of
    independent SGD updates from a common point = update by the mean
    gradient — SGD is linear in the gradient), which the tests assert
    bitwise-tolerant; with momentum/adam or ``avg_every>1`` it is
    genuinely async (copies diverge between exchanges, the modeled
    race)."""
    init_state, mapped = make_lm_async_parts(
        model,
        optimizer,
        mesh,
        axis=axis,
        avg_every=avg_every,
        update_scale=update_scale,
    )

    @partial(jax.jit, donate_argnums=0)
    def step(state, tokens):
        params, opt_state, count = state
        params, opt_state, loss = mapped(
            params, opt_state, tokens, None, count
        )
        return (params, opt_state, count + 1), loss

    return init_state, step


def make_lm_async_parts(
    model: GPTLM,
    optimizer,
    mesh,
    *,
    axis: str = "data",
    avg_every: int = 1,
    update_scale: float | None = None,
    ragged: bool = False,
):
    """Building blocks behind :func:`make_lm_async_train_step`, exposed so
    the :class:`~train.lm_trainer.LMTrainer` can embed the async local-SGD
    update inside its scanned-epoch / whole-run-compiled bodies (one
    ``lax.scan`` over many async steps) instead of paying a dispatch per
    step. Returns ``(init_state, mapped)``:

    - ``init_state(params, opt_state) -> (stacked_params, stacked_opt,
      count)`` — per-device copies ([n, ...] leaves sharded over ``axis``)
      plus the step counter the ``avg_every`` exchange keys on;
    - ``mapped(stacked_params, stacked_opt, tokens, lengths, count) ->
      (stacked_params, stacked_opt, loss)`` — NOT jitted (call it inside
      your own jit/scan); tokens [n·B, L] sharded on the batch dim,
      ``lengths`` [n·B] for ragged corpora (masked CE per copy) or None
      (``ragged`` is a factory-time choice — it shapes the shard_map
      signature); loss is the cross-device mean of the local losses.
    """
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if avg_every < 1:
        raise ValueError(f"avg_every must be >= 1, got {avg_every}")
    n = mesh.shape[axis]
    if update_scale is None:
        update_scale = float(n)

    def init_state(params, opt_state):
        stacked = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape),
            (params, opt_state),
        )
        stacked = jax.device_put(
            stacked, NamedSharding(mesh, P(axis))
        )
        return (*stacked, jnp.zeros((), jnp.int32))

    def local(params, opt_state, tokens, lens, count):
        p = jax.tree.map(lambda x: x[0], params)
        o = jax.tree.map(lambda x: x[0], opt_state)
        loss_fn = (
            (lambda q: model.loss(q, tokens, lens))
            if ragged
            else (lambda q: model.loss(q, tokens))
        )
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = optimizer.update(grads, o, p)
        if update_scale != 1.0:
            updates = jax.tree.map(lambda u: u * update_scale, updates)
        p = optax.apply_updates(p, updates)
        # lax.cond, not jnp.where: where evaluates both branches, so the
        # all-reduce would fire on EVERY step and void avg_every's traffic
        # bound. The predicate derives from the replicated count, so all
        # devices agree and the collective is uniform.
        # pmean outputs are typed invariant; cast back to varying so both
        # cond branches agree under check_vma (same pattern as the ring's
        # skip branch, ops/collectives.to_varying).
        pvary = partial(to_varying, axis_name=(axis,))
        p = lax.cond(
            (count + 1) % avg_every == 0,
            lambda p: jax.tree.map(lambda x: pvary(lax.pmean(x, axis)), p),
            lambda p: p,
            p,
        )
        return (
            jax.tree.map(lambda x: x[None], p),
            jax.tree.map(lambda x: x[None], o),
            lax.pmean(loss, axis),
        )

    lens_spec = (P(axis),) if ragged else (P(),)
    inner = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)) + lens_spec + (P(),),
        out_specs=(P(axis), P(axis), P()),
    )

    def mapped(params, opt_state, tokens, lens, count):
        if lens is None:
            lens = _default_lens(tokens, ragged)
        return inner(params, opt_state, tokens, lens, count)

    return init_state, mapped


def make_lm_train_step(
    model: GPTLM,
    optimizer,
    mesh=None,
    axis: str = "data",
    *,
    tp_axis: str | None = None,
    seq_axis: str | None = None,
):
    """``step(params, opt_state, tokens) -> (params, opt_state, loss)``,
    jitted, for any optax ``GradientTransformation`` (ops/optim.make).

    With ``mesh`` the step runs data-parallel over its ``axis``: tokens
    sharded on the batch dim, params/opt-state replicated, gradients
    all-reduced — the LM analog of ``SyncDataParallel``'s compiled
    collective (the reference's sync mode, tfdist_between_sync.py:66-68,
    minus the parameter server). Identical math to the single-device step on
    the same global batch for dense models; MoE models compute switch
    capacity from the LOCAL batch shard (standard practice), so dp equals
    single-device exactly only in the no-drop regime. Under ``shard_map`` AD auto-inserts a psum for
    grads of the replicated params, so the local grads are *summed* — the
    code divides by the axis size rather than pmean-ing (CLAUDE.md).

    ``tp_axis`` switches to the 2-D dp×tp form: params (and optimizer
    slots) laid out per :meth:`GPTLM.partition_specs` over ``tp_axis``,
    batch sharded over ``axis``, and the whole step expressed as ONE
    GSPMD program — XLA inserts the Megatron collectives (all-reduce
    after attention-out/MLP-down) and the gradient all-reduce over
    ``axis``. The math is the single-device step verbatim (GSPMD
    partitioning preserves semantics), proven in tests/test_gpt.py.
    Place params with ``jax.device_put`` under the returned layout or let
    GSPMD reshard on first call; dense models only (MoE → EP).

    ``seq_axis`` (round 9) composes GSPMD sequence sharding on top of the
    tp form — the 3-D **dp×tp×sp** mesh real pods run: tokens constrained
    ``P(axis, seq_axis)`` (batch over ``axis``, the SEQUENCE dim over
    ``seq_axis``), params still per :meth:`partition_specs`, one GSPMD
    program for the whole 3-D composition — XLA inserts the sequence
    gathers the causal attention needs next to the Megatron collectives.
    Still the single-device math verbatim; equality on the 2x2x2 mesh is
    pinned in tests/test_gpt.py. GSPMD triples compose freely this way
    because every axis is a layout annotation on one program; the
    shard_map modes (explicit sp/ep/pp) instead compose with exactly one
    data axis — docs/parallelism.md has the triple-composition menu."""
    import optax

    if seq_axis is not None and tp_axis is None:
        raise ValueError(
            "seq_axis composes on the GSPMD tp path; pass tp_axis too "
            "(for shard_map sequence parallelism use make_lm_sp_parts)"
        )
    if tp_axis is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        if mesh is None:
            raise ValueError("tp_axis requires a mesh")
        if seq_axis is not None and seq_axis not in mesh.shape:
            raise ValueError(
                f"mesh has no {seq_axis!r} axis: {dict(mesh.shape)}"
            )
        specs = model.partition_specs(tp_axis)  # raises for MoE blocks
        opt_specs = _slot_specs(
            optimizer, jax.eval_shape(model.init, 1), specs
        )
        shardings = _as_shardings(mesh, specs)
        opt_shardings = _as_shardings(mesh, opt_specs)
        batch_sharding = NamedSharding(mesh, P(axis, seq_axis))

        @jax.jit
        def step(params, opt_state, tokens):
            tokens = lax.with_sharding_constraint(tokens, batch_sharding)
            loss, grads = jax.value_and_grad(model.loss)(params, tokens)
            # Pin to the TP layout: the update stays local to each
            # device's weight shard.
            params, opt_state = _pinned_update(
                optimizer, params, opt_state, grads, shardings, opt_shardings
            )
            return params, opt_state, loss

        return step

    if mesh is None:

        @jax.jit
        def step(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(model.loss)(params, tokens)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return step

    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]

    def local(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(model.loss)(params, tokens)
        # AD's auto-psum summed the per-device grads of the replicated
        # params; the global-mean loss needs their mean.
        grads = jax.tree.map(lambda g: g / n, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, lax.pmean(loss, axis)

    mapped = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(), P(axis)),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(mapped)
