"""The model protocol every framework model satisfies.

The reference's 'model' is ~20 lines of graph construction repeated in each
script (C8). Here a model is any object with pure ``init``/``apply``:

- ``init(seed) -> params``: build the parameter pytree deterministically
  from an integer seed (so every process computes identical initial state —
  the property that replaces chief-initializes-then-others-wait, see
  train/supervisor.py).
- ``apply(params, x) -> outputs``: the jit-able forward pass.
- optionally ``partition_specs(model_axis) -> pytree[PartitionSpec]``:
  tensor-parallel layout over the mesh's ``model`` axis.

Strategies (parallel/strategy.py) and the Trainer depend only on this
protocol, so new model families drop in without touching the parallel or
training layers.
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable

import jax
import jax.numpy as jnp


@runtime_checkable
class Model(Protocol):
    def init(self, seed: int) -> Any: ...

    def apply(self, params: Any, x: jax.Array) -> jax.Array: ...


def resolve_flash_min_len(value: int | None) -> int:
    """The ONE resolver for every model's ``flash_min_len`` knob (GPT and
    transformer families — a second copy would let the measured crossover
    drift between them): ``None`` → the shared measured default,
    ``ops/pallas_attention.FLASH_MIN_LEN``. Deliberately LAZY — called at
    forward time behind the ``attention_impl == "flash"`` short-circuit,
    so xla-only models never import the Pallas stack."""
    if value is not None:
        return value
    from distributed_tensorflow_tpu.ops.pallas_attention import (
        FLASH_MIN_LEN,
    )

    return FLASH_MIN_LEN


def layernorm(x, scale, bias, eps=1e-5):
    """Shared f32 layernorm over the last axis (transformer and GPT
    families; one copy so numeric changes cannot diverge silently)."""
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps)) * scale + bias
