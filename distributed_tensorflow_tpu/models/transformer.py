"""A sequence-model family: single-block transformer classifier.

The reference has exactly one model (the C8 MLP). This family exists to
prove the framework's long-context machinery end to end — same init/apply
protocol (models/base.py), same Trainer/strategies, but the forward pass has
a real sequence dimension whose attention can run:

- dense on one device (``apply``; ``attention_impl="flash"`` swaps in the
  Pallas blockwise kernel from ``ops/pallas_attention`` — same math, no
  [L, L] score matrix in HBM), or
- **sequence-parallel** over a ``seq`` mesh axis
  (``apply_sequence_parallel``): activations sharded along the sequence,
  attention selectable between the ppermute **ring**
  (``ops/ring_attention.ring_attention``) and the all-to-all **Ulysses**
  (``ops/ring_attention.ulysses_attention``) algorithms — identical math
  either way.

The MNIST workload maps onto it by treating each image as a 28-token
sequence of 28-pixel rows (no new data pipeline needed). Architecture:
row-embed → +learned positions → pre-LN attention block with residual →
pre-LN MLP block with residual → mean-pool → linear head. All matmuls in
``compute_dtype`` (bf16 MXU) with f32 accumulation; softmax/layernorm f32.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.base import layernorm as _layernorm
from distributed_tensorflow_tpu.ops.ring_attention import (
    dense_attention,
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)


class TransformerParams(NamedTuple):
    embed: jax.Array  # [token_dim, model_dim]
    pos: jax.Array  # [seq_len, model_dim]
    ln1_scale: jax.Array
    ln1_bias: jax.Array
    wq: jax.Array  # [model_dim, model_dim]
    wk: jax.Array
    wv: jax.Array
    wo: jax.Array
    ln2_scale: jax.Array
    ln2_bias: jax.Array
    w_up: jax.Array  # [model_dim, 4*model_dim]
    b_up: jax.Array
    w_down: jax.Array  # [4*model_dim, model_dim]
    b_down: jax.Array
    w_head: jax.Array  # [model_dim, classes]
    b_head: jax.Array


class TransformerClassifier:
    """seq_len tokens of token_dim features → num_classes probabilities."""

    def __init__(
        self,
        seq_len: int = 28,
        token_dim: int = 28,
        model_dim: int = 64,
        num_heads: int = 4,
        num_classes: int = 10,
        compute_dtype: jnp.dtype = jnp.bfloat16,
        attention_impl: str = "xla",
        flash_min_len: int | None = None,
    ):
        assert model_dim % num_heads == 0
        if attention_impl not in ("xla", "flash"):
            raise ValueError(
                f"unknown attention_impl {attention_impl!r}; xla|flash"
            )
        self.seq_len = seq_len
        self.token_dim = token_dim
        self.model_dim = model_dim
        self.num_heads = num_heads
        self.head_dim = model_dim // num_heads
        self.num_classes = num_classes
        self.compute_dtype = compute_dtype
        self.attention_impl = attention_impl
        # Same knob as GPTLM.flash_min_len: None → the ONE measured
        # crossover, resolved lazily at forward time
        # (models/base.resolve_flash_min_len); 0 forces the kernel
        # (tests do — the 28-token MNIST rows are toy-length).
        self.flash_min_len = flash_min_len

    def init(self, seed: int = 1) -> TransformerParams:
        keys = jax.random.split(jax.random.key(seed), 8)
        d = self.model_dim

        def dense_init(key, shape):
            # fan-in scaled normal (unlike the MLP's reference-parity N(0,1))
            return jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(shape[0])

        return TransformerParams(
            embed=dense_init(keys[0], (self.token_dim, d)),
            pos=0.02 * jax.random.normal(keys[1], (self.seq_len, d), jnp.float32),
            ln1_scale=jnp.ones((d,), jnp.float32),
            ln1_bias=jnp.zeros((d,), jnp.float32),
            wq=dense_init(keys[2], (d, d)),
            wk=dense_init(keys[3], (d, d)),
            wv=dense_init(keys[4], (d, d)),
            wo=dense_init(keys[5], (d, d)),
            ln2_scale=jnp.ones((d,), jnp.float32),
            ln2_bias=jnp.zeros((d,), jnp.float32),
            w_up=dense_init(keys[6], (d, 4 * d)),
            b_up=jnp.zeros((4 * d,), jnp.float32),
            w_down=dense_init(keys[7], (4 * d, d)),
            b_down=jnp.zeros((d,), jnp.float32),
            w_head=jnp.zeros((d, self.num_classes), jnp.float32),
            b_head=jnp.zeros((self.num_classes,), jnp.float32),
        )

    # -- forward pieces (shared by dense and sequence-parallel paths) ------

    def _dot(self, x, w):
        cd = self.compute_dtype
        return jnp.dot(x.astype(cd), w.astype(cd), preferred_element_type=jnp.float32)

    def _qkv(self, p: TransformerParams, h):
        b, l, d = h.shape
        hn = self._layernorm_tokens(h, p.ln1_scale, p.ln1_bias)
        shape = (b, l, self.num_heads, self.head_dim)
        q = self._dot(hn, p.wq).reshape(shape)
        k = self._dot(hn, p.wk).reshape(shape)
        v = self._dot(hn, p.wv).reshape(shape)
        return q, k, v

    @staticmethod
    def _layernorm_tokens(h, scale, bias):
        return _layernorm(h, scale, bias)

    def _post_attention(self, p: TransformerParams, h, attn_out):
        b, l, _, _ = attn_out.shape
        h = h + self._dot(attn_out.reshape(b, l, self.model_dim), p.wo)
        hn = self._layernorm_tokens(h, p.ln2_scale, p.ln2_bias)
        mlp = self._dot(jax.nn.gelu(self._dot(hn, p.w_up) + p.b_up), p.w_down)
        return h + mlp + p.b_down

    def _embed(self, p: TransformerParams, x, positions=None):
        b = x.shape[0]
        tokens = x.reshape(b, self.seq_len, self.token_dim)
        h = self._dot(tokens, p.embed)
        pos = p.pos if positions is None else positions
        return h + pos

    def _head_probs(self, p: TransformerParams, h):
        pooled = h.mean(axis=1)
        logits = self._dot(pooled, p.w_head) + p.b_head
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # -- public forwards ---------------------------------------------------

    def apply(self, params: TransformerParams, x: jax.Array) -> jax.Array:
        """Dense single-device forward: x [B, seq_len*token_dim] → probs."""
        h = self._embed(params, x)
        q, k, v = self._qkv(params, h)
        from distributed_tensorflow_tpu.models.base import (
            resolve_flash_min_len,
        )

        if self.attention_impl == "flash" and q.shape[1] >= (
            resolve_flash_min_len(self.flash_min_len)
        ):
            from distributed_tensorflow_tpu.ops.pallas_attention import (
                flash_attention,
            )

            attn = flash_attention(q, k, v)
        else:
            attn = dense_attention(q, k, v)
        h = self._post_attention(params, h, attn)
        return self._head_probs(params, h)

    def apply_sequence_parallel(
        self,
        params: TransformerParams,
        x: jax.Array,
        axis_name: str = "seq",
        *,
        attention: str | None = None,
    ) -> jax.Array:
        """Sequence-parallel forward *body*: call inside ``jax.shard_map``
        with x sharded [B, (seq_len/n)*token_dim] per device and params
        replicated. ``attention`` selects the SP algorithm — ``"ring"``
        (ppermute KV rotation, bandwidth ∝ sequence), ``"ring_flash"``
        (same ring, per-hop local attention in the Pallas flash kernel — no
        [L_local, L_local] scores; off-TPU the enclosing shard_map needs
        ``check_vma=False``), or ``"ulysses"``
        (all-to-all seq↔heads reshard, needs heads divisible by the axis
        size); the mean-pool is a cross-device pmean either way. Math
        identical to :meth:`apply` for all three. The default (``None``)
        follows the constructor's ``attention_impl``: ``"flash"`` →
        ``"ring_flash"``, else ``"ring"`` — so a model configured for flash
        stays blockwise when it goes sequence-parallel."""
        if attention is None:
            attention = (
                "ring_flash" if self.attention_impl == "flash" else "ring"
            )
        if attention not in ("ring", "ring_flash", "ulysses"):
            raise ValueError(
                f"unknown attention {attention!r}; ring|ring_flash|ulysses"
            )
        n = jax.lax.axis_size(axis_name)
        my = jax.lax.axis_index(axis_name)
        l_loc = self.seq_len // n
        b = x.shape[0]
        tokens = x.reshape(b, l_loc, self.token_dim)
        pos = jax.lax.dynamic_slice_in_dim(params.pos, my * l_loc, l_loc, axis=0)
        h = self._dot(tokens, params.embed) + pos
        q, k, v = self._qkv(params, h)
        if attention == "ring":
            attn = ring_attention(q, k, v, axis_name)
        elif attention == "ring_flash":
            attn = ring_flash_attention(q, k, v, axis_name)
        else:
            if self.num_heads % n:
                raise ValueError(
                    f"ulysses needs heads ({self.num_heads}) divisible by "
                    f"the {axis_name!r} axis size ({n})"
                )
            attn = ulysses_attention(q, k, v, axis_name)
        h = self._post_attention(params, h, attn)
        pooled = jax.lax.pmean(h.mean(axis=1), axis_name)
        logits = self._dot(pooled, params.w_head) + params.b_head
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
