from distributed_tensorflow_tpu.models.mlp import MLP, MLPParams  # noqa: F401
