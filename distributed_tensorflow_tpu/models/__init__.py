"""Model families. Each satisfies the init/apply protocol (models/base.py),
so any of them drops into the strategies and Trainer unchanged.

The registry gives launchers and configs a stable string surface for model
selection — the role the reference filled by picking which script to run
(tfsingle.py vs tfdist_between.py all hardcode the same MLP graph,
reference tfsingle.py:23-42).
"""

from distributed_tensorflow_tpu.models.cnn import CNN, CNNParams  # noqa: F401
from distributed_tensorflow_tpu.models.gpt import (  # noqa: F401
    GPTLM,
    GPTLMParams,
    KVCache,
    make_lm_async_train_step,
    make_lm_train_step,
)
from distributed_tensorflow_tpu.models.mlp import MLP, MLPParams  # noqa: F401
from distributed_tensorflow_tpu.models.rnn import (  # noqa: F401
    LSTMClassifier,
    LSTMParams,
)
from distributed_tensorflow_tpu.models.transformer import (  # noqa: F401
    TransformerClassifier,
    TransformerParams,
)

MODEL_REGISTRY = {
    "mlp": MLP,
    "cnn": CNN,
    "transformer": TransformerClassifier,
    "lstm": LSTMClassifier,
    # GPTLM is deliberately NOT here: the registry serves the Trainer's
    # image-classification pipeline (C6/C14); the LM trains through
    # models.gpt.make_lm_train_step on token batches instead.
}


def build_model(name: str, **kwargs):
    """Construct a registered model family by name."""
    try:
        cls = MODEL_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; registered: {sorted(MODEL_REGISTRY)}"
        ) from None
    return cls(**kwargs)
