"""Model families. Each satisfies the init/apply protocol (models/base.py),
so any of them drops into the strategies and Trainer unchanged.

The registry gives launchers and configs a stable string surface for model
selection — the role the reference filled by picking which script to run
(tfsingle.py vs tfdist_between.py all hardcode the same MLP graph,
reference tfsingle.py:23-42).

Exports are lazy (PEP 562, same pattern as the package root, ``train/``
and ``parallel/``): importing the package names no model module, so the
serving stack (``serve.py`` → ``models/gpt.py``) stays importable in a
degraded container whose jax cannot back every family's dependencies.
"""

_LAZY_EXPORTS = {
    "CNN": ("distributed_tensorflow_tpu.models.cnn", "CNN"),
    "CNNParams": ("distributed_tensorflow_tpu.models.cnn", "CNNParams"),
    "GPTLM": ("distributed_tensorflow_tpu.models.gpt", "GPTLM"),
    "GPTLMParams": ("distributed_tensorflow_tpu.models.gpt", "GPTLMParams"),
    "KVCache": ("distributed_tensorflow_tpu.models.gpt", "KVCache"),
    "make_lm_async_train_step": (
        "distributed_tensorflow_tpu.models.gpt",
        "make_lm_async_train_step",
    ),
    "make_lm_train_step": (
        "distributed_tensorflow_tpu.models.gpt",
        "make_lm_train_step",
    ),
    "MLP": ("distributed_tensorflow_tpu.models.mlp", "MLP"),
    "MLPParams": ("distributed_tensorflow_tpu.models.mlp", "MLPParams"),
    "LSTMClassifier": (
        "distributed_tensorflow_tpu.models.rnn",
        "LSTMClassifier",
    ),
    "LSTMParams": ("distributed_tensorflow_tpu.models.rnn", "LSTMParams"),
    "TransformerClassifier": (
        "distributed_tensorflow_tpu.models.transformer",
        "TransformerClassifier",
    ),
    "TransformerParams": (
        "distributed_tensorflow_tpu.models.transformer",
        "TransformerParams",
    ),
}

# name → (module, attr); values resolve to classes in build_model. Keys are
# the stable string surface (sorted(MODEL_REGISTRY) stays the choices list).
MODEL_REGISTRY = {
    "mlp": ("distributed_tensorflow_tpu.models.mlp", "MLP"),
    "cnn": ("distributed_tensorflow_tpu.models.cnn", "CNN"),
    "transformer": (
        "distributed_tensorflow_tpu.models.transformer",
        "TransformerClassifier",
    ),
    "lstm": ("distributed_tensorflow_tpu.models.rnn", "LSTMClassifier"),
    # GPTLM is deliberately NOT here: the registry serves the Trainer's
    # image-classification pipeline (C6/C14); the LM trains through
    # models.gpt.make_lm_train_step on token batches instead.
}

__all__ = list(_LAZY_EXPORTS) + ["MODEL_REGISTRY", "build_model"]


def __getattr__(name):
    try:
        module, attr = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(module), attr)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def build_model(name: str, **kwargs):
    """Construct a registered model family by name."""
    try:
        module, attr = MODEL_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; registered: {sorted(MODEL_REGISTRY)}"
        ) from None
    import importlib

    return getattr(importlib.import_module(module), attr)(**kwargs)
