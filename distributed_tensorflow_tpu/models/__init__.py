from distributed_tensorflow_tpu.models.mlp import MLP, MLPParams  # noqa: F401
from distributed_tensorflow_tpu.models.transformer import (  # noqa: F401
    TransformerClassifier,
    TransformerParams,
)
