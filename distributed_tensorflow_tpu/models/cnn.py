"""Convolutional model family: a LeNet-style MNIST ConvNet, TPU-first.

The reference ships exactly one model — the 784→100→10 MLP repeated in each
script (reference tfsingle.py:23-42) — but it is an *MNIST training suite*,
and a convolutional classifier is the canonical next model for that workload.
This family exists to prove the framework's model protocol (models/base.py)
generalizes beyond the parity MLP: the CNN drops into the unchanged Trainer,
strategies, and data pipeline because it consumes the same flattened
``[B, 784]`` batches the reference's ``feed_dict`` carried
(reference tfdist_between.py:92-94) and produces the same float32
class-probability output the reference's softmax graph did.

TPU mapping:

- Convolutions lower to the MXU: ``lax.conv_general_dilated`` with bfloat16
  operands and float32 accumulation (``preferred_element_type``) — XLA tiles
  NHWC convs onto the systolic array the same way it tiles matmuls.
- Pooling is ``lax.reduce_window`` (VPU), fused by XLA into the surrounding
  elementwise work.
- The head is the familiar Megatron-style pair of dense layers; the softmax
  runs in float32 so the reference's naive ``log(softmax)`` loss
  (ops/losses.py) stays finite.

Init is fan-in-scaled (He) normal rather than the reference MLP's N(0, 1):
this family has no reference graph to mirror, so it uses the init a
practitioner would — deterministic from an integer seed like every model
here (the property supervisor-free chief init relies on, models/base.py).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
from jax import lax


class CNNParams(NamedTuple):
    """Parameter pytree. Conv kernels are HWIO, dense kernels [in, out]."""

    conv1_w: jax.Array  # [k, k, 1, c1]
    conv1_b: jax.Array  # [c1]
    conv2_w: jax.Array  # [k, k, c1, c2]
    conv2_b: jax.Array  # [c2]
    fc1_w: jax.Array  # [(H/4)*(W/4)*c2, hidden]
    fc1_b: jax.Array  # [hidden]
    fc2_w: jax.Array  # [hidden, out]
    fc2_b: jax.Array  # [out]


class CNN:
    """conv→relu→pool ×2 → dense→relu → dense → softmax, on [B, H*W] input."""

    def __init__(
        self,
        image_size: int = 28,
        in_channels: int = 1,
        channels: Sequence[int] = (32, 64),
        kernel: int = 5,
        hidden_dim: int = 256,
        out_dim: int = 10,
        compute_dtype: jnp.dtype = jnp.bfloat16,
    ):
        if image_size % 4 != 0:
            raise ValueError(f"image_size {image_size} must be divisible by 4 (two 2x2 pools)")
        if len(channels) != 2:
            raise ValueError(f"channels must be (c1, c2), got {tuple(channels)}")
        self.image_size = image_size
        self.in_channels = in_channels
        self.c1, self.c2 = channels
        self.kernel = kernel
        self.hidden_dim = hidden_dim
        self.out_dim = out_dim
        self.compute_dtype = compute_dtype
        self.flat_dim = (image_size // 4) * (image_size // 4) * self.c2

    # -- init --------------------------------------------------------------

    def init(self, seed: int = 1) -> CNNParams:
        """He-normal weights (stddev sqrt(2/fan_in)), zero biases; fully
        deterministic from ``seed``."""
        k = self.kernel
        keys = jax.random.split(jax.random.key(seed), 4)

        def he(key, shape, fan_in):
            return jax.random.normal(key, shape, jnp.float32) * jnp.sqrt(2.0 / fan_in)

        return CNNParams(
            conv1_w=he(keys[0], (k, k, self.in_channels, self.c1), k * k * self.in_channels),
            conv1_b=jnp.zeros((self.c1,), jnp.float32),
            conv2_w=he(keys[1], (k, k, self.c1, self.c2), k * k * self.c1),
            conv2_b=jnp.zeros((self.c2,), jnp.float32),
            fc1_w=he(keys[2], (self.flat_dim, self.hidden_dim), self.flat_dim),
            fc1_b=jnp.zeros((self.hidden_dim,), jnp.float32),
            fc2_w=he(keys[3], (self.hidden_dim, self.out_dim), self.hidden_dim),
            fc2_b=jnp.zeros((self.out_dim,), jnp.float32),
        )

    # -- forward -----------------------------------------------------------

    def _conv(self, x: jax.Array, w: jax.Array) -> jax.Array:
        """SAME conv in ``compute_dtype`` (bf16 → MXU), result upcast to f32.

        The conv's output dtype matches its operands rather than using
        ``preferred_element_type=f32``: conv's transpose (backward) rule
        re-invokes conv between the cotangent and an operand, and a
        mixed-dtype pair (f32 cotangent × bf16 operand) is rejected —
        matching dtypes keep fwd and bwd on the same MXU path. The MXU
        accumulates in f32 internally either way; only the per-window
        result is rounded to bf16 before the upcast."""
        cd = self.compute_dtype
        out = lax.conv_general_dilated(
            x.astype(cd),
            w.astype(cd),
            window_strides=(1, 1),
            padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return out.astype(jnp.float32)

    @staticmethod
    def _max_pool(x: jax.Array) -> jax.Array:
        return lax.reduce_window(
            x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        )

    def apply_logits(self, params: CNNParams, x: jax.Array) -> jax.Array:
        """Forward pass → pre-softmax logits, float32.

        Accepts the data pipeline's flattened ``[B, H*W*C]`` batches (the
        reference's feed shape) or already-shaped ``[B, H, W, C]``.
        """
        cd = self.compute_dtype
        s = self.image_size
        if x.ndim == 2:
            x = x.reshape(x.shape[0], s, s, self.in_channels)
        h = jax.nn.relu(self._conv(x, params.conv1_w) + params.conv1_b)
        h = self._max_pool(h)
        h = jax.nn.relu(self._conv(h, params.conv2_w) + params.conv2_b)
        h = self._max_pool(h)
        h = h.reshape(h.shape[0], self.flat_dim)
        h = jnp.dot(h.astype(cd), params.fc1_w.astype(cd), preferred_element_type=jnp.float32)
        h = jax.nn.relu(h + params.fc1_b)
        logits = jnp.dot(h.astype(cd), params.fc2_w.astype(cd), preferred_element_type=jnp.float32)
        return logits + params.fc2_b

    def apply(self, params: CNNParams, x: jax.Array) -> jax.Array:
        """Forward pass → class probabilities, float32 (same output contract
        as models/mlp.py, so ops/losses.cross_entropy applies unchanged)."""
        return jax.nn.softmax(self.apply_logits(params, x), axis=-1)

    # -- parallelism -------------------------------------------------------

    def partition_specs(self, model_axis: str = "model") -> CNNParams:
        """Tensor-parallel layout over the mesh's ``model`` axis.

        Two Megatron-style column→row pairs: conv1 sharded on output
        channels / conv2 on input channels, and fc1 sharded on output
        features / fc2 on input features. GSPMD inserts the one all-reduce
        each row-parallel member needs; the relu/pool between the members
        runs on local shards.
        """
        from jax.sharding import PartitionSpec as P

        return CNNParams(
            conv1_w=P(None, None, None, model_axis),
            conv1_b=P(model_axis),
            conv2_w=P(None, None, model_axis, None),
            conv2_b=P(None),
            fc1_w=P(None, model_axis),
            fc1_b=P(model_axis),
            fc2_w=P(model_axis, None),
            fc2_b=P(None),
        )
