"""The reference workload: a 2-layer sigmoid/softmax MLP (component C8).

Reference graph (reference tfsingle.py:23-42, identical in all four scripts)::

    y = softmax( sigmoid(x @ W1 + b1) @ W2 + b2 )
    x: [B, 784]   W1: [784, 100] ~ N(0, 1)   b1: zeros(100)
                  W2: [100, 10]  ~ N(0, 1)   b2: zeros(10)
    seed: tf.set_random_seed(1)              (reference tfsingle.py:17)

This is a pure-function re-design, not a graph translation: parameters are an
explicit pytree, the forward pass is a jit-able function of (params, x), and
the TPU mapping is explicit — matmuls run on the MXU in bfloat16 with float32
accumulation (``preferred_element_type``), and probabilities are produced in
float32 so the reference's numerically naive ``log(softmax)`` loss
(reference tfsingle.py:44-45) stays finite.

Init parity is distributional, not bitwise (SURVEY.md §7 hard-part b): TF1's
``random_normal`` stddev-1 draws become JAX PRNG normal draws with the same
moments; the convergence oracle (≥0.72 test accuracy, SURVEY.md §4) validates
equivalence.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class MLPParams(NamedTuple):
    """Parameter pytree. NamedTuple keeps it a static-structure pytree that
    jit/shard_map handle with zero overhead."""

    w1: jax.Array  # [in_dim, hidden]
    b1: jax.Array  # [hidden]
    w2: jax.Array  # [hidden, out]
    b2: jax.Array  # [out]


class MLP:
    """The reference's 784→100→10 MLP as pure init/apply functions."""

    def __init__(
        self,
        in_dim: int = 784,
        hidden_dim: int = 100,
        out_dim: int = 10,
        compute_dtype: jnp.dtype = jnp.bfloat16,
    ):
        self.in_dim = in_dim
        self.hidden_dim = hidden_dim
        self.out_dim = out_dim
        self.compute_dtype = compute_dtype

    def init(self, seed: int = 1) -> MLPParams:
        """N(0,1) weights, zero biases — matching the reference's
        ``random_normal``/zeros init (reference tfsingle.py:30-36)."""
        k1, k2 = jax.random.split(jax.random.key(seed))
        return MLPParams(
            w1=jax.random.normal(k1, (self.in_dim, self.hidden_dim), jnp.float32),
            b1=jnp.zeros((self.hidden_dim,), jnp.float32),
            w2=jax.random.normal(k2, (self.hidden_dim, self.out_dim), jnp.float32),
            b2=jnp.zeros((self.out_dim,), jnp.float32),
        )

    def apply(self, params: MLPParams, x: jax.Array) -> jax.Array:
        """Forward pass → class probabilities, float32.

        Matmuls are cast to ``compute_dtype`` (bf16 → MXU) and accumulate in
        float32; the softmax itself runs in float32 for loss stability.
        """
        cd = self.compute_dtype
        h = jnp.dot(
            x.astype(cd), params.w1.astype(cd), preferred_element_type=jnp.float32
        )
        h = jax.nn.sigmoid(h + params.b1)
        logits = jnp.dot(
            h.astype(cd), params.w2.astype(cd), preferred_element_type=jnp.float32
        )
        logits = logits + params.b2
        return jax.nn.softmax(logits, axis=-1)

    def partition_specs(self, model_axis: str = "model") -> MLPParams:
        """Tensor-parallel layout over the mesh's ``model`` axis (SURVEY.md
        §2b: the reference has no TP; the mesh keeps the axis first-class).

        Megatron-style column→row split: W1 sharded on its output (hidden)
        dim, W2 on its input (hidden) dim — the sigmoid runs on local shards
        and XLA inserts one all-reduce after the second matmul.
        """
        from jax.sharding import PartitionSpec as P

        return MLPParams(
            w1=P(None, model_axis),
            b1=P(model_axis),
            w2=P(model_axis, None),
            b2=P(None),
        )

    def apply_logits(self, params: MLPParams, x: jax.Array) -> jax.Array:
        """Forward pass returning pre-softmax logits (for stable-loss variants)."""
        cd = self.compute_dtype
        h = jnp.dot(
            x.astype(cd), params.w1.astype(cd), preferred_element_type=jnp.float32
        )
        h = jax.nn.sigmoid(h + params.b1)
        logits = jnp.dot(
            h.astype(cd), params.w2.astype(cd), preferred_element_type=jnp.float32
        )
        return logits + params.b2
