"""Recurrent model family: an LSTM classifier over row-sequential MNIST.

The reference ships exactly one model — the 784→100→10 MLP repeated in each
script (reference tfsingle.py:23-42). This family completes the framework's
model-protocol proof alongside the CNN and transformer: a *stateful-
recurrence* workload that drops into the unchanged strategies/Trainer on the
same flattened ``[B, 784]`` batches the reference's ``feed_dict`` carried
(reference tfdist_between.py:92-94), read as a sequence of 28 rows × 28
features (the classic "sequential MNIST" task).

TPU mapping — recurrence is where naive ports die on TPU, so the design is
explicit about the XLA semantics:

- The time loop is ``lax.scan`` — traced once, compiled once, no Python
  per-step dispatch (the reference's per-batch ``sess.run`` pathology,
  SURVEY.md §3.1, would reappear *per time step* in an eager loop).
- The four gate projections are **one fused matmul** per step against a
  stacked ``[in+hidden, 4, hidden]`` kernel: a single MXU-shaped contraction
  in bfloat16 with float32 accumulation instead of four skinny ones.
- Cell and hidden state stay float32 — bf16 carries across 28 recurrence
  steps compound rounding error; matmul inputs are cast per step.
- The head reads the final hidden state; softmax is float32 so the
  reference's numerically naive ``log(softmax)`` loss (ops/losses.py)
  stays finite.

Tensor-parallel layout (``partition_specs``): hidden units shard over the
mesh's ``model`` axis — the gate kernel on its hidden output dim, the head
on its hidden input dim (Megatron column→row). Gate nonlinearities and the
cell update are elementwise over hidden units, so they run shard-local;
GSPMD inserts the all-gather of ``h`` feeding the next step's fused matmul
and the all-reduce after the head.

Init is fan-in-scaled normal with the standard +1 forget-gate bias (keeps
gradient flow open through the 28 steps), deterministic from an integer
seed like every model here (the property supervisor-free chief init relies
on, models/base.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LSTMParams(NamedTuple):
    """Parameter pytree. Gate order on the stacked axis: i, f, g, o."""

    w: jax.Array  # [in+hidden, 4, hidden] fused gate kernel
    b: jax.Array  # [4, hidden] gate biases (forget gate init to +1)
    head_w: jax.Array  # [hidden, out]
    head_b: jax.Array  # [out]


class LSTMClassifier:
    """scan(LSTM cell over rows) → dense head → softmax, on [B, T*F] input."""

    def __init__(
        self,
        seq_len: int = 28,
        feature_dim: int = 28,
        hidden_dim: int = 128,
        out_dim: int = 10,
        compute_dtype: jnp.dtype = jnp.bfloat16,
    ):
        self.seq_len = seq_len
        self.feature_dim = feature_dim
        self.hidden_dim = hidden_dim
        self.out_dim = out_dim
        self.compute_dtype = compute_dtype

    # -- init --------------------------------------------------------------

    def init(self, seed: int = 1) -> LSTMParams:
        """Fan-in-scaled normal kernels, +1 forget-gate bias, zero elsewhere;
        fully deterministic from ``seed``."""
        kw, kh = jax.random.split(jax.random.key(seed))
        fan_in = self.feature_dim + self.hidden_dim
        b = jnp.zeros((4, self.hidden_dim), jnp.float32)
        b = b.at[1].set(1.0)  # forget gate
        return LSTMParams(
            w=jax.random.normal(
                kw, (fan_in, 4, self.hidden_dim), jnp.float32
            )
            * jnp.sqrt(1.0 / fan_in),
            b=b,
            head_w=jax.random.normal(
                kh, (self.hidden_dim, self.out_dim), jnp.float32
            )
            * jnp.sqrt(1.0 / self.hidden_dim),
            head_b=jnp.zeros((self.out_dim,), jnp.float32),
        )

    # -- forward -----------------------------------------------------------

    def _cell(self, params: LSTMParams, carry, x_t: jax.Array):
        """One LSTM step: fused-gate matmul (MXU, bf16×bf16→f32) + f32 state
        update. ``carry = (h, c)``, both [B, hidden] float32."""
        h, c = carry
        cd = self.compute_dtype
        z = jnp.concatenate([x_t, h], axis=-1)
        gates = (
            jnp.einsum(
                "bi,igh->bgh",
                z.astype(cd),
                params.w.astype(cd),
                preferred_element_type=jnp.float32,
            )
            + params.b
        )
        i = jax.nn.sigmoid(gates[:, 0])
        f = jax.nn.sigmoid(gates[:, 1])
        g = jnp.tanh(gates[:, 2])
        o = jax.nn.sigmoid(gates[:, 3])
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), None

    def apply_logits(self, params: LSTMParams, x: jax.Array) -> jax.Array:
        """Forward pass → pre-softmax logits, float32.

        Accepts the data pipeline's flattened ``[B, T*F]`` batches (the
        reference's feed shape) or already-shaped ``[B, T, F]``.
        """
        if x.ndim == 2:
            x = x.reshape(x.shape[0], self.seq_len, self.feature_dim)
        batch = x.shape[0]
        h0 = jnp.zeros((batch, self.hidden_dim), jnp.float32)
        carry = (h0, h0)
        # Time-major for scan: [T, B, F].
        xs = jnp.swapaxes(x.astype(jnp.float32), 0, 1)
        (h, _), _ = jax.lax.scan(lambda cr, xt: self._cell(params, cr, xt), carry, xs)
        cd = self.compute_dtype
        logits = jnp.dot(
            h.astype(cd),
            params.head_w.astype(cd),
            preferred_element_type=jnp.float32,
        )
        return logits + params.head_b

    def apply(self, params: LSTMParams, x: jax.Array) -> jax.Array:
        """Forward pass → class probabilities, float32."""
        return jax.nn.softmax(self.apply_logits(params, x), axis=-1)

    # -- parallelism -------------------------------------------------------

    def partition_specs(self, model_axis: str = "model") -> LSTMParams:
        """Megatron column→row split over hidden units (see module
        docstring): gate kernel/biases sharded on hidden, head row-sharded."""
        from jax.sharding import PartitionSpec as P

        return LSTMParams(
            w=P(None, None, model_axis),
            b=P(None, model_axis),
            head_w=P(model_axis, None),
            head_b=P(None),
        )
