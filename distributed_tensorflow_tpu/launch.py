"""High-level launcher: config → model + strategy + trainer (L7).

This is where ``TrainConfig``'s mode knobs are honored:

- ``sync=True``  → :class:`SyncDataParallel` (or :class:`SingleDevice` on a
  1-chip mesh) — the ``tfdist_between_sync.py`` path;
- ``sync=False`` → :class:`AsyncDataParallel` with
  ``avg_every=async_avg_every`` — the ``tfdist_between.py`` path;
- ``compute_dtype`` → the model's MXU compute dtype;
- ``checkpoint_dir`` → a :class:`Supervisor` wired into the trainer;
- ``logs_path`` → the TensorBoard scalar writer (chief only, matching the
  reference where every worker wrote summaries but only the chief's mattered).

The reference's per-script wiring (build graph → Supervisor → loop,
reference tfdist_between.py:32-113) collapses into :func:`build_trainer`.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from distributed_tensorflow_tpu.config import ClusterConfig, TrainConfig

if TYPE_CHECKING:  # jax-backed types only; see the lazy imports below
    from distributed_tensorflow_tpu.cluster import ProcessContext
    from distributed_tensorflow_tpu.train.trainer import Trainer
    from distributed_tensorflow_tpu.utils.summary import SummaryWriter

# The jax-backed stack (strategies, models, data, Trainer) is imported
# inside build_strategy/build_trainer/run: the config surface of this
# module (config_from_env / cluster_from_env) is also the elastic
# driver's — a lean supervisor process, or a degraded container, must be
# able to parse the DTF_* env without a working jax (same rationale as
# the lazy train/__init__).


def config_from_env(base: TrainConfig | None = None) -> TrainConfig:
    """Apply environment overrides to a TrainConfig — the knob the reference
    lacked (its hyperparameters were module constants, SURVEY.md §5
    "Config/flag system"). Recognized: DTF_EPOCHS, DTF_BATCH_SIZE, DTF_LR,
    DTF_SCAN (=1 → scan_epoch), DTF_COMPILED (=1 → compiled_run: the whole
    run as one dispatch), DTF_LOGS (logs path, empty disables),
    DTF_MODEL (registry name: mlp | cnn | lstm | transformer), and the
    resilience knobs (train/resilience.py): DTF_CHECKPOINT (checkpoint
    dir — what a pod scheduler sets so a preempted run can resume),
    DTF_KEEP_LAST (checkpoint retention), DTF_MAX_ROLLBACKS (anomaly
    guard budget), and the elastic knobs (train/elastic.py):
    DTF_MAX_RESTARTS (gang-restart budget), DTF_STALL_TIMEOUT_MS
    (live-but-stalled detection window), DTF_MIN_WORKERS (shrink-to-fit
    floor, round 8; 0 disables resizing) and DTF_REJOIN_TIMEOUT_S
    (replacement-registration window before a resize), the round-13
    perf knobs: DTF_REMAT (0 | 1 | selective) and DTF_MATMUL_DTYPE
    (int8 | fp8, empty → off), and the DiLoCo outer-loop knobs
    (train/local_sgd.py): DTF_SYNC_EVERY (H inner steps per outer
    round), DTF_OUTER_LR (empty → the worker-count default),
    DTF_OUTER_MOMENTUM, and the round-17 streaming/compressed levers:
    DTF_DELTA_DTYPE (int8 | fp8, empty → full-precision deltas) and
    DTF_STALE_LIMIT (stale-tolerant gang window in outer rounds; 0 =
    same-round deltas only). Invalid values
    raise ValueError naming the knob — a scheduler typo must fail the
    launch, not silently train with defaults (TrainConfig.__post_init__
    validates the perf-knob values the same way)."""
    import os

    def _parse(var: str, conv):
        try:
            return conv(os.environ[var])
        except ValueError as exc:
            raise ValueError(
                f"invalid {var}={os.environ[var]!r}: {exc}"
            ) from None

    cfg = base or TrainConfig()
    kw = {}
    if "DTF_CHECKPOINT" in os.environ:
        kw["checkpoint_dir"] = os.environ["DTF_CHECKPOINT"] or None
    if "DTF_KEEP_LAST" in os.environ:
        kw["keep_last_n"] = _parse("DTF_KEEP_LAST", int) or None
    if "DTF_MAX_ROLLBACKS" in os.environ:
        kw["max_rollbacks"] = _parse("DTF_MAX_ROLLBACKS", int)
    if "DTF_MAX_RESTARTS" in os.environ:
        kw["max_restarts"] = _parse("DTF_MAX_RESTARTS", int)
    if "DTF_STALL_TIMEOUT_MS" in os.environ:
        kw["stall_timeout_ms"] = _parse("DTF_STALL_TIMEOUT_MS", int)
    if "DTF_MIN_WORKERS" in os.environ:
        kw["min_workers"] = _parse("DTF_MIN_WORKERS", int)
    if "DTF_REJOIN_TIMEOUT_S" in os.environ:
        kw["rejoin_timeout_s"] = _parse("DTF_REJOIN_TIMEOUT_S", float)
    if "DTF_MODEL" in os.environ:
        kw["model"] = os.environ["DTF_MODEL"]
    if "DTF_EPOCHS" in os.environ:
        kw["epochs"] = _parse("DTF_EPOCHS", int)
    if "DTF_BATCH_SIZE" in os.environ:
        kw["batch_size"] = _parse("DTF_BATCH_SIZE", int)
    if "DTF_LR" in os.environ:
        kw["learning_rate"] = _parse("DTF_LR", float)
    if "DTF_SCAN" in os.environ:
        kw["scan_epoch"] = os.environ["DTF_SCAN"] == "1"
    if "DTF_COMPILED" in os.environ:
        kw["compiled_run"] = os.environ["DTF_COMPILED"] == "1"
    if "DTF_LOGS" in os.environ:
        kw["logs_path"] = os.environ["DTF_LOGS"]
    if "DTF_SYNC_EVERY" in os.environ:
        kw["sync_every"] = _parse("DTF_SYNC_EVERY", int)
    if "DTF_OUTER_LR" in os.environ:
        # Empty = the worker-count default (the update_scale=N
        # convention), mirroring the other unset-style knobs.
        raw = os.environ["DTF_OUTER_LR"]
        kw["outer_lr"] = _parse("DTF_OUTER_LR", float) if raw else None
    if "DTF_OUTER_MOMENTUM" in os.environ:
        kw["outer_momentum"] = _parse("DTF_OUTER_MOMENTUM", float)
    if "DTF_DELTA_DTYPE" in os.environ:
        # Empty = full-precision deltas (the unset-style contract, like
        # DTF_MATMUL_DTYPE); bad names fail in TrainConfig.__post_init__.
        kw["delta_dtype"] = os.environ["DTF_DELTA_DTYPE"] or None
    if "DTF_STALE_LIMIT" in os.environ:
        kw["stale_limit"] = _parse("DTF_STALE_LIMIT", int)
    if "DTF_REMAT" in os.environ:
        raw = os.environ["DTF_REMAT"]
        # Empty/0/1 keep the boolean surface (empty = off, matching the
        # sibling knob's unset-style contract); "selective" is the
        # round-13 policy; anything else fails in
        # TrainConfig.__post_init__.
        kw["remat"] = raw == "1" if raw in ("", "0", "1") else raw
    if "DTF_MATMUL_DTYPE" in os.environ:
        kw["matmul_dtype"] = os.environ["DTF_MATMUL_DTYPE"] or None
    return cfg.replace(**kw) if kw else cfg


def parse_worker_ranks(raw: str) -> tuple[int, ...]:
    """Parse a ``DTF_WORKER_RANKS`` value (comma-separated ORIGINAL
    ranks in new-rank order). THE one parser for the knob — the elastic
    driver writes it, :func:`cluster_from_env` resolves the resize
    topology from it, and ``cluster.bootstrap`` maps compact ranks back
    to original ids for per-rank journals; all three must agree on what
    is valid."""
    try:
        return tuple(int(r) for r in raw.split(","))
    except ValueError:
        raise ValueError(
            f"invalid DTF_WORKER_RANKS={raw!r}: must be comma-separated "
            "integers (original ranks in new-rank order)"
        ) from None


def cluster_from_env(base: ClusterConfig | None = None) -> ClusterConfig:
    """Apply environment overrides to a ClusterConfig — the detector half
    of the pod-scheduler surface (the trainer half is
    :func:`config_from_env`). Recognized: DTF_HEARTBEAT_PORT (UDP failure
    detector port; empty/0 disables), DTF_HEARTBEAT_TIMEOUT_MS (silence
    window), DTF_HEARTBEAT_HOST (set by an elastic agent —
    train/elastic.py — that hosts the detector out-of-band; every task
    then sends beats there instead of the chief hosting). ``launch.run``
    applies this, so a scheduler arms failure detection without code
    changes, mirroring DTF_CHECKPOINT/DTF_MAX_ROLLBACKS.

    Resize topology (round 8; set by the elastic driver on a relaunch at
    a non-original world size): DTF_WORKER_RANKS — comma-separated
    ORIGINAL ranks in new-rank order, resolved via
    ``ClusterConfig.subset`` (the worker re-bootstraps
    ``jax.distributed`` at ``len(ranks)`` processes with ``ranks[0]``'s
    host as coordinator); DTF_WORLD_SIZE — shorthand for the first-N
    prefix when the survivor set IS a prefix, and a cross-check
    (``len(ranks)`` must match) when both are set. Invalid values raise
    ValueError naming the knob."""
    import dataclasses
    import os

    def _parse(var: str, conv):
        try:
            return conv(os.environ[var])
        except ValueError as exc:
            raise ValueError(
                f"invalid {var}={os.environ[var]!r}: {exc}"
            ) from None

    cluster = base or ClusterConfig()
    kw = {}
    if "DTF_HEARTBEAT_PORT" in os.environ:
        raw = os.environ["DTF_HEARTBEAT_PORT"]
        kw["heartbeat_port"] = _parse("DTF_HEARTBEAT_PORT", int) if raw else None
        if kw["heartbeat_port"] == 0:
            kw["heartbeat_port"] = None
    if "DTF_HEARTBEAT_TIMEOUT_MS" in os.environ:
        kw["heartbeat_timeout_ms"] = _parse("DTF_HEARTBEAT_TIMEOUT_MS", int)
    if "DTF_HEARTBEAT_HOST" in os.environ:
        kw["heartbeat_host"] = os.environ["DTF_HEARTBEAT_HOST"] or None
    cluster = dataclasses.replace(cluster, **kw) if kw else cluster

    ranks = None
    if os.environ.get("DTF_WORKER_RANKS"):
        ranks = parse_worker_ranks(os.environ["DTF_WORKER_RANKS"])
    if os.environ.get("DTF_WORLD_SIZE"):
        raw = os.environ["DTF_WORLD_SIZE"]
        try:
            world = int(raw)
        except ValueError:
            raise ValueError(
                f"invalid DTF_WORLD_SIZE={raw!r}: must be an integer"
            ) from None
        if world < 1:
            raise ValueError(f"invalid DTF_WORLD_SIZE={world}: must be >= 1")
        if ranks is None:
            ranks = tuple(range(world))
        elif len(ranks) != world:
            raise ValueError(
                f"DTF_WORLD_SIZE={world} contradicts DTF_WORKER_RANKS="
                f"{ranks} (length {len(ranks)})"
            )
    if ranks is not None:
        if not cluster.worker_svrs:
            raise ValueError(
                "DTF_WORLD_SIZE/DTF_WORKER_RANKS set but the base "
                "ClusterConfig lists no worker_svrs to select from"
            )
        cluster = cluster.subset(ranks)
    return cluster


def build_strategy(config: TrainConfig, *, devices=None, mesh=None):
    if config.dp_mode not in ("replicated", "zero"):
        raise ValueError(
            f"unknown dp_mode {config.dp_mode!r} for the classifier path; "
            "use 'replicated' or 'zero' ('tp'/'ep'/'pp' are LM-trainer "
            "modes — train/lm_trainer.py)"
        )
    if config.dp_mode == "zero" and not config.sync:
        raise ValueError("dp_mode='zero' requires sync=True (async keeps per-chip copies)")
    import jax

    from distributed_tensorflow_tpu.parallel import (
        AsyncDataParallel,
        SingleDevice,
        SyncDataParallel,
        make_mesh,
    )

    devices = list(devices if devices is not None else jax.devices())
    if mesh is None and len(devices) == 1:
        return SingleDevice()
    mesh = mesh or make_mesh(devices=devices)
    if config.sync:
        if config.dp_mode == "zero":
            from distributed_tensorflow_tpu.parallel import ShardedDataParallel

            return ShardedDataParallel(mesh)
        return SyncDataParallel(mesh)
    return AsyncDataParallel(mesh, avg_every=config.async_avg_every)


class _RematAdapter:
    """Applies ``jax.checkpoint`` to the model forward: activations are
    recomputed during the backward pass instead of stored — the standard
    TPU trade of MXU FLOPs for HBM activation memory. Gradients are
    mathematically identical (tests/test_launch.py proves bitwise-close);
    only peak memory and backward-pass FLOPs change. No reference analog
    (TF1 stored everything)."""

    def __init__(self, model):
        import jax

        self._model = model
        self._apply = jax.checkpoint(model.apply)
        if hasattr(model, "apply_logits"):
            # Keep the stable-loss path remat'd too (loss="stable" wraps
            # apply_logits via _LogitsAdapter after this adapter).
            self.apply_logits = jax.checkpoint(model.apply_logits)

    def __getattr__(self, name):
        return getattr(self._model, name)

    def apply(self, params, x):
        return self._apply(params, x)


class _LogitsAdapter:
    """Presents ``apply_logits`` as ``apply`` so the logits-based stable
    loss composes with the strategy stack (accuracy argmax is unchanged)."""

    def __init__(self, model):
        self._model = model

    def __getattr__(self, name):
        return getattr(self._model, name)

    def apply(self, params, x):
        return self._model.apply_logits(params, x)


def build_trainer(
    config: TrainConfig | None = None,
    *,
    context: ProcessContext | None = None,
    model=None,
    datasets=None,
    strategy=None,
    optimizer=None,
    loss_fn=None,
    data_dir: str = "MNIST_data",
    summary_writer: SummaryWriter | None = None,
    print_fn=print,
) -> Trainer:
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.data import read_data_sets
    from distributed_tensorflow_tpu.ops import optim as optim_lib
    from distributed_tensorflow_tpu.train.trainer import Trainer
    from distributed_tensorflow_tpu.utils.summary import SummaryWriter

    config = config or TrainConfig()
    # Pure config validation runs BEFORE any model/dataset construction.
    if getattr(config, "matmul_dtype", None):
        raise ValueError(
            "matmul_dtype is an LM-family knob (models/gpt.GPTLM / "
            "LMTrainer); the classifier models have no quantized path"
        )
    is_chief = context.is_chief if context is not None else True
    if model is None:
        from distributed_tensorflow_tpu.models import build_model

        model = build_model(
            config.model, compute_dtype=jnp.dtype(config.compute_dtype)
        )
    if config.remat:
        # Any truthy value — including "selective" — is plain
        # jax.checkpoint here: the classifier models carry no
        # checkpoint-name surface for a selective policy to save.
        model = _RematAdapter(model)
    datasets = datasets or read_data_sets(data_dir, one_hot=True)
    strategy = strategy or build_strategy(config)
    if optimizer is None:
        # The schedule count advances once per optimizer *apply*: trainer
        # epochs run num_examples // (batch_size × replicas) steps (global
        # batches; trainer.py), or // batch_size under per_worker_epoch, and
        # accumulation applies once every accumulate_steps micro-steps.
        denom = config.batch_size * (
            1 if config.per_worker_epoch else strategy.num_replicas
        )
        applies_per_epoch = max(1, datasets.train.num_examples // denom)
        total_applies = max(
            1, config.epochs * applies_per_epoch // config.accumulate_steps
        )
        lr = optim_lib.schedule(
            config.lr_schedule,
            config.learning_rate,
            total_applies,
            warmup_steps=config.warmup_steps,
        )
        optimizer = optim_lib.accumulate(
            optim_lib.clip(
                optim_lib.make(config.optimizer, lr), config.grad_clip_norm
            ),
            config.accumulate_steps,
        )
    if loss_fn is None:
        from distributed_tensorflow_tpu.ops import losses as losses_lib

        if config.loss == "stable":
            if not hasattr(model, "apply_logits"):
                raise ValueError(
                    f"loss='stable' needs apply_logits on {type(model).__name__}"
                )
            model = _LogitsAdapter(model)
            loss_fn = losses_lib.stable_cross_entropy
        elif config.loss == "naive":
            loss_fn = losses_lib.cross_entropy
        else:
            raise ValueError(f"unknown loss {config.loss!r}; use 'naive' or 'stable'")
    if summary_writer is None and is_chief and config.logs_path:
        summary_writer = SummaryWriter(config.logs_path)
    trainer = Trainer(
        model,
        datasets,
        config,
        strategy=strategy,
        optimizer=optimizer,
        loss_fn=loss_fn,
        summary_writer=summary_writer,
        is_chief=is_chief,
        print_fn=print_fn,
    )
    # Failure-reactive stop: a chief with an armed heartbeat coordinator
    # (cluster.bootstrap(heartbeat_port=...)) stops cleanly when a worker
    # dies — or, with stall_timeout_ms set, stalls — instead of hanging in
    # a collective (train/supervisor.py). In elastic mode
    # (heartbeat_host set) the detector lives in the agent and
    # context.heartbeat is a plain SENDER even on the chief — nothing to
    # attach, hence the coordinator-shape check.
    if context is not None:
        has_coordinator = context.heartbeat is not None and hasattr(
            context.heartbeat, "failed_count"
        )
        has_sender = any(
            h is not None and hasattr(h, "set_progress")
            for h in (context.heartbeat_sender, context.heartbeat)
        )
        if (has_coordinator and is_chief) or has_sender:
            if trainer.supervisor is None:
                from distributed_tensorflow_tpu.train import Supervisor

                trainer.supervisor = Supervisor(is_chief=is_chief)
            if has_coordinator and is_chief:
                trainer.supervisor.attach_heartbeat(
                    context.heartbeat,
                    stall_timeout_ms=config.stall_timeout_ms,
                )
            if has_sender:
                # Progress-aware health: the trainer bumps the counter at
                # epoch boundaries; the beats carry it to the detector.
                trainer.supervisor.attach_progress(context.report_progress)
    return trainer


def run(
    cluster: ClusterConfig | None = None,
    config: TrainConfig | None = None,
    argv=None,
    **kw,
) -> dict | None:
    """End-to-end entry: parse flags, bootstrap, train. Returns the final
    metrics dict (or None for a ps no-op process)."""
    from distributed_tensorflow_tpu.cluster import bootstrap_from_argv

    # Env overrides (pod-scheduler surface): heartbeat/elastic knobs ride
    # DTF_* like the resilience knobs; bootstrap_from_argv then threads the
    # cluster-level heartbeat settings into bootstrap, so the documented
    # launch.run(cluster) entry gets failure detection too.
    cluster = cluster_from_env(cluster or ClusterConfig())
    ctx = bootstrap_from_argv(cluster, argv)
    if ctx.should_exit:
        return None
    try:
        trainer = build_trainer(config_from_env(config), context=ctx, **kw)
        print("Ready to go")  # reference tfdist_between.py:76
        return trainer.run()  # honors compiled_run / scan_epoch internally
    finally:
        ctx.close()  # stop heartbeat threads (sv.stop() analog)
