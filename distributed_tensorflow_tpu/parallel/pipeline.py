"""Pipeline parallelism: GPipe-style microbatched execution over a
``stage`` mesh axis.

Absent from the reference (SURVEY.md §2b: no pipeline stages — one tiny
MLP), provided as first-class machinery completing the framework's
parallelism matrix (dp / tp / sp / ep / pp, each live-tested). One layer's
parameters live on each device of the ``stage`` axis; activations flow
stage-to-stage over single ``ppermute`` hops; M microbatches fill the
pipeline so all S stages compute concurrently after the fill phase
(M + S - 1 total ticks).

Call :func:`pipeline_apply` inside ``jax.shard_map`` over the stage axis,
with per-stage parameters sharded on their leading axis and the microbatched
input replicated.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_tpu.ops.collectives import to_varying


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x_microbatches: jax.Array,
    axis_name: str = "stage",
) -> jax.Array:
    """Run ``y_mb = f_{S-1}(...f_1(f_0(x_mb)))`` for every microbatch.

    - ``stage_fn(params_slice, x) -> y``: one stage's computation; input and
      output activation shapes must match across stages (pipeline wiring).
    - ``stage_params``: pytree whose leaves carry a leading [1, ...] local
      slice (the full [S, ...] stack sharded over ``axis_name``).
    - ``x_microbatches``: [M, B, ...] microbatched input, replicated.

    Returns the [M, B, ...] outputs, replicated on every stage device.
    """
    s_count = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    act_shape = x_microbatches.shape[1:]
    perm = [(j, (j + 1) % s_count) for j in range(s_count)]

    pvary = lambda v: to_varying(v, (axis_name,))  # noqa: E731
    # Zeros DERIVED from the input (stop_gradient(x)*0, not fresh
    # constants) so they inherit its varying-axes type: under a 2-D
    # dp×stage shard_map the microbatches are varying over 'data' too,
    # and the fori_loop carry must carry that vma from tick 0 (check_vma
    # rejects a mid-loop lub). stop_gradient keeps the zeros off the AD
    # path (ops/ring_attention.py rationale).
    x0 = lax.stop_gradient(x_microbatches) * 0
    carry = pvary(x0[0])
    out = pvary(x0.astype(jnp.float32))

    def tick(t, state):
        carry, out = state
        mb = t - my  # which microbatch this stage works on at tick t
        valid = (mb >= 0) & (mb < m)
        x_in = x_microbatches[jnp.clip(mb, 0, m - 1)]
        inp = jnp.where(my == 0, x_in, carry)
        y = stage_fn(stage_params, inp).astype(jnp.float32)
        y = jnp.where(valid, y, 0.0)
        # Final stage banks its finished microbatch.
        bank = (my == s_count - 1) & valid
        update = lax.dynamic_update_slice(
            out, y[None], (jnp.clip(mb, 0, m - 1),) + (0,) * len(act_shape)
        )
        out = jnp.where(bank, update, out)
        carry = lax.ppermute(y.astype(x_microbatches.dtype), axis_name, perm)
        return carry, out

    _, out = lax.fori_loop(0, m + s_count - 1, tick, (carry, out))
    # Only the last stage holds real outputs; share them with every stage.
    return lax.psum(out, axis_name).astype(x_microbatches.dtype)


def microbatch(x: jax.Array, num_microbatches: int) -> jax.Array:
    """[B, ...] → [M, B/M, ...]."""
    b = x.shape[0]
    assert b % num_microbatches == 0, (b, num_microbatches)
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])
