"""Device mesh construction (component C7's TPU-native replacement).

The reference's placement layer is ``tf.train.replica_device_setter`` pinning
variables round-robin onto ``/job:ps`` tasks and ops onto
``/job:worker/task:N/gpu:N`` (reference tfdist_between.py:32-35). On TPU there
are no device strings and no PS: placement is a ``jax.sharding.Mesh`` plus
``PartitionSpec`` annotations, and XLA/GSPMD inserts the collectives.

The canonical mesh here is 2-D ``('data', 'model')``:

- ``data``  — batch sharding + gradient all-reduce (the reference's only
  parallelism dimension, SURVEY.md §2b);
- ``model`` — tensor-parallel axis for layer sharding; size 1 for reference
  parity but first-class so TP/larger models slot in without redesign
  (SURVEY.md §2b "leave a model axis open").

On multi-host topologies ``jax.make_mesh`` lays the ``data`` axis across
hosts so the gradient all-reduce rides ICI within a slice.
"""

from __future__ import annotations

from typing import Sequence

import jax
from jax.sharding import AxisType, Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    shape: Sequence[int] | None = None,
    axis_names: Sequence[str] = ("data", "model"),
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the training mesh.

    Default: all addressable devices on the ``data`` axis, ``model`` axis of
    size 1 — the TPU equivalent of the reference's N-worker data-parallel
    cluster (len(worker_svrs) → mesh size). Axes are ``Auto`` (GSPMD
    propagation), matching this framework's annotate-and-let-XLA-infer
    design; ``with_sharding_constraint`` requires Auto axes.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    if len(shape) != len(axis_names):
        raise ValueError(f"shape {shape} does not match axis names {axis_names}")
    return jax.make_mesh(
        tuple(shape),
        tuple(axis_names),
        devices=devices,
        axis_types=(AxisType.Auto,) * len(axis_names),
    )


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for values replicated on every chip — the role the reference
    gave PS-hosted variables."""
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding for per-example batch tensors, split along the data axis."""
    return NamedSharding(mesh, P(axis))


def stacked_per_device(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding for pytrees with a leading per-device axis (async-DP parameter
    copies): axis 0 is split across the data axis, one slice per chip."""
    return NamedSharding(mesh, P(axis))
