"""Generic sharding-layout utilities shared across the parallelism
surfaces (TP/PP/EP step factories in ``models/gpt.py``, the ZeRO mode of
``train/lm_trainer.py``, ``parallel/fsdp.py``): spec-tree → sharding-tree
mapping and optimizer-slot spec derivation. No reference analog — the
reference's only layout machinery is ``replica_device_setter``'s variable
round-robin (reference tfdist_between.py:32-35); here layouts are
PartitionSpec pytrees consumed by GSPMD."""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def as_shardings(mesh, spec_tree):
    """Spec pytree → NamedSharding pytree over ``mesh`` (the ``is_leaf``
    guard keeps tree.map from descending into the PartitionSpecs)."""
    return jax.tree.map(
        lambda sp: NamedSharding(mesh, sp),
        spec_tree,
        is_leaf=lambda x: isinstance(x, type(P())),
    )


def slot_specs(optimizer, params_shape, param_specs):
    """Specs for the optimizer state: each optax slot sharded like the
    parameter it tracks, scalars replicated. Slots are matched by tree-path
    suffix (optax moment subtrees mirror the param pytree) — the same
    matching rule ``parallel/fsdp.py`` uses for ZeRO; shape-only matching
    would mislayout same-shaped params with different specs."""
    from jax.tree_util import tree_flatten_with_path

    items = [
        (tuple(path), leaf.shape, spec)
        for (path, leaf), spec in zip(
            tree_flatten_with_path(params_shape)[0],
            jax.tree.leaves(
                param_specs, is_leaf=lambda x: isinstance(x, type(P()))
            ),
        )
    ]

    def slot_spec(path, leaf):
        for ppath, pshape, spec in items:
            if leaf.shape == pshape and tuple(path[-len(ppath):]) == ppath:
                return spec
        return P()

    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    leaves, treedef = tree_flatten_with_path(opt_shape)
    return jax.tree.unflatten(
        treedef, [slot_spec(path, leaf) for path, leaf in leaves]
    )


def pinned_update(optimizer, params, opt_state, grads, shardings,
                  opt_shardings):
    """The ONE pin-grads → update → pin-params-and-slots sequence every
    sharded-layout train step uses (TP, PP, the LM trainer's ZeRO eager
    and scanned bodies — a divergence between copies would silently break
    their proven equality): constrain grads to the owner layout so the
    batch reduction lowers onto it (e.g. reduce-scatter under ZeRO), run
    the optax update locally on each shard, and pin the results back."""
    import optax

    grads = jax.lax.with_sharding_constraint(grads, shardings)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    params = jax.lax.with_sharding_constraint(params, shardings)
    opt_state = jax.lax.with_sharding_constraint(opt_state, opt_shardings)
    return params, opt_state
