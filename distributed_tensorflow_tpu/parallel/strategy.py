"""Execution strategies: the reference's four run modes, TPU-native.

Reference modes → strategies here (SURVEY.md §2b):

- ``tfsingle.py`` (one device)                    → :class:`SingleDevice`
- ``tfdist_between_sync.py`` (sync DP over PS)    → :class:`SyncDataParallel`
- ``tfdist_between.py`` (async/HOGWILD DP)        → :class:`AsyncDataParallel`
- multi-host (settings.py host lists)             → same strategies over a
  multi-process mesh (see ``cluster.py``)

Design: a Strategy owns placement (how the train state and batches are laid
out on the mesh) and aggregation (what collective combines gradients). The
trainer is strategy-agnostic: it calls ``init_state`` once, then
``train_step(state, x, y) -> (state, cost)`` in the hot loop, all compiled.

Sync DP replaces ``SyncReplicasOptimizer``'s C++ accumulators + token queues
(reference tfdist_between_sync.py:66-68,86) with a single compiled all-reduce
over the mesh ``data`` axis — either implicitly via GSPMD (batch sharded,
params replicated, XLA inserts the reduce) or explicitly via ``shard_map`` +
``lax.pmean``. Both paths are provided; they compile to the same collective.

Async DP cannot be literal on an SPMD machine (XLA is lockstep; SURVEY.md §7
hard-part a). It is emulated as HOGWILD-style *local SGD*: each chip owns a
private parameter copy advancing on its own batch stream (the reference's
per-worker independent ``minimize``, tfdist_between.py:64-66), with two knobs
mapping to the reference's observed semantics:

- ``avg_every`` — periodic parameter exchange (mean over chips), bounding
  staleness the way the PS bounded it by serializing applies;
- ``update_scale`` — scales the learning rate by the replica count to match
  async's N×-total-update-count effect on convergence (the README's
  0.72→0.80 accuracy gain comes from 2× updates, reference README.md:66-72;
  SURVEY.md §2b sanctions step-count/update-count matching).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.ops import losses as losses_lib

from distributed_tensorflow_tpu.ops.collectives import to_varying as _to_varying


class TrainState(NamedTuple):
    """On-device training state. ``step`` is the reference's ``global_step``
    (component C12): scalar under sync, per-chip vector under async."""

    params: Any
    opt_state: Any
    step: jax.Array


LossFn = Callable[[jax.Array, jax.Array], jax.Array]


def merge_replica_leaf(a: jax.Array) -> jax.Array:
    """Fold a leading replica axis to the canonical single copy: float
    leaves merge at the mean (async's own effective_params semantics);
    integer/bool leaves (e.g. adam's int32 count) take replica 0's value —
    the float mean is exact only below 2^24, so mean-then-cast silently
    corrupts a large step count (ADVICE round 5). Integer replicas are
    identical by construction (every copy applied the same number of
    updates); when the call is concrete (the restore paths are), that
    invariant is asserted rather than assumed."""
    if not jnp.issubdtype(a.dtype, jnp.floating):
        if not isinstance(a, jax.core.Tracer) and a.shape[0] > 1:
            if not bool(jnp.all(a == a[0:1])):
                raise ValueError(
                    "integer optimizer-state leaf differs across replicas; "
                    "refusing to merge (the copies should be identical)"
                )
        return a[0]
    return jnp.mean(a, axis=0).astype(a.dtype)


def _loss_from_model(model, loss_fn: LossFn, params, x, y) -> jax.Array:
    return loss_fn(model.apply(params, x), y)


def _scan_with_exchange(step, carry, xs, steps: int, avg_every: int):
    """Scan ``step`` over the leading axis of the pytree ``xs`` (length
    ``steps``), pmean-exchanging the params element of ``carry`` every
    ``avg_every`` iterations — the async emulation's local-stream +
    periodic-exchange cadence as one compiled structure. Exchange happens
    after every full round (including an epoch-final one when the count
    divides); a non-dividing remainder of steps runs after the last
    exchange. Must run inside ``shard_map`` over ``'data'``."""
    if avg_every and steps >= avg_every:
        rounds = steps // avg_every
        head = rounds * avg_every

        def round_body(carry, xs_round):
            carry, costs = jax.lax.scan(step, carry, xs_round)
            params, opt_state = carry
            # pmean output is device-invariant; cast it back to the
            # varying-over-'data' type the scan carry requires.
            params = jax.tree.map(
                lambda a: _to_varying(jax.lax.pmean(a, "data"), "data"),
                params,
            )
            return (params, opt_state), costs

        head_xs = jax.tree.map(
            lambda a: a[:head].reshape(rounds, avg_every, *a.shape[1:]), xs
        )
        carry, costs = jax.lax.scan(round_body, carry, head_xs)
        costs = costs.reshape(head)
        if steps % avg_every:
            carry, tail = jax.lax.scan(
                step, carry, jax.tree.map(lambda a: a[head:], xs)
            )
            costs = jnp.concatenate([costs, tail])
        return carry, costs
    return jax.lax.scan(step, carry, xs)


def _local_sgd_update(model, loss_fn, optimizer, scale, params, opt_state, x, y):
    """One local optimizer apply — the shared update math of the async
    eager step and the async scanned epoch (their bitwise equivalence is a
    tested guarantee, tests/test_scan.py::test_async_scan_matches_eager_async;
    keeping one implementation makes it structural)."""
    cost, grads = jax.value_and_grad(partial(_loss_from_model, model, loss_fn))(
        params, x, y
    )
    updates, opt_state = optimizer.update(grads, opt_state, params)
    updates = jax.tree.map(lambda u: u * scale, updates)
    params = optax.apply_updates(params, updates)
    return params, opt_state, cost


class Strategy:
    """Interface. Subclasses define placement + aggregation."""

    def init_state(self, model, optimizer: optax.GradientTransformation, seed: int) -> TrainState:
        raise NotImplementedError

    def make_train_step(self, model, loss_fn: LossFn, optimizer):
        raise NotImplementedError

    def make_eval_fn(self, model):
        """Returns fn(state, images, labels) -> accuracy (float32 scalar),
        evaluating the state's *effective* parameters on a replicated batch."""
        raise NotImplementedError

    def prepare_batch(self, x, y):
        """Place a host batch onto devices with this strategy's sharding."""
        raise NotImplementedError

    def global_step(self, state: TrainState) -> int:
        return int(jnp.sum(state.step))

    def effective_params(self, state: TrainState):
        """The single parameter set this state denotes — what the reference
        called "the parameters on the PS". Identity for sync strategies;
        async overrides with the mean of the per-chip copies."""
        return state.params

    def cost_scalar(self, cost: jax.Array) -> float:
        return float(jnp.mean(cost))

    # -- cross-topology checkpoint interchange (round 5) ------------------
    # Any strategy's state is a re-layout of ONE canonical form — the
    # single-device (params, opt_state, scalar step). to_canonical folds a
    # state into it (async merges its per-chip copies at the mean, the
    # parameters it evaluates at; sync layouts are already canonical in
    # shape); from_canonical re-stages it into this strategy's layout. A
    # checkpoint saved canonically therefore restores under ANY strategy —
    # dp=N→dp=M, async→sync, TP re-layout — where the reference's
    # Supervisor could only re-attach to the identical topology
    # (reference tfdist_between.py:78,83). LMTrainer carries the same
    # surface for the LM modes (train/lm_trainer.py _state_to_canonical).

    def to_canonical(self, state: TrainState) -> TrainState:
        return TrainState(
            state.params,
            state.opt_state,
            jnp.asarray(jnp.sum(state.step), jnp.int32),
        )

    def from_canonical(self, canonical: TrainState) -> TrainState:
        return canonical

    def layout_meta(self) -> dict:
        """Topology descriptor saved alongside checkpoints (the classifier
        analog of LMTrainer._layout_meta): sync-family layouts share the
        canonical shapes; async overrides with its stacked-copies shape."""
        return {"mode": "sync"}

    @property
    def num_replicas(self) -> int:
        return 1


class SingleDevice(Strategy):
    """The ``tfsingle.py`` mode: everything on one chip, ``jax.jit`` step."""

    def init_state(self, model, optimizer, seed: int) -> TrainState:
        params = model.init(seed)
        state = TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))
        # Commit to the default device: eagerly-built arrays are uncommitted
        # (UnspecifiedValue sharding), so the first dispatch would compile
        # one executable and the second — whose inputs are the committed
        # outputs of the first — would miss the jit cache and recompile
        # (docs/performance.md, "The round-1 73-second warmup 2").
        return jax.device_put(state, jax.devices()[0])

    def from_canonical(self, canonical: TrainState) -> TrainState:
        return jax.device_put(canonical, jax.devices()[0])

    def make_train_step(self, model, loss_fn, optimizer):
        @partial(jax.jit, donate_argnums=0)
        def step(state: TrainState, x, y):
            cost, grads = jax.value_and_grad(
                partial(_loss_from_model, model, loss_fn)
            )(state.params, x, y)
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return TrainState(params, opt_state, state.step + 1), cost

        return step

    def make_eval_fn(self, model):
        @jax.jit
        def evaluate(state: TrainState, x, y):
            return losses_lib.accuracy(model.apply(state.params, x), y)

        return evaluate

    def prepare_batch(self, x, y):
        return jnp.asarray(x), jnp.asarray(y)

    # Scanned-epoch support (config.scan_epoch).
    stage_sharding = None
    replicated_sharding = None  # whole-run staging (train/compiled_run.py)

    def make_scanned_train_fn(self, model, loss_fn, optimizer):
        from distributed_tensorflow_tpu.train.scan import make_scanned_train_fn

        return make_scanned_train_fn(model, loss_fn, optimizer)

    def make_indexed_scanned_train_fn(self, model, loss_fn, optimizer):
        from distributed_tensorflow_tpu.train.scan import (
            make_indexed_scanned_train_fn,
        )

        return make_indexed_scanned_train_fn(model, loss_fn, optimizer)

    def make_compiled_run_fn(self, model, loss_fn, optimizer, **kw):
        from distributed_tensorflow_tpu.train.compiled_run import make_compiled_run_fn

        return make_compiled_run_fn(model, loss_fn, optimizer, **kw)


class SyncDataParallel(Strategy):
    """The ``tfdist_between_sync.py`` mode: lockstep DP with gradient
    averaging — ``SyncReplicasOptimizer`` rebuilt as an ICI all-reduce.

    ``explicit_collectives=False`` (default): GSPMD path — params replicated,
    batch sharded on ``data``, XLA inserts the gradient reduce.
    ``explicit_collectives=True``: ``shard_map`` + ``lax.pmean`` path — the
    collective is visible in the program, pedagogically mirroring the
    reference's explicit aggregation step.
    """

    def __init__(
        self,
        mesh: Mesh,
        *,
        explicit_collectives: bool = False,
        param_specs=None,
    ):
        """``param_specs``: an optional pytree of ``PartitionSpec`` matching
        the model's params (e.g. ``MLP.partition_specs()``) enabling tensor
        parallelism over the ``model`` axis on top of DP over ``data``.
        Without it, params are replicated (pure DP, reference parity)."""
        self.mesh = mesh
        self.explicit = explicit_collectives
        self._repl = NamedSharding(mesh, P())
        self._batch = NamedSharding(mesh, P("data"))
        self.param_specs = param_specs
        self._param_shardings = (
            jax.tree.map(lambda s: NamedSharding(mesh, s), param_specs)
            if param_specs is not None
            else None
        )
        if explicit_collectives and param_specs is not None:
            raise ValueError("explicit_collectives path supports pure DP only")

    @property
    def num_replicas(self) -> int:
        return self.mesh.shape["data"]

    def init_state(self, model, optimizer, seed: int) -> TrainState:
        if self._param_shardings is None:
            params = model.init(seed)
            state = TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))
            return jax.device_put(state, self._repl)

        # TP path: build state inside jit with sharding constraints on the
        # params; GSPMD propagates matching layouts into the optimizer state.
        shardings = self._param_shardings

        @jax.jit
        def _init():
            params = jax.lax.with_sharding_constraint(model.init(seed), shardings)
            return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))

        return _init()

    def from_canonical(self, canonical: TrainState) -> TrainState:
        if self._param_shardings is None:
            return jax.device_put(canonical, self._repl)
        # TP re-layout: shard the params under the specs; optimizer slots
        # ride replicated (GSPMD re-propagates working layouts from the
        # param shardings on the first step).
        return TrainState(
            jax.device_put(canonical.params, self._param_shardings),
            jax.device_put(canonical.opt_state, self._repl),
            jax.device_put(canonical.step, self._repl),
        )

    def make_train_step(self, model, loss_fn, optimizer):
        if self.explicit:
            return self._make_shard_map_step(model, loss_fn, optimizer)
        return self._make_gspmd_step(model, loss_fn, optimizer)

    def _make_gspmd_step(self, model, loss_fn, optimizer):
        shardings = self._param_shardings

        def _step(state: TrainState, x, y):
            cost, grads = jax.value_and_grad(
                partial(_loss_from_model, model, loss_fn)
            )(state.params, x, y)
            if shardings is not None:
                grads = jax.lax.with_sharding_constraint(grads, shardings)
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return TrainState(params, opt_state, state.step + 1), cost

        if shardings is None:
            return partial(
                jax.jit,
                donate_argnums=0,
                in_shardings=(self._repl, self._batch, self._batch),
                out_shardings=(self._repl, self._repl),
            )(_step)
        # TP path: computation follows the data/state shardings laid down by
        # init_state/prepare_batch; no blanket replication constraints.
        return partial(jax.jit, donate_argnums=0)(_step)

    def _make_shard_map_step(self, model, loss_fn, optimizer):
        n = self.num_replicas

        def local_step(state: TrainState, x, y):
            cost, grads = jax.value_and_grad(
                partial(_loss_from_model, model, loss_fn)
            )(state.params, x, y)
            # The reference's SyncReplicasOptimizer accumulate-and-average as
            # one compiled collective over ICI. The cross-replica *sum* is
            # inserted by AD itself: params are unvarying (P()) under
            # shard_map, and the transpose of their broadcast is a psum — so
            # `grads` already holds the summed per-replica gradients; dividing
            # by the replica count completes the average.
            grads = jax.tree.map(lambda g: g / n, grads)
            cost = jax.lax.pmean(cost, "data")
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return TrainState(params, opt_state, state.step + 1), cost

        mapped = jax.shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(P(), P("data"), P("data")),
            out_specs=(P(), P()),
        )
        return jax.jit(mapped, donate_argnums=0)

    def make_eval_fn(self, model):
        def _evaluate(state: TrainState, x, y):
            return losses_lib.accuracy(model.apply(state.params, x), y)

        if self._param_shardings is None:
            return partial(
                jax.jit, in_shardings=(self._repl, self._repl, self._repl)
            )(_evaluate)
        return jax.jit(_evaluate)

    def prepare_batch(self, x, y):
        return (
            jax.device_put(jnp.asarray(x), self._batch),
            jax.device_put(jnp.asarray(y), self._batch),
        )

    # Scanned-epoch support: staged arrays are [steps, batch, ...] with the
    # batch dim sharded over 'data'; each scan slice keeps that sharding.
    @property
    def stage_sharding(self):
        return NamedSharding(self.mesh, P(None, "data"))

    # Whole-run staging (train/compiled_run.py): the full train/test arrays
    # live replicated — per-step batches are random gathers, which would be
    # cross-device traffic if the example dim were sharded. Also makes the
    # staged arrays globally addressable in multi-process meshes.
    @property
    def replicated_sharding(self):
        return self._repl

    def make_scanned_train_fn(self, model, loss_fn, optimizer):
        if self.explicit:
            raise NotImplementedError(
                "scan_epoch uses the GSPMD path; explicit_collectives=False"
            )
        from distributed_tensorflow_tpu.train.scan import make_scanned_train_fn

        return make_scanned_train_fn(
            model, loss_fn, optimizer, batch_sharding=self._batch
        )

    def make_indexed_scanned_train_fn(self, model, loss_fn, optimizer):
        if self.explicit:
            raise NotImplementedError(
                "scan_epoch uses the GSPMD path; explicit_collectives=False"
            )
        from distributed_tensorflow_tpu.train.scan import (
            make_indexed_scanned_train_fn,
        )

        return make_indexed_scanned_train_fn(
            model, loss_fn, optimizer, batch_sharding=self._batch
        )

    def make_compiled_run_fn(self, model, loss_fn, optimizer, **kw):
        if self.explicit:
            raise NotImplementedError(
                "compiled run uses the GSPMD path; explicit_collectives=False"
            )
        from distributed_tensorflow_tpu.train.compiled_run import make_compiled_run_fn

        return make_compiled_run_fn(
            model, loss_fn, optimizer, batch_sharding=self._batch, **kw
        )


class AsyncDataParallel(Strategy):
    """The ``tfdist_between.py`` mode: HOGWILD-style async DP, emulated as
    local SGD with per-chip parameter copies (see module docstring).

    State pytrees carry a leading replica axis of size ``n`` sharded across
    the ``data`` mesh axis — chip i owns copy i, exactly one worker's view.
    """

    def __init__(
        self,
        mesh: Mesh,
        *,
        avg_every: int = 0,
        update_scale: float | None = None,
    ):
        self.mesh = mesh
        self.n = mesh.shape["data"]
        self.avg_every = avg_every
        # None → scale lr by replica count (async N×-update-count parity).
        self.update_scale = float(self.n if update_scale is None else update_scale)
        self._stacked = NamedSharding(mesh, P("data"))
        self._batch = NamedSharding(mesh, P("data"))
        self._repl = NamedSharding(mesh, P())

    @property
    def num_replicas(self) -> int:
        return self.n

    def init_state(self, model, optimizer, seed: int) -> TrainState:
        # Every reference worker builds the same graph with the same seed
        # (tf.set_random_seed(1) in each process) — so all copies start equal.
        params = model.init(seed)
        opt_state = optimizer.init(params)
        stacked = jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (self.n,) + a.shape),
            (params, opt_state),
        )
        state = TrainState(stacked[0], stacked[1], jnp.zeros((self.n,), jnp.int32))
        return jax.device_put(state, self._stacked)

    def layout_meta(self) -> dict:
        return {"mode": "async", "replicas": int(self.n)}

    def to_canonical(self, state: TrainState) -> TrainState:
        """Merge the per-chip copies at the mean — exactly the parameters
        this strategy evaluates at (effective_params); integer optimizer
        leaves (identical across copies) take replica 0's value outright
        (merge_replica_leaf — the float mean is exact only below 2^24).
        Step: the summed per-chip vector (global_step — total applied
        updates, the PS semantics)."""
        merge = lambda t: jax.tree.map(merge_replica_leaf, t)  # noqa: E731
        return TrainState(
            merge(state.params),
            merge(state.opt_state),
            jnp.asarray(jnp.sum(state.step), jnp.int32),
        )

    def from_canonical(self, canonical: TrainState) -> TrainState:
        """Broadcast the canonical state into n equal copies (how every
        async run starts) and spread the scalar step over the per-chip
        vector so global_step (the sum) is preserved exactly."""
        stacked = jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (self.n,) + a.shape),
            (canonical.params, canonical.opt_state),
        )
        total = jnp.asarray(canonical.step, jnp.int32)
        base = total // self.n
        rem = total - base * self.n
        steps = base + (jnp.arange(self.n, dtype=jnp.int32) < rem)
        return jax.device_put(
            TrainState(stacked[0], stacked[1], steps), self._stacked
        )

    def make_train_step(self, model, loss_fn, optimizer):
        scale = self.update_scale

        def local_step(state: TrainState, x, y):
            # Each chip sees leading-axis slices of size 1: its own copy.
            params = jax.tree.map(lambda a: a[0], state.params)
            opt_state = jax.tree.map(lambda a: a[0], state.opt_state)
            params, opt_state, cost = _local_sgd_update(
                model, loss_fn, optimizer, scale, params, opt_state, x, y
            )
            new = TrainState(
                jax.tree.map(lambda a: a[None], params),
                jax.tree.map(lambda a: a[None], opt_state),
                state.step + 1,
            )
            return new, cost[None]

        mapped = jax.shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(P("data"), P("data"), P("data")),
            out_specs=(P("data"), P("data")),
        )
        return jax.jit(mapped, donate_argnums=0)

    def make_exchange_fn(self, collective: str = "auto"):
        """Periodic parameter exchange: every copy jumps to the mean — the
        staleness-bounding analog of the PS serializing worker applies.

        ``collective="auto"`` lets XLA lower the mean-over-copies (typically
        an all-reduce); ``"ring"`` runs it explicitly as a ppermute ring
        (ops/collectives.py) — N-1 single-hop neighbor exchanges over ICI.
        """
        if collective == "ring":
            from distributed_tensorflow_tpu.ops.collectives import ring_all_mean

            def local_exchange(state: TrainState):
                params = jax.tree.map(
                    lambda a: ring_all_mean(a, "data"), state.params
                )
                return TrainState(params, state.opt_state, state.step)

            mapped = jax.shard_map(
                local_exchange,
                mesh=self.mesh,
                in_specs=(P("data"),),
                out_specs=P("data"),
            )
            return jax.jit(mapped, donate_argnums=0)
        if collective != "auto":
            raise ValueError(f"unknown collective {collective!r}; use 'auto' or 'ring'")

        @partial(jax.jit, donate_argnums=0, out_shardings=self._stacked)
        def exchange(state: TrainState):
            params = jax.tree.map(
                lambda a: jnp.broadcast_to(a.mean(axis=0, keepdims=True), a.shape),
                state.params,
            )
            return TrainState(params, state.opt_state, state.step)

        return exchange

    # Scanned-epoch support: staged arrays are [steps, n*batch, ...] with
    # the batch dim sharded over 'data' (chip i's slice is worker i's batch
    # stream), mirroring the sync layout.
    @property
    def stage_sharding(self):
        return NamedSharding(self.mesh, P(None, "data"))

    def make_scanned_train_fn(self, model, loss_fn, optimizer):
        """One dispatch per epoch for the async emulation: each chip scans
        its own local-SGD stream, and the periodic parameter exchange
        (``avg_every``) becomes a ``pmean`` between inner scan rounds —
        the whole HOGWILD-emulation epoch (local steps + exchanges) is a
        single XLA program. Exchange cadence and semantics match the eager
        path exactly: params jump to the mean every ``avg_every`` local
        steps (including an epoch-final exchange when the count divides),
        optimizer slots stay local, and a non-dividing remainder of steps
        runs after the last exchange.
        """
        scale = self.update_scale
        avg_every = self.avg_every

        def local_epoch(state: TrainState, xs, ys):
            # Local slices: state leading axis 1 (this chip's copy), xs/ys
            # [steps, batch, ...] (this chip's share of each global batch).
            params = jax.tree.map(lambda a: a[0], state.params)
            opt_state = jax.tree.map(lambda a: a[0], state.opt_state)

            def step(carry, xy):
                params, opt_state = carry
                x, y = xy
                params, opt_state, cost = _local_sgd_update(
                    model, loss_fn, optimizer, scale, params, opt_state, x, y
                )
                return (params, opt_state), cost

            carry, costs = _scan_with_exchange(
                step, (params, opt_state), (xs, ys), xs.shape[0], avg_every
            )
            params, opt_state = carry
            steps = xs.shape[0]
            new = TrainState(
                jax.tree.map(lambda a: a[None], params),
                jax.tree.map(lambda a: a[None], opt_state),
                state.step + steps,
            )
            return new, costs[:, None]  # [steps, 1] → global [steps, n]

        mapped = jax.shard_map(
            local_epoch,
            mesh=self.mesh,
            in_specs=(P("data"), P(None, "data"), P(None, "data")),
            out_specs=(P("data"), P(None, "data")),
        )

        @partial(jax.jit, donate_argnums=0)
        def run(state: TrainState, xs, ys):
            state, costs = mapped(state, xs, ys)
            # Mean over replicas per step — what the eager path's
            # cost_scalar logs.
            return state, jnp.mean(costs, axis=1)

        return run

    def make_indexed_scanned_train_fn(self, model, loss_fn, optimizer):
        """Indexed variant of the scanned epoch (see train/scan.py): the full
        train arrays stay device-resident (replicated) and each chip gathers
        its slice of every global batch by row index — ``idxs`` is
        ``[steps, n*b_loc]`` with chip i consuming columns
        ``[i*b_loc, (i+1)*b_loc)``, exactly the eager trainer's batch split.
        Update semantics identical to ``make_scanned_train_fn`` over staged
        batches of the same permutation."""
        scale = self.update_scale
        avg_every = self.avg_every
        n = self.n

        def local_epoch(state: TrainState, train_x, train_y, idxs):
            my = jax.lax.axis_index("data")
            steps = idxs.shape[0]
            b_loc = idxs.shape[1] // n
            params = jax.tree.map(lambda a: a[0], state.params)
            opt_state = jax.tree.map(lambda a: a[0], state.opt_state)
            my_idxs = _to_varying(idxs.reshape(steps, n, b_loc), "data")[:, my]

            def step(carry, idx_row):
                params, opt_state = carry
                x = jnp.take(train_x, idx_row, axis=0)
                y = jnp.take(train_y, idx_row, axis=0)
                params, opt_state, cost = _local_sgd_update(
                    model, loss_fn, optimizer, scale, params, opt_state, x, y
                )
                return (params, opt_state), cost

            carry, costs = _scan_with_exchange(
                step, (params, opt_state), my_idxs, steps, avg_every
            )
            params, opt_state = carry
            new = TrainState(
                jax.tree.map(lambda a: a[None], params),
                jax.tree.map(lambda a: a[None], opt_state),
                state.step + steps,
            )
            return new, costs[:, None]

        mapped = jax.shard_map(
            local_epoch,
            mesh=self.mesh,
            in_specs=(P("data"), P(), P(), P()),
            out_specs=(P("data"), P(None, "data")),
        )

        @partial(jax.jit, donate_argnums=0)
        def run(state: TrainState, train_x, train_y, idxs):
            state, costs = mapped(state, train_x, train_y, idxs)
            return state, jnp.mean(costs, axis=1)

        return run

    def make_divergence_fn(self):
        """Race observability: the largest elementwise distance of any
        parameter copy from the mean of the copies. The reference could only
        *discuss* its async parameter race qualitatively (stale HOGWILD
        applies, reference README.md:70-74); this measures the modeled race
        directly — 0 right after an exchange, growing with local drift, the
        quantitative staleness bound `avg_every` controls.
        """

        @jax.jit
        def divergence(state: TrainState) -> jax.Array:
            def leaf_div(a):
                return jnp.max(jnp.abs(a - a.mean(axis=0, keepdims=True)))

            return jax.tree.reduce(
                jnp.maximum, jax.tree.map(leaf_div, state.params)
            )

        return divergence

    # Whole-run staging (train/compiled_run.py): full dataset replicated.
    @property
    def replicated_sharding(self):
        return self._repl

    def make_compiled_run_fn(
        self,
        model,
        loss_fn,
        optimizer,
        *,
        batch_size: int,
        epochs: int,
        shuffle: bool = True,
        donate: bool = True,
        steps_per_epoch: int | None = None,
    ):
        """The WHOLE async experiment as one dispatch: every epoch of every
        chip's local-SGD stream, the pmean exchanges, the on-device global
        shuffles, and a per-epoch eval on the mean of the copies (what the
        eager path's ``make_eval_fn`` evaluates — "the parameters on the
        PS"). Same contract as train/compiled_run.py's
        ``make_compiled_run_fn``: ``fn(state, train_x, train_y, test_x,
        test_y, key) -> (state, {"costs": [epochs, steps], "accuracy":
        [epochs]})`` with ``batch_size`` the *global* batch; each chip
        consumes its 1/n slice of every global batch, matching the eager
        trainer's batch split."""
        from distributed_tensorflow_tpu.train.compiled_run import (
            wrapped_epoch_perm,
        )

        scale = self.update_scale
        avg_every = self.avg_every
        n = self.n

        def local_run(state: TrainState, train_x, train_y, test_x, test_y, key):
            my = jax.lax.axis_index("data")
            b_loc = batch_size // n
            steps = (
                train_x.shape[0] // batch_size
                if steps_per_epoch is None
                else steps_per_epoch
            )
            need = steps * batch_size
            # Index-stream domain: trimmed for the plain convention (old
            # behavior preserved); the full dataset, wrapping across fresh
            # permutations, under per_worker_epoch (each worker runs
            # num_examples/batch steps — reference tfdist_between.py:87).
            domain = need if steps_per_epoch is None else train_x.shape[0]
            k = (need + domain - 1) // domain if need else 1
            params = jax.tree.map(lambda a: a[0], state.params)
            opt_state = jax.tree.map(lambda a: a[0], state.opt_state)

            def step(carry, idx_row):
                params, opt_state = carry
                x = jnp.take(train_x, idx_row, axis=0)
                y = jnp.take(train_y, idx_row, axis=0)
                params, opt_state, cost = _local_sgd_update(
                    model, loss_fn, optimizer, scale, params, opt_state, x, y
                )
                return (params, opt_state), cost

            def epoch_body(carry, _):
                params, opt_state, key = carry
                key, sub = jax.random.split(key)
                # Same key on every chip → same global permutation; chip i
                # takes slice i of each global batch (the eager split).
                perm = wrapped_epoch_perm(
                    sub, domain=domain, need=need, k=k, shuffle=shuffle
                )
                idxs = _to_varying(
                    perm.reshape(steps, n, b_loc), "data"
                )[:, my]
                (params, opt_state), costs = _scan_with_exchange(
                    step, (params, opt_state), idxs, steps, avg_every
                )
                eff = jax.tree.map(
                    lambda a: jax.lax.pmean(a, "data"), params
                )
                acc = losses_lib.accuracy(model.apply(eff, test_x), test_y)
                return (params, opt_state, key), (costs, acc)

            (params, opt_state, _), (costs, accs) = jax.lax.scan(
                epoch_body, (params, opt_state, key), None, length=epochs
            )
            new = TrainState(
                jax.tree.map(lambda a: a[None], params),
                jax.tree.map(lambda a: a[None], opt_state),
                state.step + epochs * steps,
            )
            # costs [epochs, steps] per chip → global [epochs, steps, n];
            # accuracy is invariant (computed from the pmean'd params).
            return new, costs[..., None], accs

        mapped = jax.shard_map(
            local_run,
            mesh=self.mesh,
            in_specs=(P("data"), P(), P(), P(), P(), P()),
            out_specs=(P("data"), P(None, None, "data"), P()),
        )

        @partial(jax.jit, donate_argnums=0 if donate else ())
        def run(state: TrainState, train_x, train_y, test_x, test_y, key):
            state, costs, accs = mapped(
                state, train_x, train_y, test_x, test_y, key
            )
            return state, {"costs": jnp.mean(costs, axis=-1), "accuracy": accs}

        return run

    def effective_params(self, state: TrainState):
        return jax.tree.map(lambda a: a.mean(axis=0), state.params)

    def make_eval_fn(self, model):
        """Evaluates the mean of the per-chip copies — the closest analog of
        'the parameters on the PS' that every reference worker evaluated."""

        @partial(jax.jit, in_shardings=(self._stacked, self._repl, self._repl))
        def evaluate(state: TrainState, x, y):
            return losses_lib.accuracy(model.apply(self.effective_params(state), x), y)

        return evaluate

    def prepare_batch(self, x, y):
        return (
            jax.device_put(jnp.asarray(x), self._batch),
            jax.device_put(jnp.asarray(y), self._batch),
        )
