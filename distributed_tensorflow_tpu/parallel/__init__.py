from distributed_tensorflow_tpu.parallel.fsdp import (  # noqa: F401
    ShardedDataParallel,
    fsdp_specs,
)
from distributed_tensorflow_tpu.parallel.mesh import make_mesh  # noqa: F401
from distributed_tensorflow_tpu.parallel.specs import (  # noqa: F401
    as_shardings,
    pinned_update,
    slot_specs,
)
from distributed_tensorflow_tpu.parallel.strategy import (  # noqa: F401
    AsyncDataParallel,
    SingleDevice,
    Strategy,
    SyncDataParallel,
)
