"""Placement/parallelism layer: mesh, strategies, specs, FSDP, pipeline.

Lazy exports (PEP 562, same pattern as the package root and ``train/``):
``mesh.py`` needs a mesh-capable jax (``jax.sharding.AxisType``), but much
of the package — ``TrainState``, spec utilities, the serving stack that
imports ``models/gpt.py`` (whose module level pulls ``parallel.specs``) —
does not. Deferring the submodule imports keeps those surfaces importable
in a degraded container or a lean supervisor process; only touching
``make_mesh``/a Strategy pulls the mesh-backed half in.
"""

_LAZY_EXPORTS = {
    "ShardedDataParallel": (
        "distributed_tensorflow_tpu.parallel.fsdp",
        "ShardedDataParallel",
    ),
    "fsdp_specs": ("distributed_tensorflow_tpu.parallel.fsdp", "fsdp_specs"),
    "make_mesh": ("distributed_tensorflow_tpu.parallel.mesh", "make_mesh"),
    "as_shardings": (
        "distributed_tensorflow_tpu.parallel.specs",
        "as_shardings",
    ),
    "pinned_update": (
        "distributed_tensorflow_tpu.parallel.specs",
        "pinned_update",
    ),
    "slot_specs": ("distributed_tensorflow_tpu.parallel.specs", "slot_specs"),
    "AsyncDataParallel": (
        "distributed_tensorflow_tpu.parallel.strategy",
        "AsyncDataParallel",
    ),
    "SingleDevice": (
        "distributed_tensorflow_tpu.parallel.strategy",
        "SingleDevice",
    ),
    "Strategy": ("distributed_tensorflow_tpu.parallel.strategy", "Strategy"),
    "SyncDataParallel": (
        "distributed_tensorflow_tpu.parallel.strategy",
        "SyncDataParallel",
    ),
}

__all__ = list(_LAZY_EXPORTS)


def __getattr__(name):
    try:
        module, attr = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(module), attr)
    globals()[name] = value  # cache: next access skips __getattr__
    return value
