"""ZeRO/FSDP-sharded data parallelism.

The reference's only answer to "parameters don't fit one worker" is the PS
itself: ``replica_device_setter`` round-robins *variables* across ps tasks
(reference tfdist_between.py:32-35), so each PS holds a slice of the model and
every worker holds a full copy transiently per step. This module is the
TPU-native generalization of that idea, done the modern way (ZeRO-3/FSDP):

- parameters AND optimizer state are sharded across the ``data`` axis — each
  chip *owns* a 1/N slice (the PS round-robin, flattened onto the chips);
- the forward/backward all-gathers parameters just-in-time (the worker's
  transient full copy, now an ICI collective XLA schedules and overlaps);
- gradients are reduce-scattered so each chip updates only the slice it owns
  (the PS apply, now a collective).

All of it is expressed as GSPMD sharding annotations on one ordinary train
step — no wrapper modules, no hooks, no manual gather/scatter code. XLA
inserts and fuses the collectives.

Composes with tensor parallelism: pass ``base`` specs (e.g.
``MLP.partition_specs()``) and each parameter's remaining unsharded dims are
ZeRO-sharded over ``data`` on top of the TP layout.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.ops import losses as losses_lib
from distributed_tensorflow_tpu.parallel.strategy import (
    Strategy,
    TrainState,
    _loss_from_model,
)


def fsdp_specs(
    params: Any,
    mesh: Mesh,
    *,
    axis: str = "data",
    base: Any = None,
) -> Any:
    """Per-parameter ``PartitionSpec``s sharding each tensor's largest
    divisible dim over ``axis``.

    Dims already taken by ``base`` (a pytree of specs, e.g. a TP layout) are
    preserved; the largest remaining dim divisible by the axis size gets
    ``axis``; tensors with no divisible free dim stay as ``base`` says
    (replicated over ``axis``) — small biases aren't worth a gather.
    """
    n = mesh.shape[axis]

    def spec_for(leaf, base_spec):
        entries = list(base_spec) if base_spec is not None else []
        entries += [None] * (leaf.ndim - len(entries))
        best = None
        for d in range(leaf.ndim):
            if entries[d] is None and leaf.shape[d] % n == 0 and leaf.shape[d] >= n:
                if best is None or leaf.shape[d] > leaf.shape[best]:
                    best = d
        if best is not None:
            entries[best] = axis
        while entries and entries[-1] is None:
            entries.pop()
        return P(*entries)

    if base is None:
        return jax.tree.map(lambda leaf: spec_for(leaf, None), params)
    return jax.tree.map(spec_for, params, base)


class ShardedDataParallel(Strategy):
    """Sync DP with ZeRO-3 parameter/optimizer-state sharding (see module
    docstring). Update semantics are identical to :class:`SyncDataParallel` —
    same batches produce the same parameters — only the memory layout and
    collective pattern differ (all-gather fwd/bwd + reduce-scatter grads
    instead of replicated params + all-reduce)."""

    def __init__(self, mesh: Mesh, *, axis: str = "data", param_specs=None):
        """``param_specs``: optional TP base layout (e.g.
        ``MLP.partition_specs()``) that ZeRO sharding is layered onto."""
        self.mesh = mesh
        self.axis = axis
        self._base = param_specs
        self._repl = NamedSharding(mesh, P())
        self._batch = NamedSharding(mesh, P(axis))
        self._specs = None  # resolved against params in init_state

    @property
    def num_replicas(self) -> int:
        return self.mesh.shape[self.axis]

    def _shardings(self, params):
        if self._specs is None:
            self._specs = fsdp_specs(
                params, self.mesh, axis=self.axis, base=self._base
            )
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s), self._specs)

    def _state_shardings(self, model, optimizer) -> TrainState:
        """Shardings for the full TrainState: params per ``fsdp_specs``, each
        optimizer slot sharded like the parameter it tracks (ZeRO-1), scalars
        replicated. Slots are matched to their param by tree-path suffix —
        optax slot subtrees (momentum/adam moments) mirror the param pytree,
        so a slot leaf's path ends with its param's path; shape-only matching
        would mislayout same-shaped params with different specs."""
        from jax.tree_util import tree_flatten_with_path

        params_shape = jax.eval_shape(model.init, 0)
        shardings = self._shardings(params_shape)
        param_items = [
            (tuple(path), leaf.shape, sh)
            for (path, leaf), sh in zip(
                tree_flatten_with_path(params_shape)[0], jax.tree.leaves(shardings)
            )
        ]

        def slot_sharding(path, leaf):
            for ppath, pshape, sh in param_items:
                if leaf.shape == pshape and tuple(path[-len(ppath):]) == ppath:
                    return sh
            return self._repl

        opt_shape = jax.eval_shape(optimizer.init, params_shape)
        leaves, treedef = tree_flatten_with_path(opt_shape)
        opt_shardings = jax.tree.unflatten(
            treedef, [slot_sharding(path, leaf) for path, leaf in leaves]
        )
        return TrainState(shardings, opt_shardings, self._repl)

    def init_state(self, model, optimizer, seed: int) -> TrainState:
        out = self._state_shardings(model, optimizer)

        @partial(jax.jit, out_shardings=out)
        def _init():
            params = model.init(seed)
            return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))

        return _init()

    def make_train_step(self, model, loss_fn, optimizer):
        shardings = self._shardings(jax.eval_shape(model.init, 0))
        state_out = self._state_shardings(model, optimizer)

        @partial(jax.jit, donate_argnums=0, out_shardings=(state_out, None))
        def step(state: TrainState, x, y):
            x = jax.lax.with_sharding_constraint(x, self._batch)
            y = jax.lax.with_sharding_constraint(y, self._batch)
            cost, grads = jax.value_and_grad(
                partial(_loss_from_model, model, loss_fn)
            )(state.params, x, y)
            # Pin gradients to the owner layout: the batch-sum over 'data'
            # becomes a reduce-scatter, and the update math below is local to
            # each chip's slice.
            grads = jax.lax.with_sharding_constraint(grads, shardings)
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            params = jax.lax.with_sharding_constraint(params, shardings)
            return TrainState(params, opt_state, state.step + 1), cost

        return step

    def make_eval_fn(self, model):
        @jax.jit
        def evaluate(state: TrainState, x, y):
            return losses_lib.accuracy(model.apply(state.params, x), y)

        return evaluate

    def prepare_batch(self, x, y):
        return (
            jax.device_put(jnp.asarray(x), self._batch),
            jax.device_put(jnp.asarray(y), self._batch),
        )

    # Scanned-epoch support: batch dim of each scan slice sharded over 'data'.
    @property
    def stage_sharding(self):
        return NamedSharding(self.mesh, P(None, self.axis))

    # Whole-dataset staging for the indexed scan (train/scan.py): per-step
    # batches are random gathers, so the flat arrays live replicated.
    @property
    def replicated_sharding(self):
        return self._repl

    def make_scanned_train_fn(self, model, loss_fn, optimizer):
        from distributed_tensorflow_tpu.train.scan import make_scanned_train_fn

        return make_scanned_train_fn(
            model, loss_fn, optimizer, batch_sharding=self._batch
        )

    def make_indexed_scanned_train_fn(self, model, loss_fn, optimizer):
        """Indexed scanned epoch (train/scan.py): train arrays device-
        resident, per-epoch index upload only. The ZeRO layout rides the
        carried state's shardings — GSPMD keeps params/opt-state sharded and
        inserts the same all-gather/reduce-scatter pattern as the per-step
        path."""
        from distributed_tensorflow_tpu.train.scan import (
            make_indexed_scanned_train_fn,
        )

        return make_indexed_scanned_train_fn(
            model, loss_fn, optimizer, batch_sharding=self._batch
        )
