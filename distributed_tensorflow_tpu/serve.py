"""Batched LM serving: compiled prefill+decode with continuous batching.

The reference's only "inference" was the in-loop eval fetch
(reference tfsingle.py:94); the classifier side of this framework got
``inference.py::Predictor`` (fixed-shape compiled prediction). This module
is the LM analog — text in, text out, from a checkpoint directory — built
from the pieces rounds 5-8 left on the table: the cross-topology canonical
restore (``step_N.layout.json`` sidecars), the ``tokenizer.json`` the
LMTrainer ships into ``checkpoint_dir``, and the unrolled-layer KV-cache
decode step. Three serving-engine ideas, adapted to one tunneled TPU
(~20-40 ms/dispatch, ~100 ms per host round-trip — CLAUDE.md):

- **Bucketed prefill** (vLLM-style fixed shapes): prompts are padded to a
  small set of length buckets and prefilled BATCHED across the server's
  fixed request slots with ragged ``kv_lens`` masking
  (``GPTLM.prefill_slots``), so the compile count is ``len(buckets)``, not
  one per prompt length.
- **Multi-token decode chunks**: ``chunk`` decode steps — including the
  sampling — run as ONE ``lax.scan`` dispatch (``GPTLM.decode_slots`` per
  step, in-graph greedy/temperature/nucleus picks, per-slot EOS/budget
  tracking), so the ~100 ms tunnel round-trip is paid once per ``chunk``
  tokens instead of once per token. This is the environment-specific lever:
  on-chip the scan also removes per-step dispatch latency, through the
  tunnel it removes a 100 ms round-trip per token.
- **Continuous batching** (Orca-style): a slot scheduler admits queued
  requests into freed slots at chunk boundaries — each slot is an
  independent request at its own position (``SlotKVCache`` carries per-slot
  lengths), so throughput never drains to the longest request in a static
  batch.

Parity contract (pinned in tests/test_serve.py): for every request, the
served token stream equals the in-process single-prompt
``GPTLM.greedy_decode`` / ``sample_decode(key=jax.random.key(seed))``
stream token for token — generation is batch-invariant, so a request's
output does not depend on what shared the batch with it.
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from collections import deque
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.gpt import GPTLM, GPTLMParams
from distributed_tensorflow_tpu.observability import journal as obs_journal
from distributed_tensorflow_tpu.observability import tracing
from distributed_tensorflow_tpu.observability.exporter import MetricsExporter
from distributed_tensorflow_tpu.observability.metrics import MetricsRegistry
from distributed_tensorflow_tpu.observability.spans import SpanRecorder
from distributed_tensorflow_tpu import serve_pool
from distributed_tensorflow_tpu.serve_pool import (
    BlockAllocator,
    PrefixCache,
    QueueFull,
    RequestCancelled,
    RequestShed,
    blocks_for,
    lookup_draft,
)

__all__ = [  # noqa: F822 — QueueFull/RequestCancelled/RequestShed re-exported
    "GenerationConfig", "QueueFull", "RequestCancelled", "RequestShed",
    "TextServer", "canonical_lm_params", "load_tokenizer",
]


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Per-request decoding knobs. ``greedy=True`` (default) reproduces
    ``GPTLM.greedy_decode``; ``greedy=False`` reproduces
    ``sample_decode(key=jax.random.key(seed), temperature=, top_p=)``
    (nucleus sampling; ``top_p=1.0`` keeps the whole distribution).
    ``eos_id`` stops a request early once emitted (the EOS token itself is
    included in the output); None generates exactly ``max_new`` tokens."""

    max_new: int = 64
    greedy: bool = True
    temperature: float = 1.0
    top_p: float = 1.0
    seed: int = 0
    eos_id: int | None = None

    def validate(self, vocab_size: int) -> None:
        if self.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {self.max_new}")
        if self.temperature <= 0:
            raise ValueError(
                f"temperature must be > 0, got {self.temperature}"
            )
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.eos_id is not None and not 0 <= self.eos_id < vocab_size:
            raise ValueError(
                f"eos_id must be in [0, {vocab_size}), got {self.eos_id}"
            )


# -- checkpoint loading (the round-5 canonical layer, params-only) ---------


def canonical_lm_params(
    model: GPTLM, checkpoint_dir: str, *, optimizer=None
) -> tuple[GPTLMParams, int]:
    """Restore the newest valid checkpoint under ``checkpoint_dir`` written
    by :class:`~train.lm_trainer.LMTrainer` in ANY mode layout, and return
    ``(dense canonical params, step)`` — the serving-side half of the
    round-5 cross-topology contract: the ``step_N.layout.json`` sidecar
    names the source layout, pipeline checkpoints unstage their
    [S, L/S, ...] block stacks back to [L, ...], async checkpoints merge
    their per-replica copies at the mean (integer leaves take replica 0 —
    ``merge_replica_leaf``), and the dense family restores as-is.

    ``optimizer`` must match the training optimizer (the checkpoint stores
    its slots; orbax fails loudly on a structure mismatch); defaults to
    the reference SGD whose slot state is empty."""
    from distributed_tensorflow_tpu.ops import optim as optim_lib
    from distributed_tensorflow_tpu.parallel.strategy import TrainState
    from distributed_tensorflow_tpu.train import supervisor as _sup

    probe = _sup.latest_checkpoint_step(checkpoint_dir)
    if probe is None:
        raise FileNotFoundError(f"no checkpoint under {checkpoint_dir}")
    if not _sup._HAVE_ORBAX:
        raise RuntimeError(
            f"checkpoint found under {checkpoint_dir} but orbax is not"
            " importable; cannot restore"
        )
    sup = _sup.Supervisor(checkpoint_dir=checkpoint_dir)
    step = sup.newest_restorable_step()
    if step is None:
        raise RuntimeError(
            f"no restorable checkpoint under {checkpoint_dir} (all steps "
            "fail manifest verification)"
        )
    optimizer = optimizer or optim_lib.sgd(0.001)
    meta = sup.saved_layout(step) or {}
    mode = meta.get("mode", "single")

    params = jax.eval_shape(lambda: model.init(seed=0))
    if mode == "pp":
        from distributed_tensorflow_tpu.models.gpt import (
            pipeline_stage_params,
        )

        params = jax.eval_shape(
            lambda p: pipeline_stage_params(model, p, meta["stages"]), params
        )
    opt = jax.eval_shape(optimizer.init, params)
    step_leaf = jax.ShapeDtypeStruct((), jnp.int32)
    if mode == "async":
        n = int(meta["replicas"])
        stack = lambda t: jax.tree.map(  # noqa: E731
            lambda s: jax.ShapeDtypeStruct((n,) + s.shape, s.dtype), t
        )
        abstract = TrainState(stack(params), stack(opt), step_leaf)
    else:
        abstract = TrainState(params, opt, step_leaf)
    # eval_shape structs carry sharding=None, which some orbax vintages
    # cannot normalize — pin every leaf to the default device explicitly.
    dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    abstract = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=dev),
        abstract,
    )
    state = sup.restore_raw(step, abstract)

    if mode == "async":
        from distributed_tensorflow_tpu.parallel.strategy import (
            merge_replica_leaf,
        )

        served = jax.tree.map(merge_replica_leaf, state.params)
    elif mode == "pp":
        served = state.params._replace(
            blocks=jax.tree.map(
                lambda a: a.reshape((-1,) + a.shape[2:]), state.params.blocks
            )
        )
    else:
        served = state.params
    return served, step


def load_tokenizer(checkpoint_dir: str):
    """The vocab that produced the checkpoint's token ids:
    ``tokenizer.json`` (the record LMTrainer ships) when present, else the
    byte-level identity tokenizer (trainings that never passed one)."""
    from distributed_tensorflow_tpu.data.text import (
        BPETokenizer,
        ByteTokenizer,
    )

    path = os.path.join(checkpoint_dir, "tokenizer.json")
    if os.path.exists(path):
        return BPETokenizer.load(path)
    return ByteTokenizer()


# -- the engine ------------------------------------------------------------


class _DecodeState(NamedTuple):
    """Device-resident per-slot serving state, one pytree so every
    prefill/chunk dispatch carries it whole. PRNG keys ride as raw
    ``key_data`` (uint32) — jnp.where composes on those."""

    k: jax.Array  # [layers, S, C, Hkv, Dh]
    v: jax.Array
    lengths: jax.Array  # [S] i32 — tokens written into each slot's cache
    last_tok: jax.Array  # [S] i32 — most recent token (next decode input)
    key: jax.Array  # [S, ...] u32 — per-slot PRNG key data
    emitted: jax.Array  # [S] i32 — generated tokens so far
    budget: jax.Array  # [S] i32 — max_new for the resident request
    finished: jax.Array  # [S] bool — True: slot idle (done or vacant)
    greedy: jax.Array  # [S] bool
    temp: jax.Array  # [S] f32
    top_p: jax.Array  # [S] f32
    eos: jax.Array  # [S] i32 — -1: no EOS stop
    # Quantized-cache scale side tensors (round 15; None on the bf16
    # default — the pytree simply has no leaves there).
    k_scale: jax.Array | None = None  # [layers, S, C, Hkv] f32
    v_scale: jax.Array | None = None


class _PagedState(NamedTuple):
    """:class:`_DecodeState` for the paged engine: the slab rows become
    the shared block pool plus per-slot block tables (same scheduler
    fields otherwise, so the host loop is mode-agnostic)."""

    k: jax.Array  # [layers, num_blocks, block_size, Hkv, Dh]
    v: jax.Array
    block_tables: jax.Array  # [S, max_blocks] i32
    lengths: jax.Array  # [S] i32 — tokens written into each slot's cache
    last_tok: jax.Array  # [S] i32 — most recent token (next decode input)
    key: jax.Array  # [S, ...] u32 — per-slot PRNG key data
    emitted: jax.Array  # [S] i32 — generated tokens so far
    budget: jax.Array  # [S] i32 — max_new for the resident request
    finished: jax.Array  # [S] bool — True: slot idle (done or vacant)
    greedy: jax.Array  # [S] bool
    temp: jax.Array  # [S] f32
    top_p: jax.Array  # [S] f32
    eos: jax.Array  # [S] i32 — -1: no EOS stop
    k_scale: jax.Array | None = None  # [layers, NB, bs, Hkv] f32
    v_scale: jax.Array | None = None


class _Request:
    __slots__ = (
        "rid", "tokens", "config", "out", "done", "trace", "cancelled",
        "shed", "priority", "deadline", "t_submit", "t_admit", "t_first",
        "prefill_only", "resume", "export", "migrated",
    )

    def __init__(
        self, rid, tokens, config, *, trace=None, deadline_s=None, priority=0
    ):
        self.rid = rid
        self.tokens = tokens
        self.config = config
        self.out: list[int] = []
        self.done = False
        self.cancelled = False
        # Disaggregated-fleet handoff state (round 23, docs/serving.md
        # §disaggregation): prefill_only requests stop after the
        # prefill's first token and EXPORT their paged KV + sampling
        # state (``export`` holds the payload until take_export);
        # ``resume`` carries an imported payload — admission skips
        # prefill and continues the chunk scan from it.
        self.prefill_only = False
        self.resume = None
        self.export = None
        self.migrated = False
        # Shed (round 21): dropped by the scheduler WITHOUT spending a
        # dispatch — terminal like cancelled, but typed RequestShed.
        self.shed = False
        self.priority = priority  # int >= 0; higher = more important
        # Trace id (round 12, observability/tracing.py): joins every
        # journal event of this request's life — request_submit →
        # admission → prefill/decode spans (by rid) → completion — so
        # obs_report --requests rebuilds the per-request timeline from
        # the journal alone. A caller-supplied trace (the fleet router)
        # wins, so one logical request keeps ONE id across replicas.
        self.trace = trace if trace else tracing.new_trace_id()
        self.t_submit = time.perf_counter()
        # Absolute deadline on the submit clock; None = no deadline. An
        # overdue request is cancelled at the next chunk boundary.
        self.deadline = (
            None if deadline_s is None else self.t_submit + float(deadline_s)
        )
        self.t_admit = None  # set at slot admission
        self.t_first = None  # set when the first token lands (TTFT)


class TextServer:
    """Continuous-batching text server over a fixed bank of request slots.

    Construct from live params or :meth:`from_checkpoint`; submit requests
    (:meth:`submit` / :meth:`generate` / :meth:`serve_text`) and drive the
    engine with :meth:`step` (one admission round + one compiled
    ``chunk``-token decode dispatch) until :meth:`idle`.

    Compiled shapes: one prefill executable per length bucket (shared
    jitted function, shape-keyed) and ONE decode-chunk executable serving
    every occupancy pattern — finished/vacant slots ride along masked, so
    admission order and slot churn never recompile anything."""

    def __init__(
        self,
        model: GPTLM,
        params: GPTLMParams,
        tokenizer=None,
        *,
        slots: int = 8,
        buckets: tuple[int, ...] | None = None,
        chunk: int = 32,
        paged: bool = False,
        block_size: int = 16,
        kv_blocks: int | None = None,
        kv_hbm_bytes: int | None = None,
        kv_dtype: str = "bf16",
        decode_matmul_dtype: str | None = None,
        decode_engine: str | None = None,
        prefix_caching: bool = True,
        spec_draft: int = 0,
        spec_ngram: int = 2,
        queue_limit: int | None = None,
        journal=None,
        metrics: MetricsRegistry | None = None,
        metrics_port: int | None = None,
    ):
        from distributed_tensorflow_tpu.ops.quantized import (
            KV_DTYPES,
            MATMUL_DTYPES,
            kv_elem_bytes,
        )

        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        if kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"unknown kv_dtype {kv_dtype!r}; one of {KV_DTYPES}"
            )
        if decode_matmul_dtype is not None and (
            decode_matmul_dtype not in MATMUL_DTYPES
        ):
            raise ValueError(
                f"unknown decode_matmul_dtype {decode_matmul_dtype!r}; "
                f"None or one of {MATMUL_DTYPES}"
            )
        if kv_hbm_bytes is not None and not paged:
            raise ValueError(
                "kv_hbm_bytes sizes the paged block pool; pass paged=True"
            )
        if kv_hbm_bytes is not None and kv_blocks is not None:
            raise ValueError(
                "pass kv_blocks or kv_hbm_bytes, not both (kv_hbm_bytes "
                "derives kv_blocks from the element size)"
            )
        if spec_draft < 0:
            raise ValueError(f"spec_draft must be >= 0, got {spec_draft}")
        if spec_ngram < 1:
            raise ValueError(f"spec_ngram must be >= 1, got {spec_ngram}")
        if spec_draft and not paged:
            raise ValueError(
                "speculative decoding requires the paged cache "
                "(paged=True): the verify pass extends through block "
                "tables"
            )
        if queue_limit is not None and queue_limit < 1:
            raise ValueError(
                f"queue_limit must be >= 1 (or None), got {queue_limit}"
            )
        self.model = model
        # Bounded admission queue (round 16): submit() raises QueueFull
        # past this depth instead of growing without bound; /healthz
        # surfaces the saturation so a router can route around a
        # backed-up replica. None = unbounded (the round-9 behavior).
        self.queue_limit = queue_limit
        # Drain / live-weight-swap state (round 16, docs/serving.md
        # §fleet): draining closes admission permanently (residents
        # finish); a pending swap pauses admission until the last
        # old-weight resident completes, then the whole param tree is
        # replaced between dispatches — params are runtime args of every
        # compiled graph, so a swap recompiles NOTHING.
        self._draining = False
        self._pending_swap: tuple | None = None
        # Provenance of the served weights (set by from_checkpoint; swap
        # staleness checks compare against checkpoint_step).
        self.checkpoint_dir: str | None = None
        self.checkpoint_step: int | None = None
        self._restore_optimizer = None
        # Weight-only quantized decode projections (round 15): quantize
        # ONCE at construction (the restore-time artifact
        # GPTLM.decode_weights documents) and serve the quantized tree
        # through EVERY compiled graph — prefill, chunk decode, and the
        # speculative verify all see one consistent set of weights, so
        # served streams are exactly the greedy/sampled streams of the
        # weight-quantized model (the parity tests pin this: weight-only
        # quantization does not relax batch-invariance, only the values).
        self.decode_matmul_dtype = decode_matmul_dtype
        if decode_matmul_dtype is not None and params is not None:
            params = model.decode_weights(params, decode_matmul_dtype)
        self.params = params
        # Decode-engine knob (rounds 18+20, docs/serving.md
        # §decode-kernel): None defers to the model's own
        # ``decode_engine``; "pallas" runs the k-token chunk scan's
        # step as ONE megakernel launch per token AND — with
        # spec_draft — the verify extend as the fused small-L kernel
        # (ops/pallas_decode.py verify_tokens_paged, threaded through
        # GPTLM.verify_paged); "pallas-layer" is the round-18
        # per-layer kernel (verify falls back to XLA there). The
        # EFFECTIVE engine (explicit knob OR the model's) is resolved
        # ONCE here so an unsupported pairing (e.g.
        # decode_matmul_dtype's QuantizedLinear tree + a pallas model
        # knob) refuses at construction, not first dispatch. Prefill
        # and the non-spec extend stay on XLA — they are batched-L
        # graphs the flash/dense attention already serves; the
        # kernels' domain is the L=1 chunk scan plus the
        # L ≤ spec_draft+1 verify.
        self.decode_engine = decode_engine
        if params is not None:
            model._resolve_decode_engine(decode_engine, params)
        self.tokenizer = tokenizer
        self.slots = slots
        self.chunk = chunk
        self.kv_dtype = kv_dtype
        # Element-size-aware cache accounting (serve_pool helpers): what
        # one position / one block actually costs, scale side tensors
        # included — the quantized pool's capacity gain is exactly this
        # quotient, and obs_report renders it so a quantized pool reads
        # as "smaller bytes", not "bigger chip".
        self.kv_position_bytes = serve_pool.kv_position_bytes(
            model.num_layers,
            model.num_kv_heads,
            model.head_dim,
            kv_elem_bytes(kv_dtype, model.compute_dtype),
            scale_bytes=0 if kv_dtype == "bf16" else 4,
        )
        # Paged mode (round 11): KV lives in a shared pool of
        # `kv_blocks` blocks of `block_size` positions; slots map
        # logical positions through block tables, admission is gated on
        # FREE BLOCKS (a request reserves ceil((prompt+max_new)/bs)
        # blocks, minus prefix-cache hits), and an oversized request
        # queues without blocking shorter ones behind it. Default pool
        # = slots × ceil(max_len/bs) — the slab footprint for full-
        # context models, so paged=True alone changes layout, not
        # capacity; density comes from shrinking kv_blocks below that
        # (or raising slots above it) for short-request mixes. CAVEAT:
        # windowed models keep FULL history in the paged layout
        # (absolute-position addressing; the slab's rolling buffer is
        # only min(window, max_len) rows), so for window << max_len the
        # default pool is ~max_len/window times the slab's KV HBM —
        # size kv_blocks explicitly there.
        self.paged = paged
        self.block_size = int(block_size)
        self.spec_draft = int(spec_draft)
        self.spec_ngram = int(spec_ngram)
        self._alloc: BlockAllocator | None = None
        self._prefix: PrefixCache | None = None
        self.kv_block_bytes = self.kv_position_bytes * self.block_size
        if paged:
            nb_slot = model.paged_blocks_per_slot(self.block_size)
            if kv_hbm_bytes is not None:
                # Byte-budget sizing (round 15): blocks-per-budget from
                # the ELEMENT SIZE, so an int8/fp8 pool under the same
                # budget holds ~2×/~2× the blocks — admission capacity
                # actually grows instead of the dtype silently changing
                # only the array layout.
                self.kv_blocks = serve_pool.blocks_for_hbm_bytes(
                    kv_hbm_bytes,
                    self.block_size,
                    num_layers=model.num_layers,
                    kv_heads=model.num_kv_heads,
                    head_dim=model.head_dim,
                    elem_bytes=kv_elem_bytes(kv_dtype, model.compute_dtype),
                    scale_bytes=0 if kv_dtype == "bf16" else 4,
                )
            else:
                self.kv_blocks = (
                    int(kv_blocks)
                    if kv_blocks is not None
                    else slots * nb_slot
                )
            if self.kv_blocks < 1:
                raise ValueError(
                    f"kv_blocks must be >= 1, got {self.kv_blocks}"
                )
            self._alloc = BlockAllocator(self.kv_blocks)
            # self._prefix (initialized above) is constructed after the
            # journal resolves, so the radix can journal its evictions.
            # Host-authoritative block tables (the device copy is an
            # input of every prefill dispatch) + per-slot held blocks
            # for release at completion.
            self._host_tables = np.zeros((slots, nb_slot), np.int32)
            self._slot_blocks: list[list[int] | None] = [None] * slots
        else:
            self.kv_blocks = 0
        # Serving telemetry (round 10, observability/): admissions and
        # completions as journal events (rid, TTFT, latency, tokens),
        # queue/occupancy gauges + latency histograms in the registry,
        # and every prefill/chunk dispatch as a host span closed by the
        # scheduler's own D2H token fetch. Defaults are no-ops.
        self.journal = journal if journal is not None else obs_journal.get_journal()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.spans = SpanRecorder(journal=self.journal)
        if paged and prefix_caching:
            # Constructed here (not in the paged block above) so the
            # radix can journal its eviction-under-pressure events.
            self._prefix = PrefixCache(
                self._alloc, self.block_size, journal=self.journal
            )
        # Cache-geometry record (round 15): dtype + honest byte
        # accounting as ONE journal event at construction, so
        # obs_report's serving-cache section can say "int8 pool,
        # N bytes/slot" — without it a quantized pool's higher
        # occupancy is indistinguishable from a bigger chip.
        self.kv_slot_bytes = (
            self.model.paged_blocks_per_slot(self.block_size)
            * self.kv_block_bytes
            if paged
            else self.model.cache_len * self.kv_position_bytes
        )
        self.journal.emit(
            "serving_cache_config",
            kv_dtype=self.kv_dtype,
            decode_matmul_dtype=self.decode_matmul_dtype,
            decode_engine=self.decode_engine,
            paged=bool(paged),
            block_size=int(self.block_size) if paged else None,
            kv_blocks=int(self.kv_blocks) if paged else None,
            position_bytes=int(self.kv_position_bytes),
            block_bytes=int(self.kv_block_bytes) if paged else None,
            pool_bytes=int(
                self.kv_blocks * self.kv_block_bytes
                if paged
                else self.slots * self.kv_slot_bytes
            ),
            slot_bytes=int(self.kv_slot_bytes),
        )
        if buckets is None:
            # Doubling buckets up to max_len-1 (a prompt always leaves at
            # least one position of generation room): 16, 32, ... — small
            # enough a handful of executables covers everything.
            buckets, b = [], 16
            while b < model.max_len:
                buckets.append(min(b, model.max_len - 1))
                b *= 2
            if not buckets or buckets[-1] != model.max_len - 1:
                buckets.append(model.max_len - 1)
        buckets = tuple(sorted(set(int(b) for b in buckets)))
        if buckets[0] < 1 or buckets[-1] > model.max_len:
            raise ValueError(
                f"buckets must lie in [1, max_len={model.max_len}]: {buckets}"
            )
        self.buckets = buckets
        self._queue: deque[_Request] = deque()
        self._slot_req: list[_Request | None] = [None] * slots
        self._next_rid = 0
        self._results: dict[int, _Request] = {}
        # Measured per-token decode seconds (EWMA over chunk dispatches,
        # round 21): the "provably cannot finish" shed predicate's only
        # evidence. None until the first measured chunk — the scheduler
        # never sheds on a guess, only on expiry, before then.
        self._tok_ewma: float | None = None
        # The first decode dispatch carries the chunk-scan COMPILE —
        # seconds/token of one-time cost. Feeding it to the EWMA made a
        # freshly-warmed replica shed its first deadline-bearing traffic
        # as "hopeless" within microseconds (the round-21 chaos schedule
        # caught this live); that measurement is discarded instead.
        self._tok_first_dispatch = True
        self._state = self._init_state()
        self._prefill_jit = jax.jit(
            self._paged_prefill_graph if paged else self._prefill_graph
        )
        self._chunk_jit = jax.jit(self._chunk_graph)
        self._verify_jit = jax.jit(self._verify_graph) if spec_draft else None
        if paged:
            self.metrics.gauge("kv_blocks_total").set(self.kv_blocks)
            self.metrics.gauge("kv_blocks_used").set(0)
            # Byte-honest pool size (round 15): block count × what a
            # block actually costs at this kv_dtype, scales included.
            self.metrics.gauge("kv_pool_bytes").set(
                self.kv_blocks * self.kv_block_bytes
            )
        # Live scrape surface (round 12, observability/exporter.py):
        # /metrics = the registry's Prometheus text, /healthz = engine
        # heartbeat (seconds since the last step() tick) + occupancy.
        # Opt-in: None/0 leaves nothing listening; port 0 is reserved
        # for "off" so production wiring stays explicit — pass a real
        # port (tests bind an ephemeral one via MetricsExporter
        # directly). Started LAST: a constructor failure above must not
        # leave a bound port + daemon thread with no handle to stop.
        self._last_tick = time.time()
        self.exporter: MetricsExporter | None = None
        if metrics_port:
            self.exporter = MetricsExporter(
                self.metrics, port=int(metrics_port), health_fn=self.health
            )
            self.exporter.start()

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_checkpoint(
        cls,
        model: GPTLM,
        checkpoint_dir: str,
        *,
        optimizer=None,
        tokenizer=None,
        **kw,
    ) -> "TextServer":
        """Serve the newest valid checkpoint in ``checkpoint_dir`` — any
        mode layout (:func:`canonical_lm_params`), with the shipped
        ``tokenizer.json`` unless an explicit tokenizer is passed. The
        restored step and directory are recorded so
        :meth:`swap_from_checkpoint` can later adopt a NEWER step from
        the same directory (the live-weight-swap half of the
        train→publish→serve loop)."""
        params, step = canonical_lm_params(
            model, checkpoint_dir, optimizer=optimizer
        )
        tok = tokenizer if tokenizer is not None else load_tokenizer(
            checkpoint_dir
        )
        srv = cls(model, params, tok, **kw)
        srv.checkpoint_dir = checkpoint_dir
        srv.checkpoint_step = int(step)
        srv._restore_optimizer = optimizer
        return srv

    # -- compiled graphs ---------------------------------------------------

    def _init_state(self):
        s = self.slots
        kd = jax.random.key_data(jax.random.split(jax.random.key(0), s))
        common = dict(
            last_tok=jnp.zeros((s,), jnp.int32),
            key=kd,
            emitted=jnp.zeros((s,), jnp.int32),
            budget=jnp.zeros((s,), jnp.int32),
            finished=jnp.ones((s,), bool),  # vacant == finished
            greedy=jnp.ones((s,), bool),
            temp=jnp.ones((s,), jnp.float32),
            top_p=jnp.ones((s,), jnp.float32),
            eos=jnp.full((s,), -1, jnp.int32),
        )
        if self.paged:
            cache = self.model.empty_paged_cache(
                s, self.kv_blocks, self.block_size, self.kv_dtype
            )
            return _PagedState(
                k=cache.k,
                v=cache.v,
                block_tables=cache.block_tables,
                lengths=cache.lengths,
                k_scale=cache.k_scale,
                v_scale=cache.v_scale,
                **common,
            )
        cache = self.model.empty_slot_cache(s, self.kv_dtype)
        return _DecodeState(
            k=cache.k,
            v=cache.v,
            lengths=cache.lengths,
            k_scale=cache.k_scale,
            v_scale=cache.v_scale,
            **common,
        )

    def _pick(self, logits, key_data, greedy, temp, top_p):
        """Per-slot next-token pick, the exact arithmetic of
        ``GPTLM.{greedy,sample}_decode``'s pick closures (greedy: argmax of
        the raw logits; sampled: f32/temperature, nucleus keep-mask by
        EXCLUSIVE cumulative probability, categorical) — vmapped per row
        with per-slot knobs. ``top_p=1.0`` keeps every token, making the
        nucleus branch the identity, and the categorical runs at [1, V] so
        its noise bits match the in-process B=1 call exactly (the parity
        contract)."""

        amax = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def row(lg, kd, t, p):
            lt = lg.astype(jnp.float32) / t
            order = jnp.argsort(lt)[::-1]
            sorted_l = lt[order]
            probs = jax.nn.softmax(sorted_l)
            keep_sorted = jnp.cumsum(probs) - probs < p
            keep = jnp.zeros(lt.shape, bool).at[order].set(keep_sorted)
            lt = jnp.where(keep, lt, -jnp.inf)
            return jax.random.categorical(
                jax.random.wrap_key_data(kd), lt[None, :], axis=-1
            )[0].astype(jnp.int32)

        def mixed(_):
            sampled = jax.vmap(row)(logits, key_data, temp, top_p)
            return jnp.where(greedy, amax, sampled)

        # Greedy-only banks (the default config) skip the full-vocab
        # sort/softmax/gumbel machinery entirely — it is O(V log V) per
        # slot per token in the hot chunk graph, and jnp.where alone
        # would still evaluate it.
        return jax.lax.cond(jnp.all(greedy), lambda _: amax, mixed, None)

    def _split_keys(self, key_data):
        """Per-slot ``key, sub = jax.random.split(key)`` on key-data rows —
        the exact chain ``GPTLM._decode_loop`` advances per request."""

        def row(kd):
            nxt = jax.random.split(jax.random.wrap_key_data(kd))
            return (
                jax.random.key_data(nxt[0]),
                jax.random.key_data(nxt[1]),
            )

        carried, sub = jax.vmap(row)(key_data)
        return carried, sub

    def _cache(self, st):
        from distributed_tensorflow_tpu.models.gpt import (
            PagedKVCache,
            SlotKVCache,
        )

        if self.paged:
            return PagedKVCache(
                k=st.k,
                v=st.v,
                block_tables=st.block_tables,
                lengths=st.lengths,
                k_scale=st.k_scale,
                v_scale=st.v_scale,
            )
        return SlotKVCache(
            k=st.k,
            v=st.v,
            lengths=st.lengths,
            k_scale=st.k_scale,
            v_scale=st.v_scale,
        )

    def _prefill_graph(
        self, params, st, tokens, plens, admit, key, budget, greedy, temp,
        top_p, eos,
    ):
        """One admission round: ragged batched prefill into admitted slots
        + the first sampled token per admitted request (the pick
        ``_decode_loop`` makes from the prefill logits), all in-graph."""
        logits, cache = self.model.prefill_slots(
            params, self._cache(st), tokens, plens, admit
        )
        keys = jnp.where(admit[:, None], key, st.key)
        carried, sub = self._split_keys(keys)
        first = self._pick(logits, sub, greedy, temp, top_p)
        sel = lambda n, o: jnp.where(admit, n, o)  # noqa: E731
        eos_eff = sel(eos, st.eos)
        fin = sel(
            (first == eos_eff) | (budget <= 1), st.finished
        )
        return st._replace(
            k=cache.k,
            v=cache.v,
            k_scale=cache.k_scale,
            v_scale=cache.v_scale,
            lengths=cache.lengths,
            last_tok=sel(first, st.last_tok),
            key=jnp.where(admit[:, None], carried, st.key),
            emitted=sel(jnp.ones_like(st.emitted), st.emitted),
            budget=sel(budget, st.budget),
            finished=fin,
            greedy=sel(greedy, st.greedy),
            temp=jnp.where(admit, temp, st.temp),
            top_p=jnp.where(admit, top_p, st.top_p),
            eos=eos_eff,
        )

    def _paged_prefill_graph(
        self, params, st, tokens, suffix_lens, prefix_lens, admit,
        block_tables, key, budget, greedy, temp, top_p, eos,
    ):
        """Paged admission round: ragged batched EXTEND through the
        block tables (prefix-cache hits arrive as nonzero
        ``prefix_lens`` — those blocks are read, not recomputed; the
        host strips the cached prefix, so ``tokens`` is only each
        request's suffix padded to its bucket) + the first pick from
        each row's last real suffix position. ``block_tables`` [S, NB]
        is the host-authoritative table snapshot (non-admitted rows
        unchanged by construction)."""
        cache = self._cache(st)._replace(block_tables=block_tables)
        logits, cache = self.model.extend_paged(
            params, cache, tokens, suffix_lens, prefix_lens, admit
        )
        last_lg = jnp.take_along_axis(
            logits,
            jnp.maximum(suffix_lens - 1, 0)[:, None, None],
            axis=1,
        )[:, 0]  # [S, vocab]
        keys = jnp.where(admit[:, None], key, st.key)
        carried, sub = self._split_keys(keys)
        first = self._pick(last_lg, sub, greedy, temp, top_p)
        sel = lambda n, o: jnp.where(admit, n, o)  # noqa: E731
        eos_eff = sel(eos, st.eos)
        fin = sel((first == eos_eff) | (budget <= 1), st.finished)
        return st._replace(
            k=cache.k,
            v=cache.v,
            k_scale=cache.k_scale,
            v_scale=cache.v_scale,
            block_tables=block_tables,
            lengths=sel(prefix_lens + suffix_lens, st.lengths),
            last_tok=sel(first, st.last_tok),
            key=jnp.where(admit[:, None], carried, st.key),
            emitted=sel(jnp.ones_like(st.emitted), st.emitted),
            budget=sel(budget, st.budget),
            finished=fin,
            greedy=sel(greedy, st.greedy),
            temp=jnp.where(admit, temp, st.temp),
            top_p=jnp.where(admit, top_p, st.top_p),
            eos=eos_eff,
        )

    def _verify_graph(self, params, st, suffix, suffix_lens):
        """One speculative verify round (the paged engine's decode tick
        when ``spec_draft > 0``): per active slot the host sent
        ``suffix = [last_tok, d_1..d_k]`` (k = that slot's draft length,
        0 for sampled slots — speculation is greedy-only) — ONE batched
        extend scores every draft position, then GREEDY-EXACT
        acceptance in-graph: target ``tgt[i] = argmax(logits[i])``
        (position 0 through :meth:`_pick`, so sampled slots keep their
        PRNG chain), draft ``d_i`` is accepted iff it equals
        ``tgt[i-1]`` and every earlier draft was accepted, and the
        emitted run is ``tgt[0..n_acc]`` — each accepted position's
        target IS the draft token, plus the first-mismatch correction,
        so the stream is the pure greedy stream by construction (the
        parity contract survives speculation; a bad draft costs wasted
        compute, never a changed token). EOS/budget truncate the run
        exactly as the chunk scan would token by token; ``lengths``
        advance only by tokens actually emitted — rejected drafts' K/V
        stay past ``lengths`` as unreachable garbage, overwritten by
        the next write at those positions. Returns
        ``(state, tokens [D+1, S], valid [D+1, S])`` — the chunk
        graph's host contract, so the scheduler loop is shared."""
        max_len = self.model.max_len
        act = ~st.finished & (st.lengths < max_len)
        logits, cache = self.model.verify_paged(
            params, self._cache(st), suffix, suffix_lens, st.lengths, act,
            engine=self.decode_engine,
        )
        s, d1 = suffix.shape
        amax = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, D+1]
        carried, sub = self._split_keys(st.key)
        t0 = self._pick(logits[:, 0], sub, st.greedy, st.temp, st.top_p)
        tgt = amax.at[:, 0].set(t0)
        pos = jnp.arange(d1)
        # Leading accepted-draft run: d_i == tgt_{i-1}, all-prior rule.
        ok = (suffix[:, 1:] == tgt[:, :-1]) & (
            pos[None, 1:] < suffix_lens[:, None]
        )
        n_acc = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(1)  # [S]
        eos_hit = tgt == st.eos[:, None]
        prev_eos = (
            jnp.cumsum(eos_hit.astype(jnp.int32), axis=1)
            - eos_hit.astype(jnp.int32)
        ) > 0
        valid = (
            act[:, None]
            & (pos[None] <= n_acc[:, None])
            & (pos[None] < (st.budget - st.emitted)[:, None])
            & ~prev_eos
        )
        n_emit = valid.sum(1).astype(jnp.int32)  # >= 1 for active slots
        emitted = st.emitted + n_emit
        last = jnp.take_along_axis(
            tgt, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
        )[:, 0]
        fin = st.finished | (
            act & ((eos_hit & valid).any(1) | (emitted >= st.budget))
        )
        st = st._replace(
            k=cache.k,
            v=cache.v,
            k_scale=cache.k_scale,
            v_scale=cache.v_scale,
            lengths=st.lengths + n_emit,
            last_tok=jnp.where(act, last, st.last_tok),
            key=jnp.where(act[:, None], carried, st.key),
            emitted=emitted,
            finished=fin,
        )
        return st, tgt.T, valid.T

    def _chunk_graph(self, params, st):
        """``chunk`` decode steps as one ``lax.scan``: per step every
        unfinished slot advances one token (decode + in-graph pick),
        finished/vacant slots ride along masked. Returns the new state
        plus the [chunk, S] token block and its validity mask — the only
        per-chunk host traffic. One body for both cache layouts: the
        paged step differs only in how the cache row is addressed
        (:meth:`GPTLM.decode_paged` vs :meth:`GPTLM.decode_slots`)."""
        max_len = self.model.max_len
        decode = (
            self.model.decode_paged if self.paged else self.model.decode_slots
        )

        def body(st, _):
            act = ~st.finished & (st.lengths < max_len)
            logits, cache = decode(
                params, st.last_tok, self._cache(st), active=act,
                engine=self.decode_engine,
            )
            carried, sub = self._split_keys(st.key)
            nxt = self._pick(logits, sub, st.greedy, st.temp, st.top_p)
            nxt = jnp.where(act, nxt, st.last_tok)
            emitted = st.emitted + act.astype(jnp.int32)
            fin = st.finished | (
                act
                & (
                    (nxt == st.eos)
                    | (emitted >= st.budget)
                    | (cache.lengths >= max_len)
                )
            )
            st = st._replace(
                k=cache.k,
                v=cache.v,
                k_scale=cache.k_scale,
                v_scale=cache.v_scale,
                lengths=cache.lengths,
                last_tok=nxt,
                key=jnp.where(act[:, None], carried, st.key),
                emitted=emitted,
                finished=fin,
            )
            return st, (nxt, act)

        st, (toks, valid) = jax.lax.scan(
            body, st, None, length=self.chunk
        )
        return st, toks, valid

    # -- the scheduler (host side) -----------------------------------------

    def submit(
        self,
        tokens,
        config: GenerationConfig | None = None,
        *,
        deadline_s: float | None = None,
        priority: int = 0,
        trace: str | None = None,
        prefill_only: bool = False,
        resume: dict | None = None,
        emitted_tokens=None,
    ) -> int:
        """Queue one request (prompt as a 1-D int token array). Returns a
        request id for :meth:`result`. Validates against the bucket/cache
        geometry up front: the prompt must fit a bucket and
        ``len + max_new`` must fit ``max_len`` (the KV cache is the slot's
        whole memory — vLLM's fixed-slot discipline).

        ``deadline_s`` (round 16, shed semantics round 21): wall-clock
        budget from NOW. A RESIDENT request past its deadline is
        cancelled at the next chunk boundary (slot/blocks freed,
        ``request_cancelled`` event, :meth:`result` raises
        :class:`RequestCancelled`). A QUEUED request past its deadline —
        or whose remaining budget provably cannot finish inside it at
        the measured per-token rate — is SHED before any prefill
        dispatch (``request_shed`` event, :class:`RequestShed`); one
        that arrives already dead (``deadline_s <= 0``) is shed AT
        SUBMIT and never occupies queue_limit budget.

        ``priority`` (round 21): int >= 0, higher = more important.
        Admission picks by (priority class, earliest deadline first);
        with every queued request at priority 0 and no deadline the
        order is EXACTLY the round-16 FIFO. Under saturation a
        higher-priority submit sheds the lowest class's most deferrable
        request instead of bouncing QueueFull.

        ``trace`` overrides the generated trace id so a fleet router's
        retries keep one id across replicas. Raises :class:`QueueFull`
        when the queue is at ``queue_limit`` with no lower class to
        shed, and RuntimeError once :meth:`drain` closed admission.

        Disaggregated handoff (round 23, docs/serving.md
        §disaggregation; both knobs require ``paged=True`` — block
        tables are what make the cache relocatable):

        - ``prefill_only=True``: run prefill + the first token, then
          EXPORT the request's written KV blocks + sampling state
          (:meth:`take_export`) and free the slot — the prefill leg of
          a two-leg fleet request. A request that FINISHES at prefill
          (budget 1 / immediate EOS) completes normally instead.
        - ``resume=payload``: admit a mid-flight request — the decode
          leg. The payload (an export from a prefill replica, same
          model geometry) is imported into freshly reserved blocks and
          the chunk scan continues token-identically.
          ``emitted_tokens`` seeds the output with leg 1's tokens so
          :meth:`result` returns the complete stream."""
        config = config or GenerationConfig()
        priority = int(priority)
        if priority < 0:
            raise ValueError(f"priority must be >= 0, got {priority}")
        config.validate(self.model.vocab_size)
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size < 1:
            raise ValueError("empty prompt")
        if tokens.size > self.buckets[-1]:
            raise ValueError(
                f"prompt length {tokens.size} exceeds the largest bucket "
                f"{self.buckets[-1]}"
            )
        if tokens.size + config.max_new > self.model.max_len:
            raise ValueError(
                f"prompt {tokens.size} + max_new {config.max_new} exceeds "
                f"max_len {self.model.max_len}"
            )
        if self.paged:
            need = blocks_for(
                tokens.size + config.max_new, self.block_size
            )
            if need > self.kv_blocks:
                raise ValueError(
                    f"request needs {need} KV blocks but the pool holds "
                    f"{self.kv_blocks}; raise kv_blocks or shrink the "
                    "request"
                )
        if (prefill_only or resume is not None) and not self.paged:
            raise ValueError(
                "KV migration requires paged=True (block tables are what "
                "make the cache relocatable across replicas)"
            )
        if prefill_only and resume is not None:
            raise ValueError(
                "prefill_only and resume are the two LEGS of one request "
                "— a submit is at most one of them"
            )
        if resume is not None:
            self._validate_resume(resume, tokens, config)
        if self._draining:
            raise RuntimeError(
                "server is draining: admission is closed (residents are "
                "being finished; route new requests to another replica)"
            )
        if deadline_s is not None and float(deadline_s) <= 0.0:
            # Arrived dead: terminal RequestShed at submit — it must
            # never occupy queue_limit budget or displace live work
            # (satellite, round 21). The birth event still fires so the
            # per-request timeline reconstruction sees one lifecycle.
            rid = self._next_rid
            self._next_rid += 1
            req = _Request(
                rid, tokens, config,
                trace=trace, deadline_s=deadline_s, priority=priority,
            )
            self._results[rid] = req
            self.metrics.counter("requests_submitted_total").inc()
            self._emit_submit(req)
            self._shed(req, reason="expired_at_submit")
            return rid
        if (
            self.queue_limit is not None
            and len(self._queue) >= self.queue_limit
        ):
            victim = self._shed_victim(priority)
            if victim is None:
                self.metrics.counter("queue_rejections_total").inc()
                self.journal.emit(
                    "queue_reject",
                    prompt_len=int(tokens.size),
                    queue_depth=len(self._queue),
                    queue_limit=int(self.queue_limit),
                    **({"trace": trace} if trace else {}),
                )
                raise QueueFull(
                    f"admission queue is at queue_limit={self.queue_limit}; "
                    "retry later or route to another replica"
                )
            # Saturation shed (round 21): the newcomer outranks the
            # lowest queued class — shed that class's most deferrable
            # member (no deadline first, then latest deadline; never out
            # of deadline order within the class) instead of bouncing
            # the higher-priority request.
            self._queue.remove(victim)
            self._shed(victim, reason="preempted")
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(
            rid, tokens, config,
            trace=trace, deadline_s=deadline_s, priority=priority,
        )
        req.prefill_only = bool(prefill_only)
        if resume is not None:
            req.resume = resume
            if emitted_tokens is not None:
                req.out = [int(t) for t in np.asarray(emitted_tokens)]
            if len(req.out) != int(resume["meta"]["emitted"]):
                raise ValueError(
                    f"resume payload says {resume['meta']['emitted']} "
                    f"tokens were emitted on leg 1 but emitted_tokens "
                    f"carries {len(req.out)}"
                )
        self._queue.append(req)
        self._results[rid] = req
        self.metrics.counter("requests_submitted_total").inc()
        self.metrics.gauge("queue_depth").set(len(self._queue))
        self._emit_submit(req)
        return rid

    def _emit_submit(self, req: _Request) -> None:
        # The trace's birth event: everything downstream (admission,
        # spans, completion/shed) joins to it by trace/rid. ``priority``
        # rides only when non-default — the round-16 event bytes are
        # preserved on the default path.
        self.journal.emit(
            "request_submit",
            rid=req.rid,
            trace=req.trace,
            prompt_len=int(req.tokens.size),
            max_new=int(req.config.max_new),
            greedy=bool(req.config.greedy),
            **({"priority": req.priority} if req.priority else {}),
        )

    def _validate_resume(self, resume: dict, tokens, config) -> None:
        """Refuse a migration payload that cannot continue here — wrong
        model geometry, wrong cache dtype, or inconsistent with the
        request it claims to resume. Raises ValueError (a PERMANENT
        rejection in the fleet protocol: the router falls back to
        re-prefill, it does not retry the import)."""
        meta = resume.get("meta") or {}
        arrays = resume.get("arrays") or {}
        want = {
            "kv_dtype": self.kv_dtype,
            "block_size": self.block_size,
            "num_layers": self.model.num_layers,
            "num_kv_heads": self.model.num_kv_heads,
            "head_dim": self.model.head_dim,
        }
        for k, w in want.items():
            if meta.get(k) != w:
                raise ValueError(
                    f"resume payload geometry mismatch: {k}="
                    f"{meta.get(k)!r} but this replica serves {w!r}"
                )
        if int(meta.get("length", -1)) != int(tokens.size):
            raise ValueError(
                f"resume payload covers {meta.get('length')} positions "
                f"but the prompt has {tokens.size}"
            )
        if int(meta.get("emitted", 0)) < 1:
            raise ValueError("resume payload emitted no leg-1 token")
        need = {"k", "v", "key"}
        if self.kv_dtype != "bf16":
            need |= {"k_scale", "v_scale"}
        missing = need - set(arrays)
        if missing:
            raise ValueError(
                f"resume payload missing arrays: {sorted(missing)}"
            )
        n_src = int(meta.get("blocks", 0))
        if n_src != blocks_for(int(tokens.size), self.block_size):
            raise ValueError(
                f"resume payload carries {n_src} blocks; "
                f"{blocks_for(int(tokens.size), self.block_size)} cover "
                "the prompt"
            )

    def _shed_victim(self, priority: int) -> _Request | None:
        """Under a full queue: the request a ``priority``-class submit may
        displace — a member of the strictly LOWEST queued class when that
        class ranks below the newcomer; within the class the most
        deferrable one (no deadline, then latest deadline, then newest).
        All-default traffic (priority 0 everywhere) finds no victim and
        keeps the round-16 QueueFull contract."""
        if priority <= 0 or not self._queue:
            return None
        low = min(r.priority for r in self._queue)
        if low >= priority:
            return None
        return max(
            (r for r in self._queue if r.priority == low),
            key=lambda r: (
                math.inf if r.deadline is None else r.deadline, r.rid,
            ),
        )

    def _shed(self, req: _Request, *, reason: str) -> None:
        """Terminal drop WITHOUT spending a dispatch: the loud record
        (``request_shed`` event + ``sheds_total``) a router or load
        generator keys on. Distinct from :meth:`_cancel` — no slot or
        blocks exist to free, and :meth:`result` raises
        :class:`RequestShed`."""
        req.shed = True
        self.metrics.counter("sheds_total").inc()
        self.journal.emit(
            "request_shed",
            rid=req.rid,
            trace=req.trace,
            priority=req.priority,
            reason=reason,
            age_s=round(time.perf_counter() - req.t_submit, 6),
        )

    def bucket_for(self, length: int) -> int:
        """Smallest bucket holding a ``length``-token prompt."""
        for b in self.buckets:
            if length <= b:
                return b
        raise ValueError(
            f"prompt length {length} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )

    def _free_slots(self) -> list[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _admit(self) -> None:
        """Move queued requests into free slots; one prefill dispatch per
        length bucket among this round's admissions. Paged mode admits by
        free BLOCKS (worst-case reservation minus prefix-cache hits) with
        no head-of-line blocking; slab mode by free slots alone."""
        if self.paged:
            self._admit_paged()
        else:
            self._admit_slab()

    def _plan_admission(self, req: _Request):
        """Block reservation for one request: prefix-cache match
        (matched blocks retained IMMEDIATELY, so this round's own
        evictions cannot free them out from under the plan), worst-case
        new-block reservation for ``prompt + max_new`` (admission never
        overcommits, so generation never OOMs mid-flight), LRU eviction
        of cache-only blocks under pressure. Returns None — releasing
        any retains — when the request does not fit right now."""
        bs = self.block_size
        total = blocks_for(int(req.tokens.size) + req.config.max_new, bs)
        matched: list[int] = []
        if self._prefix is not None:
            matched = self._prefix.match(req.tokens)
            for b in matched:
                self._alloc.retain(b)
        n_new = total - len(matched)
        if not self._alloc.can_alloc(n_new) and self._prefix is not None:
            deficit = n_new - self._alloc.free_blocks
            # Evict only when eviction can actually make this request
            # fit — a hopeless flush would trade the warm prefix cache
            # for nothing and the request would still be skipped.
            if self._prefix.evictable_blocks() >= deficit:
                self._prefix.evict(deficit)
        if not self._alloc.can_alloc(n_new):
            for b in matched:
                self._alloc.release(b)
            return None
        return {
            "table": matched + self._alloc.alloc(n_new),
            "matched": len(matched),
            "new": n_new,
        }

    def _plan_import(self, req: _Request):
        """Block reservation for a migration import: ``prompt + max_new``
        FRESH blocks, no prefix-cache match — the payload's blocks are
        the authoritative prompt KV (round-15 storage-dtype values), and
        splicing locally cached prefix blocks under an imported stream
        would trade a bitwise guarantee for a recomputed one. Same
        eviction-under-pressure rule as :meth:`_plan_admission`."""
        total = blocks_for(
            int(req.tokens.size) + req.config.max_new, self.block_size
        )
        if not self._alloc.can_alloc(total) and self._prefix is not None:
            deficit = total - self._alloc.free_blocks
            if self._prefix.evictable_blocks() >= deficit:
                self._prefix.evict(deficit)
        if not self._alloc.can_alloc(total):
            return None
        return {"table": self._alloc.alloc(total), "matched": 0,
                "new": total}

    def _import_resume(self, slot: int, req: _Request, plan: dict) -> None:
        """Admit a mid-flight request from a migration payload: write the
        exported blocks into this pool (:func:`import_kv_blocks` — the
        sentinel=``num_blocks`` scatter rule), restore the per-slot
        sampling/progress rows EXACTLY as the prefill dispatch left them
        on the source replica, and let the ordinary chunk scan continue.
        Token parity is by construction: the blocks carry the exact
        storage-dtype values (round-15 uniform rule) and the PRNG row is
        the carried key after leg 1's single split."""
        from distributed_tensorflow_tpu.models.gpt import import_kv_blocks

        t0 = time.perf_counter()
        payload = req.resume
        meta = payload["meta"]
        arrays = payload["arrays"]
        table = plan["table"]
        row = self._host_tables[slot]
        row[:] = 0
        row[: len(table)] = table
        self._slot_blocks[slot] = list(table)
        n_src = int(meta["blocks"])
        blocks = {
            k: arrays[k]
            for k in ("k", "v", "k_scale", "v_scale")
            if k in arrays
        }
        # Pad every import to ONE canonical block count (sentinel ids
        # drop their zero rows): the eager scatter otherwise compiles a
        # fresh executable per distinct payload size — a ~1 s XLA
        # compile per prompt-length class, which the disagg bench
        # measured as the dominant cost of the whole migration path.
        pool = self._cache(self._state)
        cap = blocks_for(self.model.max_len, self.block_size)
        ids = list(int(b) for b in table[:n_src])
        ids += [int(pool.k.shape[1])] * (cap - n_src)
        if cap > n_src:
            blocks = {
                k: np.concatenate(
                    [
                        np.asarray(a),
                        np.zeros(
                            (a.shape[0], cap - n_src) + a.shape[2:],
                            np.asarray(a).dtype,
                        ),
                    ],
                    axis=1,
                )
                for k, a in (
                    (k, np.asarray(a)) for k, a in blocks.items()
                )
            }
        cache = import_kv_blocks(pool, ids, blocks)
        st = self._state

        def put_row(field, value):
            a = np.asarray(getattr(st, field)).copy()
            a[slot] = value
            return self._commit_row(a)

        c = req.config
        self._state = st._replace(
            k=cache.k,
            v=cache.v,
            k_scale=cache.k_scale,
            v_scale=cache.v_scale,
            block_tables=put_row("block_tables", row),
            lengths=put_row("lengths", int(meta["length"])),
            last_tok=put_row("last_tok", int(meta["last_tok"])),
            key=put_row("key", np.asarray(arrays["key"])),
            emitted=put_row("emitted", int(meta["emitted"])),
            budget=put_row("budget", c.max_new),
            finished=put_row("finished", False),
            greedy=put_row("greedy", c.greedy),
            temp=put_row("temp", c.temperature),
            top_p=put_row("top_p", c.top_p),
            eos=put_row("eos", -1 if c.eos_id is None else c.eos_id),
        )
        self._slot_req[slot] = req
        req.t_admit = time.perf_counter()
        nbytes = sum(
            np.asarray(a).nbytes for a in arrays.values()
        )
        self.metrics.counter("admissions_total").inc()
        self.metrics.counter("migrations_imported_total").inc()
        self.journal.emit(
            "admission",
            rid=req.rid,
            trace=req.trace,
            slot=int(slot),
            bucket=0,
            prompt_len=int(req.tokens.size),
            imported_blocks=n_src,
            new_blocks=int(plan["new"]),
            migrated=True,
            queue_wait_s=round(req.t_admit - req.t_submit, 6),
        )
        self.journal.emit(
            "kv_migration",
            phase="import",
            rid=req.rid,
            trace=req.trace,
            slot=int(slot),
            blocks=n_src,
            nbytes=int(nbytes),
            wall_ms=round((time.perf_counter() - t0) * 1e3, 3),
        )

    def _admit_member_row(
        self, slot, req, lb, key, budget, greedy, temp, top_p, eos,
        journal_extra=None,
    ) -> None:
        """Per-member sampling/budget row + admission telemetry shared by
        BOTH engine modes — a ``GenerationConfig`` field wired here
        reaches the slab and paged admission paths together (they must
        never drift: the parity contract spans both)."""
        c = req.config
        key[slot] = np.asarray(
            jax.random.key_data(jax.random.key(c.seed))
        )
        budget[slot] = c.max_new
        greedy[slot] = c.greedy
        temp[slot] = c.temperature
        top_p[slot] = c.top_p
        eos[slot] = -1 if c.eos_id is None else c.eos_id
        self._slot_req[slot] = req
        req.t_admit = time.perf_counter()
        self.metrics.counter("admissions_total").inc()
        self.journal.emit(
            "admission",
            rid=req.rid,
            trace=req.trace,
            slot=int(slot),
            bucket=int(lb),
            prompt_len=int(req.tokens.size),
            **(journal_extra or {}),
            queue_wait_s=round(req.t_admit - req.t_submit, 6),
        )

    def _record_first_token(self, slot, req, first, fin, t_first) -> None:
        """Post-prefill bookkeeping shared by both engine modes: TTFT,
        the admission's first generated token, early EOS/budget finish."""
        req.t_first = t_first
        self.metrics.histogram("ttft_s").observe(t_first - req.t_submit)
        req.out.append(int(first[slot]))
        if fin[slot]:
            # A prefill_only request that FINISHES at prefill (budget 1,
            # immediate EOS) completes normally — nothing to migrate.
            self._finish(slot)
        elif req.prefill_only:
            self._export_request(slot, req)

    def _commit_row(self, a):
        """Host-edited state rows must re-enter the jit as arrays
        COMMITTED to the same device as the graph outputs they replace:
        a raw numpy leaf keys the executable cache under unspecified
        sharding, and the NEXT prefill/chunk dispatch silently
        recompiles its multi-second program (same trace, different
        executable — the round-23 disagg A/B surfaced this as a full
        recompile after every export/import/cancel)."""
        sharding = getattr(self._state.k, "sharding", None)
        return jax.device_put(a, sharding)

    def _export_request(self, slot: int, req: _Request) -> None:
        """The prefill leg's terminal act: fetch the request's WRITTEN
        KV blocks (``ceil(prompt/block_size)`` — the first generated
        token's KV is written by the first decode step, which runs on
        the importing replica) + the per-slot sampling/progress rows,
        stash them as the migration payload (:meth:`take_export`), and
        free the slot. The request is terminal HERE; the radix keeps the
        prompt's prefix blocks warm for future prefills."""
        from distributed_tensorflow_tpu.models.gpt import export_kv_blocks

        t0 = time.perf_counter()
        st = self._state
        length = int(np.asarray(st.lengths[slot]))
        n_src = blocks_for(length, self.block_size)
        ids = self._slot_blocks[slot][:n_src]
        # Gather at the ONE canonical block count every export shares
        # (pad with repeats of a real id — export has no sentinel), then
        # trim on the host: the eager gather's executable is keyed on
        # len(ids), so per-prompt-length shapes would compile a fresh
        # XLA program per length class at serving time. Wire bytes stay
        # the trimmed n_src blocks.
        cap = blocks_for(self.model.max_len, self.block_size)
        padded = list(ids) + [int(ids[0])] * (cap - n_src)
        arrays = {
            k: np.asarray(v)[:, :n_src]
            for k, v in export_kv_blocks(self._cache(st), padded).items()
        }
        arrays["key"] = np.asarray(st.key[slot])
        meta = {
            "kv_dtype": self.kv_dtype,
            "block_size": self.block_size,
            "num_layers": self.model.num_layers,
            "num_kv_heads": self.model.num_kv_heads,
            "head_dim": self.model.head_dim,
            "length": length,
            "blocks": n_src,
            "last_tok": int(np.asarray(st.last_tok[slot])),
            "emitted": int(np.asarray(st.emitted[slot])),
            "max_new": int(req.config.max_new),
        }
        req.export = {"arrays": arrays, "meta": meta}
        req.migrated = True
        req.done = True
        fin = np.asarray(st.finished).copy()
        fin[slot] = True
        self._state = self._state._replace(finished=self._commit_row(fin))
        self._release_slot(slot)
        nbytes = sum(a.nbytes for a in arrays.values())
        self.metrics.counter("migrations_exported_total").inc()
        self.journal.emit(
            "kv_migration",
            phase="export",
            rid=req.rid,
            trace=req.trace,
            slot=int(slot),
            blocks=n_src,
            nbytes=int(nbytes),
            wall_ms=round((time.perf_counter() - t0) * 1e3, 3),
            ttft_s=round(
                (req.t_first if req.t_first is not None else t0)
                - req.t_submit,
                6,
            ),
        )

    def warm_import(self) -> None:
        """Compile BOTH migration executables ahead of traffic: one
        all-sentinel import against the live pool (every row drops, so
        the pool values are untouched) plus one canonical-shape export
        gather. `_import_resume` pads every real payload and
        `_export_request` pads every gather to this single shape, so
        these two programs are the only ones migration ever dispatches —
        first-request TTFT on either leg's replica must not be an XLA
        compile measurement (the ``--warm`` contract)."""
        if not self.paged:
            return
        from distributed_tensorflow_tpu.models.gpt import (
            export_kv_blocks,
            import_kv_blocks,
        )

        pool = self._cache(self._state)
        cap = blocks_for(self.model.max_len, self.block_size)

        def zeros(p):
            return np.zeros((p.shape[0], cap) + tuple(p.shape[2:]), p.dtype)

        blocks = {"k": zeros(pool.k), "v": zeros(pool.v)}
        if pool.k_scale is not None:
            blocks["k_scale"] = zeros(pool.k_scale)
            blocks["v_scale"] = zeros(pool.v_scale)
        import_kv_blocks(pool, [int(pool.k.shape[1])] * cap, blocks)
        jax.block_until_ready(
            list(export_kv_blocks(pool, [0] * cap).values())
        )

    def take_export(self, rid: int) -> dict | None:
        """Consume a migrated request's payload: the KV-block arrays +
        state meta :meth:`_export_request` stashed, plus leg 1's emitted
        tokens. Returns None when the request completed without
        migrating (finished at prefill) — the caller then treats
        :meth:`result` as the terminal read. A consumed or unknown rid
        also returns None (idempotent, like a second ``result`` read is
        not): the worker loop probes every done rid through here."""
        req = self._results.get(rid)
        if req is None or not req.migrated:
            return None
        del self._results[rid]
        return {
            "arrays": req.export["arrays"],
            "meta": req.export["meta"],
            "tokens": list(req.out),
            "trace": req.trace,
        }

    def _admit_paged(self) -> None:
        free = self._free_slots()
        if not free or not self._queue:
            return
        batch: list[tuple[int, _Request, dict, int]] = []
        skipped: deque[_Request] = deque()
        # Same-round cold-prefix serialization (round 14): block id →
        # the admission WAVE whose prefill writes its K/V this round.
        pending: dict[int, int] = {}
        bs = self.block_size
        imports: list[tuple[int, _Request, dict]] = []
        while free and self._queue:
            req = self._queue.popleft()
            plan = (
                self._plan_import(req) if req.resume is not None
                else self._plan_admission(req)
            )
            if plan is None:
                # No head-of-line blocking: a request the pool cannot
                # hold yet waits WITHOUT starving shorter requests
                # behind it (relative FIFO order is preserved both among
                # the admitted and among the skipped).
                skipped.append(req)
                continue
            if req.resume is not None:
                # Migration import (round 23): the payload's device
                # writes land synchronously below, BEFORE any of this
                # round's prefill waves dispatch — so the radix entries
                # registered here are valid for every same-round reader
                # without joining the wave dependency graph.
                if self._prefix is not None:
                    self._prefix.insert(
                        req.tokens, plan["table"],
                        int(req.tokens.size) // bs,
                    )
                imports.append((free.pop(0), req, plan))
                continue
            # Register the planned full PROMPT blocks in the radix NOW —
            # round 11 registered post-prefill, so N cold requests
            # sharing a prefix admitted in ONE round all missed and
            # prefilled it N times (the GOTCHA that needed staggered
            # test choreography). A match against a block whose K/V
            # this round has not yet written is sound only when the
            # reader dispatches AFTER the writer, so each member lands
            # in a wave one past its deepest pending dependency and
            # waves dispatch in order below. Refcounts make the early
            # registration safe: the writer's slot holds every pending
            # block until its prefill ran, so eviction (cache-only,
            # refcount 1) can never reclaim one, and an early finisher
            # only drops the slot references — the radix keeps its own.
            wave = 0
            if self._prefix is not None:
                matched_ids = plan["table"][: plan["matched"]]
                deps = [pending[b] for b in matched_ids if b in pending]
                if deps:
                    wave = max(deps) + 1
                n_full = int(req.tokens.size) // bs
                self._prefix.insert(req.tokens, plan["table"], n_full)
                for b in plan["table"][plan["matched"]: n_full]:
                    pending[b] = wave
            batch.append((free.pop(0), req, plan, wave))
        skipped.extend(self._queue)
        self._queue = skipped
        self.metrics.gauge("queue_depth").set(len(self._queue))
        for slot, req, plan in imports:
            self._import_resume(slot, req, plan)
        if not batch:
            self.metrics.gauge("kv_blocks_used").set(
                self._alloc.used_blocks
            )
            return
        for slot, req, plan, wave in batch:
            row = self._host_tables[slot]
            row[:] = 0
            row[: len(plan["table"])] = plan["table"]
            self._slot_blocks[slot] = list(plan["table"])
        for wave in sorted({w for _, _, _, w in batch}):
            self._prefill_wave(
                [m for m in batch if m[3] == wave], wave
            )
        self.metrics.gauge("kv_blocks_used").set(self._alloc.used_blocks)

    def _prefill_wave(self, members_w, wave: int) -> None:
        """One admission wave's prefill dispatches (one per length
        bucket among the wave's members)."""
        s = self.slots
        by_bucket: dict[int, list] = {}
        for slot, req, plan, _ in members_w:
            prefix_len = plan["matched"] * self.block_size
            suffix = req.tokens[prefix_len:]
            by_bucket.setdefault(self.bucket_for(suffix.size), []).append(
                (slot, req, plan, prefix_len, suffix)
            )
        for lb, members in sorted(by_bucket.items()):
            tokens = np.zeros((s, lb), np.int32)
            slens = np.ones((s,), np.int32)  # suffix lens must be >= 1
            plens = np.zeros((s,), np.int32)  # cached-prefix lens
            admit = np.zeros((s,), bool)
            key = np.array(self._state.key)  # writable host copy
            budget = np.zeros((s,), np.int32)
            greedy = np.ones((s,), bool)
            temp = np.ones((s,), np.float32)
            top_p = np.ones((s,), np.float32)
            eos = np.full((s,), -1, np.int32)
            for slot, req, plan, prefix_len, suffix in members:
                tokens[slot, : suffix.size] = suffix
                slens[slot] = suffix.size
                plens[slot] = prefix_len
                admit[slot] = True
                miss = 0
                if self._prefix is not None:
                    miss = (
                        self._prefix.matchable_blocks(int(req.tokens.size))
                        - plan["matched"]
                    )
                    self.metrics.counter("prefix_cache_hits").inc(
                        plan["matched"]
                    )
                    self.metrics.counter("prefix_cache_misses").inc(miss)
                self._admit_member_row(
                    slot, req, lb, key, budget, greedy, temp, top_p, eos,
                    journal_extra=dict(
                        prefix_len=int(prefix_len),
                        prefix_hit_blocks=int(plan["matched"]),
                        prefix_miss_blocks=int(miss),
                        new_blocks=int(plan["new"]),
                        wave=int(wave),
                    ),
                )
            with self.spans.dispatch(
                "prefill", bucket=int(lb), admitted=len(members),
                rids=[int(m[1].rid) for m in members],
            ) as sp:
                self._state = self._prefill_jit(
                    self.params,
                    self._state,
                    jnp.asarray(tokens),
                    jnp.asarray(slens),
                    jnp.asarray(plens),
                    jnp.asarray(admit),
                    jnp.asarray(self._host_tables),
                    jnp.asarray(key),
                    jnp.asarray(budget),
                    jnp.asarray(greedy),
                    jnp.asarray(temp),
                    jnp.asarray(top_p),
                    jnp.asarray(eos),
                )
                first = sp.fetch(self._state.last_tok)
            fin = np.asarray(self._state.finished)
            t_first = time.perf_counter()
            for slot, req, plan, prefix_len, suffix in members:
                # Prompt blocks were registered in the radix at
                # admission-plan time (wave scheduling above); their K/V
                # is valid as of this dispatch.
                self._record_first_token(slot, req, first, fin, t_first)

    def _admit_slab(self) -> None:
        free = self._free_slots()
        if not free or not self._queue:
            return
        batch: list[tuple[int, _Request]] = []
        while free and self._queue:
            batch.append((free.pop(0), self._queue.popleft()))
        by_bucket: dict[int, list[tuple[int, _Request]]] = {}
        for slot, req in batch:
            by_bucket.setdefault(
                self.bucket_for(req.tokens.size), []
            ).append((slot, req))
        s = self.slots
        for lb, members in sorted(by_bucket.items()):
            tokens = np.zeros((s, lb), np.int32)
            plens = np.ones((s,), np.int32)  # kv_lens must be >= 1
            admit = np.zeros((s,), bool)
            key = np.array(self._state.key)  # writable host copy
            budget = np.zeros((s,), np.int32)
            greedy = np.ones((s,), bool)
            temp = np.ones((s,), np.float32)
            top_p = np.ones((s,), np.float32)
            eos = np.full((s,), -1, np.int32)
            for slot, req in members:
                tokens[slot, : req.tokens.size] = req.tokens
                plens[slot] = req.tokens.size
                admit[slot] = True
                self._admit_member_row(
                    slot, req, lb, key, budget, greedy, temp, top_p, eos
                )
            with self.spans.dispatch(
                "prefill", bucket=int(lb), admitted=len(members),
                rids=[int(r.rid) for _, r in members],
            ) as sp:
                self._state = self._prefill_jit(
                    self.params,
                    self._state,
                    jnp.asarray(tokens),
                    jnp.asarray(plens),
                    jnp.asarray(admit),
                    jnp.asarray(key),
                    jnp.asarray(budget),
                    jnp.asarray(greedy),
                    jnp.asarray(temp),
                    jnp.asarray(top_p),
                    jnp.asarray(eos),
                )
                # The admission's first tokens come back with this fetch —
                # a real D2H value read, so it is also the execution
                # barrier (and what lets the dispatch span close).
                first = sp.fetch(self._state.last_tok)
            fin = np.asarray(self._state.finished)
            t_first = time.perf_counter()
            for slot, req in members:
                self._record_first_token(slot, req, first, fin, t_first)
        self.metrics.gauge("queue_depth").set(len(self._queue))

    def _release_slot(self, slot: int) -> None:
        """Return a slot (and, paged, its block references) to the free
        pool — the shared half of completion AND cancellation. Prefix-
        cached blocks keep the radix's own reference and stay resident
        for future hits."""
        self._slot_req[slot] = None
        if self.paged and self._slot_blocks[slot] is not None:
            for b in self._slot_blocks[slot]:
                self._alloc.release(b)
            self._slot_blocks[slot] = None
            self.metrics.gauge("kv_blocks_used").set(
                self._alloc.used_blocks
            )

    def _cancel(self, req: _Request, *, slot: int | None = None) -> None:
        """Cancel one overdue request at a chunk boundary. Resident
        requests free their slot/blocks (the device-side ``finished``
        flag masks the slot out of the next dispatch exactly as a normal
        completion would); queued requests just leave the queue. The
        structured ``request_cancelled`` event + counter is the record a
        router keys on — a cancelled request must never be resurrected
        by a failover retry."""
        req.cancelled = True
        if slot is not None:
            fin = np.asarray(self._state.finished).copy()
            fin[slot] = True
            self._state = self._state._replace(
                finished=self._commit_row(fin)
            )
            self._release_slot(slot)
        self.metrics.counter("cancellations_total").inc()
        self.journal.emit(
            "request_cancelled",
            rid=req.rid,
            trace=req.trace,
            resident=slot is not None,
            slot=None if slot is None else int(slot),
            tokens=len(req.out),
            age_s=round(time.perf_counter() - req.t_submit, 6),
        )

    def _hopeless(self, req: _Request, now: float) -> bool:
        """True when the request provably cannot finish: full remaining
        budget × the measured per-token EWMA exceeds the deadline slack.
        Conservative by construction — no measurement yet (or no
        deadline) never sheds, and the estimate ignores queue wait ahead
        of the request, so only truly unreachable deadlines trip it."""
        if req.deadline is None or self._tok_ewma is None:
            return False
        # Remaining budget, not max_new: a resumed decode leg already
        # carries leg 1's tokens (round 23) — its remaining work is
        # what the deadline must cover.
        remaining = req.config.max_new - len(req.out)
        return remaining * self._tok_ewma > req.deadline - now

    def _shed_overdue(self) -> None:
        """Queued-side deadline enforcement at the chunk boundary (round
        21): a queued request past its deadline — or provably unable to
        finish inside it — is SHED before any prefill dispatch is spent
        on it. Residents are the :meth:`_cancel_overdue` half."""
        now = time.perf_counter()
        if not any(
            r.deadline is not None
            and (now > r.deadline or self._hopeless(r, now))
            for r in self._queue
        ):
            return
        keep: deque[_Request] = deque()
        for req in self._queue:
            if req.deadline is not None and now > req.deadline:
                self._shed(req, reason="expired")
            elif self._hopeless(req, now):
                self._shed(req, reason="hopeless")
            else:
                keep.append(req)
        self._queue = keep
        self.metrics.gauge("queue_depth").set(len(self._queue))

    def _cancel_overdue(self) -> None:
        """Deadline enforcement at the chunk boundary: cancel RESIDENT
        requests whose ``deadline_s`` budget elapsed mid-generation
        (queued ones are shed instead — :meth:`_shed_overdue`)."""
        now = time.perf_counter()
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.deadline is not None and now > req.deadline:
                self._cancel(req, slot=slot)

    def _schedule(self) -> None:
        """Admission order (round 21): (priority class desc, earliest
        deadline first, submission order). When every queued request is
        priority 0 with no deadline the sort is skipped entirely — the
        queue stays the round-16 FIFO deque, untouched."""
        if all(r.priority == 0 and r.deadline is None for r in self._queue):
            return
        self._queue = deque(sorted(
            self._queue,
            key=lambda r: (
                -r.priority,
                math.inf if r.deadline is None else r.deadline,
                r.rid,
            ),
        ))

    def _finish(self, slot: int) -> None:
        req = self._slot_req[slot]
        if req is not None:
            req.done = True
            # Completion IS the block eviction: every reference this
            # request held returns before the next chunk boundary's
            # admissions.
            self._release_slot(slot)
            now = time.perf_counter()
            latency = now - req.t_submit
            self.metrics.counter("completions_total").inc()
            # A completion IS the slot eviction in this engine (no
            # preemptive eviction yet); counted under both names so the
            # scheduler-side math (admissions - evictions = occupancy)
            # reads naturally.
            self.metrics.counter("slot_evictions_total").inc()
            self.metrics.counter("tokens_generated_total").inc(len(req.out))
            self.metrics.histogram("request_latency_s").observe(latency)
            self.journal.emit(
                "completion",
                rid=req.rid,
                trace=req.trace,
                slot=int(slot),
                tokens=len(req.out),
                latency_s=round(latency, 6),
                ttft_s=round(
                    (req.t_first if req.t_first is not None else now)
                    - req.t_submit,
                    6,
                ),
            )

    def _spec_dispatch(self, occupied: int):
        """One speculative decode tick (replaces the chunk scan when
        ``spec_draft > 0``): host-side prompt-lookup drafts per GREEDY
        slot (``serve_pool.lookup_draft`` over the request's own
        prompt + generated stream — no draft model), then ONE batched
        verify dispatch (:meth:`_verify_graph`) that scores every draft
        position and emits ``accepted + 1`` tokens per slot. Sampled
        slots ride along at draft length 0 (one ordinary pick — their
        PRNG chain is untouchable by speculation). Draft length is
        capped at remaining budget MINUS ONE — a verify round emits at
        most ``accepted + 1`` tokens, so the last position of a
        full-budget draft could never be consumed — which also keeps
        verify writes inside the blocks reserved at admission.

        NOTE: on greedy ticks this replaces the chunk scan, so
        tokens/dispatch is bounded by ``spec_draft + 1`` — where the
        fixed dispatch cost dominates (the tunneled chip, small models)
        a large ``chunk`` can beat speculation outright; measure both
        (docs/serving.md §speculation)."""
        s, d1 = self.slots, self.spec_draft + 1
        suffix = np.zeros((s, d1), np.int32)
        slens = np.ones((s,), np.int32)
        proposed = 0
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            suffix[slot, 0] = req.out[-1]
            if req.config.greedy:
                cap = min(
                    self.spec_draft, req.config.max_new - len(req.out) - 1
                )
                if cap <= 0:
                    continue  # last budgeted token: drafting is wasted work
                ctx = np.concatenate(
                    [req.tokens, np.asarray(req.out, np.int32)]
                )
                d = lookup_draft(ctx, cap, self.spec_ngram)
                if d:
                    suffix[slot, 1 : 1 + len(d)] = d
                    slens[slot] = 1 + len(d)
                    proposed += len(d)
        with self.spans.dispatch(
            "spec_verify", draft=self.spec_draft, active=int(occupied),
            rids=[int(r.rid) for r in self._slot_req if r is not None],
        ) as sp:
            self._state, toks, valid = self._verify_jit(
                self.params,
                self._state,
                jnp.asarray(suffix),
                jnp.asarray(slens),
            )
            # D2H fetch = execution barrier (closes the span).
            toks = sp.fetch(toks)
        valid = np.asarray(valid)
        accepted = int(valid.sum()) - int(occupied)
        self.metrics.counter("spec_tokens_proposed").inc(proposed)
        self.metrics.counter("spec_tokens_accepted").inc(accepted)
        self.journal.emit(
            "spec_verify",
            proposed=int(proposed),
            accepted=int(accepted),
            emitted=int(valid.sum()),
            active=int(occupied),
        )
        return np.asarray(toks), valid

    def step(self) -> bool:
        """One engine tick: admit queued requests into free slots (per-
        bucket prefill dispatches), then — if any slot is mid-generation —
        ONE compiled ``chunk``-token decode dispatch, then collect
        finished requests so their slots free for the next tick's
        admissions. Returns True while there is work left.

        Chunk boundaries are also where the lifecycle levers act (round
        16): overdue requests are cancelled first (freeing their slots),
        a pending weight swap applies once the last old-weight resident
        has finished, and admission is skipped while draining or while a
        swap is pending — so residents ALWAYS complete under the weights
        they were admitted with (the parity contract is per-admission)."""
        self._last_tick = time.time()  # /healthz heartbeat: engine ticking
        self._shed_overdue()
        self._cancel_overdue()
        self._maybe_apply_swap()
        if not self._draining and self._pending_swap is None:
            self._schedule()
            self._admit()
        occupied = sum(r is not None for r in self._slot_req)
        self.metrics.gauge("slots_busy").set(occupied)
        if occupied:
            # Speculate only when a greedy slot is resident: sampled
            # slots ride verify dispatches at draft 0 (one token each),
            # so an all-sampled tick through the verify graph would pay
            # one dispatch PER TOKEN — fall back to the chunk scan and
            # keep its chunk-way amortization instead.
            spec = self.spec_draft and any(
                r is not None and r.config.greedy for r in self._slot_req
            )
            t_dispatch = time.perf_counter()
            if spec:
                toks, valid = self._spec_dispatch(occupied)
            else:
                with self.spans.dispatch(
                    "decode_chunk", chunk=self.chunk, active=int(occupied),
                    rids=[
                        int(r.rid) for r in self._slot_req if r is not None
                    ],
                ) as sp:
                    self._state, toks, valid = self._chunk_jit(
                        self.params, self._state
                    )
                    # D2H fetch = execution barrier (closes the span).
                    toks = sp.fetch(toks)
                valid = np.asarray(valid)
            fin = np.asarray(self._state.finished)
            emitted = 0
            for slot, req in enumerate(self._slot_req):
                if req is None:
                    continue
                picked = [int(t) for t in toks[valid[:, slot], slot]]
                req.out.extend(picked)
                emitted += len(picked)
                if fin[slot]:
                    self._finish(slot)
            # Per-token EWMA (round 21): one decode dispatch's wall time
            # over the tokens it emitted — the evidence the hopeless-shed
            # predicate runs on. EWMA (not last-sample) so one slow tick
            # (GC pause, cold path) cannot trigger a shed storm.
            if emitted:
                if self._tok_first_dispatch:
                    # Compile-bearing measurement: discard (see __init__).
                    self._tok_first_dispatch = False
                else:
                    inst = (time.perf_counter() - t_dispatch) / emitted
                    self._tok_ewma = (
                        inst if self._tok_ewma is None
                        else 0.8 * self._tok_ewma + 0.2 * inst
                    )
            # Re-read after _finish frees slots: the tick that completes
            # the last request must leave the gauge at 0 (an idle server
            # must not scrape as busy forever).
            self.metrics.gauge("slots_busy").set(
                sum(r is not None for r in self._slot_req)
            )
        return not self.idle()

    def idle(self) -> bool:
        return not self._queue and all(r is None for r in self._slot_req)

    # -- drain + live weight swap (round 16, docs/serving.md §fleet) -------

    def drain(self) -> None:
        """Graceful stop: close admission (``submit()`` raises from now
        on; queued-but-unadmitted requests stay queued for the caller to
        re-route) and run the engine until every RESIDENT request has
        finished. Idempotent — a second call returns immediately once
        the slots are empty. This is the graceful half of both failover
        (a replica told to retire finishes what it holds, loses nothing)
        and weight swap."""
        if not self._draining:
            self._draining = True
            self.journal.emit(
                "serve_drain",
                residents=sum(r is not None for r in self._slot_req),
                queued=len(self._queue),
            )
        while any(r is not None for r in self._slot_req):
            self.step()

    @property
    def draining(self) -> bool:
        return self._draining

    def request_swap(self, params, *, step=None, source=None) -> None:
        """Arm a live weight swap: ``params`` replaces the served tree at
        the first chunk boundary with NO residents (admission pauses
        until then, so every request completes under the weights it was
        admitted with — the parity contract is per-admission). Nothing
        recompiles: params are runtime arguments of every compiled
        graph. ``decode_matmul_dtype`` re-quantizes the incoming tree,
        keeping the weight-only discipline across swaps."""
        if self.decode_matmul_dtype is not None:
            params = self.model.decode_weights(
                params, self.decode_matmul_dtype
            )
        self._pending_swap = (params, step, source)
        self.journal.emit(
            "weight_swap_requested",
            step=None if step is None else int(step),
            source=source,
        )
        self._maybe_apply_swap()  # an idle server swaps immediately

    def swap_from_checkpoint(
        self, checkpoint_dir: str | None = None, *, optimizer=None
    ) -> int | None:
        """Adopt the newest CRC-verified checkpoint under
        ``checkpoint_dir`` (default: the directory this server restored
        from) if it is NEWER than the served step — the serving end of
        the train→publish→serve loop (a DiLoCo trainer keeps
        checkpointing; replicas pick the steps up without dropping a
        single resident). Returns the adopted step, or None when there
        is nothing newer (no swap armed). Restores through
        :func:`canonical_lm_params`, so any training layout publishes."""
        d = checkpoint_dir or self.checkpoint_dir
        if d is None:
            raise ValueError(
                "no checkpoint_dir: construct via from_checkpoint or pass "
                "one explicitly"
            )
        opt = optimizer if optimizer is not None else self._restore_optimizer
        params, step = canonical_lm_params(self.model, d, optimizer=opt)
        if self.checkpoint_step is not None and step <= self.checkpoint_step:
            return None
        self.checkpoint_dir = d
        self.request_swap(params, step=int(step), source=d)
        return int(step)

    def _maybe_apply_swap(self) -> None:
        if self._pending_swap is None:
            return
        if any(r is not None for r in self._slot_req):
            return  # old-weight residents still decoding: wait
        params, step, source = self._pending_swap
        self._pending_swap = None
        old = self.checkpoint_step
        self.params = params
        if self._prefix is not None:
            # The radix caches K/V computed under the OLD weights; a
            # post-swap prefix hit would splice stale keys into a
            # new-weights stream and silently break the parity contract.
            # No residents exist here, so every cached block is
            # cache-only (refcount 1) and evictable — flush them all.
            self._prefix.evict(self._prefix.evictable_blocks())
            self.metrics.gauge("kv_blocks_used").set(
                self._alloc.used_blocks
            )
        if step is not None:
            self.checkpoint_step = int(step)
        self.metrics.counter("weight_swaps_total").inc()
        self.journal.emit(
            "weight_swap",
            step=None if step is None else int(step),
            from_step=old,
            source=source,
        )

    def health(self) -> dict:
        """The /healthz payload: engine heartbeat age (seconds since the
        last ``step()`` tick — an idle-but-alive server reads old, a
        wedged one reads ancient; the scraper applies the SLO), the
        occupancy the admission controller sees, and the round-16
        routing signals (queue saturation, draining, swap state, served
        checkpoint step)."""
        return {
            "heartbeat_age_s": round(time.time() - self._last_tick, 3),
            "slots_busy": sum(r is not None for r in self._slot_req),
            "slots": self.slots,
            "queue_depth": len(self._queue),
            "queue_limit": self.queue_limit,
            "queue_saturation": (
                round(len(self._queue) / self.queue_limit, 3)
                if self.queue_limit
                else 0.0
            ),
            "draining": self._draining,
            "swap_pending": self._pending_swap is not None,
            "checkpoint_step": self.checkpoint_step,
            "kv_blocks_free": (
                self._alloc.free_blocks if self._alloc is not None else None
            ),
        }

    def shutdown(self) -> None:
        """Graceful stop: :meth:`drain` (admission closed, residents
        finished — nothing in flight is dropped), then stop the live
        exporter (if armed). The engine itself holds no threads — jit
        caches and device state die with the object."""
        self.drain()
        if self.exporter is not None:
            self.exporter.stop()
            self.exporter = None

    def done(self, rid: int) -> bool:
        """True once the request reached a terminal state (finished,
        cancelled, or shed) — the poll half of the submit/step/result
        cycle a replica worker loop drives."""
        req = self._results[rid]
        return req.done or req.cancelled or req.shed

    def result(self, rid: int) -> np.ndarray:
        """Generated tokens of a finished request (prompt excluded).
        Consumes the record — a second read raises — so a long-lived
        server does not accumulate every request it ever served. A
        deadline-cancelled request raises :class:`RequestCancelled`, a
        shed one :class:`RequestShed` (record consumed either way)."""
        req = self._results[rid]
        if req.shed:
            del self._results[rid]
            raise RequestShed(
                f"request {rid} was shed before prefill (deadline "
                "unreachable or displaced under saturation)"
            )
        if req.cancelled:
            del self._results[rid]
            raise RequestCancelled(
                f"request {rid} was cancelled at a chunk boundary "
                "(deadline exceeded)"
            )
        if req.migrated:
            # NOT consumed: take_export() owns this record — result()
            # must not destroy the payload a confused caller probed.
            raise RuntimeError(
                f"request {rid} migrated — take_export() owns its "
                "payload; the decode leg's result is the stream"
            )
        if not req.done:
            raise RuntimeError(f"request {rid} is not finished")
        del self._results[rid]
        return np.asarray(req.out, np.int32)

    # -- convenience entries ----------------------------------------------

    def generate(
        self, prompts, configs: GenerationConfig | list | None = None
    ) -> list[np.ndarray]:
        """Serve a batch of token prompts to completion; returns each
        request's generated tokens in submission order."""
        if configs is None or isinstance(configs, GenerationConfig):
            configs = [configs] * len(prompts)
        rids = [
            self.submit(p, c) for p, c in zip(prompts, configs, strict=True)
        ]
        while self.step():
            pass
        return [self.result(r) for r in rids]

    def serve_text(self, texts: list[str], **gen_kwargs) -> list[str]:
        """Text in → text out: encode with the served tokenizer, generate,
        decode (EOS and padding drop out in ``tokenizer.decode``). By
        default requests stop at the tokenizer's EOS id."""
        if self.tokenizer is None:
            raise ValueError("no tokenizer attached (pass one, or use "
                             "from_checkpoint with a shipped tokenizer.json)")
        gen_kwargs.setdefault("eos_id", self.tokenizer.eos_id)
        cfg = GenerationConfig(**gen_kwargs)
        prompts = [self.tokenizer.encode(t) for t in texts]
        return self.tokenizer.decode_batch(self.generate(prompts, cfg))
