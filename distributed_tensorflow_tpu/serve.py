"""Batched LM serving: compiled prefill+decode with continuous batching.

The reference's only "inference" was the in-loop eval fetch
(reference tfsingle.py:94); the classifier side of this framework got
``inference.py::Predictor`` (fixed-shape compiled prediction). This module
is the LM analog — text in, text out, from a checkpoint directory — built
from the pieces rounds 5-8 left on the table: the cross-topology canonical
restore (``step_N.layout.json`` sidecars), the ``tokenizer.json`` the
LMTrainer ships into ``checkpoint_dir``, and the unrolled-layer KV-cache
decode step. Three serving-engine ideas, adapted to one tunneled TPU
(~20-40 ms/dispatch, ~100 ms per host round-trip — CLAUDE.md):

- **Bucketed prefill** (vLLM-style fixed shapes): prompts are padded to a
  small set of length buckets and prefilled BATCHED across the server's
  fixed request slots with ragged ``kv_lens`` masking
  (``GPTLM.prefill_slots``), so the compile count is ``len(buckets)``, not
  one per prompt length.
- **Multi-token decode chunks**: ``chunk`` decode steps — including the
  sampling — run as ONE ``lax.scan`` dispatch (``GPTLM.decode_slots`` per
  step, in-graph greedy/temperature/nucleus picks, per-slot EOS/budget
  tracking), so the ~100 ms tunnel round-trip is paid once per ``chunk``
  tokens instead of once per token. This is the environment-specific lever:
  on-chip the scan also removes per-step dispatch latency, through the
  tunnel it removes a 100 ms round-trip per token.
- **Continuous batching** (Orca-style): a slot scheduler admits queued
  requests into freed slots at chunk boundaries — each slot is an
  independent request at its own position (``SlotKVCache`` carries per-slot
  lengths), so throughput never drains to the longest request in a static
  batch.

Parity contract (pinned in tests/test_serve.py): for every request, the
served token stream equals the in-process single-prompt
``GPTLM.greedy_decode`` / ``sample_decode(key=jax.random.key(seed))``
stream token for token — generation is batch-invariant, so a request's
output does not depend on what shared the batch with it.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.gpt import GPTLM, GPTLMParams
from distributed_tensorflow_tpu.observability import journal as obs_journal
from distributed_tensorflow_tpu.observability.metrics import MetricsRegistry
from distributed_tensorflow_tpu.observability.spans import SpanRecorder


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Per-request decoding knobs. ``greedy=True`` (default) reproduces
    ``GPTLM.greedy_decode``; ``greedy=False`` reproduces
    ``sample_decode(key=jax.random.key(seed), temperature=, top_p=)``
    (nucleus sampling; ``top_p=1.0`` keeps the whole distribution).
    ``eos_id`` stops a request early once emitted (the EOS token itself is
    included in the output); None generates exactly ``max_new`` tokens."""

    max_new: int = 64
    greedy: bool = True
    temperature: float = 1.0
    top_p: float = 1.0
    seed: int = 0
    eos_id: int | None = None

    def validate(self, vocab_size: int) -> None:
        if self.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {self.max_new}")
        if self.temperature <= 0:
            raise ValueError(
                f"temperature must be > 0, got {self.temperature}"
            )
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.eos_id is not None and not 0 <= self.eos_id < vocab_size:
            raise ValueError(
                f"eos_id must be in [0, {vocab_size}), got {self.eos_id}"
            )


# -- checkpoint loading (the round-5 canonical layer, params-only) ---------


def canonical_lm_params(
    model: GPTLM, checkpoint_dir: str, *, optimizer=None
) -> tuple[GPTLMParams, int]:
    """Restore the newest valid checkpoint under ``checkpoint_dir`` written
    by :class:`~train.lm_trainer.LMTrainer` in ANY mode layout, and return
    ``(dense canonical params, step)`` — the serving-side half of the
    round-5 cross-topology contract: the ``step_N.layout.json`` sidecar
    names the source layout, pipeline checkpoints unstage their
    [S, L/S, ...] block stacks back to [L, ...], async checkpoints merge
    their per-replica copies at the mean (integer leaves take replica 0 —
    ``merge_replica_leaf``), and the dense family restores as-is.

    ``optimizer`` must match the training optimizer (the checkpoint stores
    its slots; orbax fails loudly on a structure mismatch); defaults to
    the reference SGD whose slot state is empty."""
    from distributed_tensorflow_tpu.ops import optim as optim_lib
    from distributed_tensorflow_tpu.parallel.strategy import TrainState
    from distributed_tensorflow_tpu.train import supervisor as _sup

    probe = _sup.latest_checkpoint_step(checkpoint_dir)
    if probe is None:
        raise FileNotFoundError(f"no checkpoint under {checkpoint_dir}")
    if not _sup._HAVE_ORBAX:
        raise RuntimeError(
            f"checkpoint found under {checkpoint_dir} but orbax is not"
            " importable; cannot restore"
        )
    sup = _sup.Supervisor(checkpoint_dir=checkpoint_dir)
    step = sup.newest_restorable_step()
    if step is None:
        raise RuntimeError(
            f"no restorable checkpoint under {checkpoint_dir} (all steps "
            "fail manifest verification)"
        )
    optimizer = optimizer or optim_lib.sgd(0.001)
    meta = sup.saved_layout(step) or {}
    mode = meta.get("mode", "single")

    params = jax.eval_shape(lambda: model.init(seed=0))
    if mode == "pp":
        from distributed_tensorflow_tpu.models.gpt import (
            pipeline_stage_params,
        )

        params = jax.eval_shape(
            lambda p: pipeline_stage_params(model, p, meta["stages"]), params
        )
    opt = jax.eval_shape(optimizer.init, params)
    step_leaf = jax.ShapeDtypeStruct((), jnp.int32)
    if mode == "async":
        n = int(meta["replicas"])
        stack = lambda t: jax.tree.map(  # noqa: E731
            lambda s: jax.ShapeDtypeStruct((n,) + s.shape, s.dtype), t
        )
        abstract = TrainState(stack(params), stack(opt), step_leaf)
    else:
        abstract = TrainState(params, opt, step_leaf)
    # eval_shape structs carry sharding=None, which some orbax vintages
    # cannot normalize — pin every leaf to the default device explicitly.
    dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    abstract = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=dev),
        abstract,
    )
    state = sup.restore_raw(step, abstract)

    if mode == "async":
        from distributed_tensorflow_tpu.parallel.strategy import (
            merge_replica_leaf,
        )

        served = jax.tree.map(merge_replica_leaf, state.params)
    elif mode == "pp":
        served = state.params._replace(
            blocks=jax.tree.map(
                lambda a: a.reshape((-1,) + a.shape[2:]), state.params.blocks
            )
        )
    else:
        served = state.params
    return served, step


def load_tokenizer(checkpoint_dir: str):
    """The vocab that produced the checkpoint's token ids:
    ``tokenizer.json`` (the record LMTrainer ships) when present, else the
    byte-level identity tokenizer (trainings that never passed one)."""
    from distributed_tensorflow_tpu.data.text import (
        BPETokenizer,
        ByteTokenizer,
    )

    path = os.path.join(checkpoint_dir, "tokenizer.json")
    if os.path.exists(path):
        return BPETokenizer.load(path)
    return ByteTokenizer()


# -- the engine ------------------------------------------------------------


class _DecodeState(NamedTuple):
    """Device-resident per-slot serving state, one pytree so every
    prefill/chunk dispatch carries it whole. PRNG keys ride as raw
    ``key_data`` (uint32) — jnp.where composes on those."""

    k: jax.Array  # [layers, S, C, Hkv, Dh]
    v: jax.Array
    lengths: jax.Array  # [S] i32 — tokens written into each slot's cache
    last_tok: jax.Array  # [S] i32 — most recent token (next decode input)
    key: jax.Array  # [S, ...] u32 — per-slot PRNG key data
    emitted: jax.Array  # [S] i32 — generated tokens so far
    budget: jax.Array  # [S] i32 — max_new for the resident request
    finished: jax.Array  # [S] bool — True: slot idle (done or vacant)
    greedy: jax.Array  # [S] bool
    temp: jax.Array  # [S] f32
    top_p: jax.Array  # [S] f32
    eos: jax.Array  # [S] i32 — -1: no EOS stop


class _Request:
    __slots__ = (
        "rid", "tokens", "config", "out", "done",
        "t_submit", "t_admit", "t_first",
    )

    def __init__(self, rid, tokens, config):
        self.rid = rid
        self.tokens = tokens
        self.config = config
        self.out: list[int] = []
        self.done = False
        self.t_submit = time.perf_counter()
        self.t_admit = None  # set at slot admission
        self.t_first = None  # set when the first token lands (TTFT)


class TextServer:
    """Continuous-batching text server over a fixed bank of request slots.

    Construct from live params or :meth:`from_checkpoint`; submit requests
    (:meth:`submit` / :meth:`generate` / :meth:`serve_text`) and drive the
    engine with :meth:`step` (one admission round + one compiled
    ``chunk``-token decode dispatch) until :meth:`idle`.

    Compiled shapes: one prefill executable per length bucket (shared
    jitted function, shape-keyed) and ONE decode-chunk executable serving
    every occupancy pattern — finished/vacant slots ride along masked, so
    admission order and slot churn never recompile anything."""

    def __init__(
        self,
        model: GPTLM,
        params: GPTLMParams,
        tokenizer=None,
        *,
        slots: int = 8,
        buckets: tuple[int, ...] | None = None,
        chunk: int = 32,
        journal=None,
        metrics: MetricsRegistry | None = None,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.model = model
        self.params = params
        self.tokenizer = tokenizer
        self.slots = slots
        self.chunk = chunk
        # Serving telemetry (round 10, observability/): admissions and
        # completions as journal events (rid, TTFT, latency, tokens),
        # queue/occupancy gauges + latency histograms in the registry,
        # and every prefill/chunk dispatch as a host span closed by the
        # scheduler's own D2H token fetch. Defaults are no-ops.
        self.journal = journal if journal is not None else obs_journal.get_journal()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.spans = SpanRecorder(journal=self.journal)
        if buckets is None:
            # Doubling buckets up to max_len-1 (a prompt always leaves at
            # least one position of generation room): 16, 32, ... — small
            # enough a handful of executables covers everything.
            buckets, b = [], 16
            while b < model.max_len:
                buckets.append(min(b, model.max_len - 1))
                b *= 2
            if not buckets or buckets[-1] != model.max_len - 1:
                buckets.append(model.max_len - 1)
        buckets = tuple(sorted(set(int(b) for b in buckets)))
        if buckets[0] < 1 or buckets[-1] > model.max_len:
            raise ValueError(
                f"buckets must lie in [1, max_len={model.max_len}]: {buckets}"
            )
        self.buckets = buckets
        self._queue: deque[_Request] = deque()
        self._slot_req: list[_Request | None] = [None] * slots
        self._next_rid = 0
        self._results: dict[int, _Request] = {}
        self._state = self._init_state()
        self._prefill_jit = jax.jit(self._prefill_graph)
        self._chunk_jit = jax.jit(self._chunk_graph)

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_checkpoint(
        cls,
        model: GPTLM,
        checkpoint_dir: str,
        *,
        optimizer=None,
        tokenizer=None,
        **kw,
    ) -> "TextServer":
        """Serve the newest valid checkpoint in ``checkpoint_dir`` — any
        mode layout (:func:`canonical_lm_params`), with the shipped
        ``tokenizer.json`` unless an explicit tokenizer is passed."""
        params, _ = canonical_lm_params(
            model, checkpoint_dir, optimizer=optimizer
        )
        tok = tokenizer if tokenizer is not None else load_tokenizer(
            checkpoint_dir
        )
        return cls(model, params, tok, **kw)

    # -- compiled graphs ---------------------------------------------------

    def _init_state(self) -> _DecodeState:
        cache = self.model.empty_slot_cache(self.slots)
        s = self.slots
        kd = jax.random.key_data(jax.random.split(jax.random.key(0), s))
        return _DecodeState(
            k=cache.k,
            v=cache.v,
            lengths=cache.lengths,
            last_tok=jnp.zeros((s,), jnp.int32),
            key=kd,
            emitted=jnp.zeros((s,), jnp.int32),
            budget=jnp.zeros((s,), jnp.int32),
            finished=jnp.ones((s,), bool),  # vacant == finished
            greedy=jnp.ones((s,), bool),
            temp=jnp.ones((s,), jnp.float32),
            top_p=jnp.ones((s,), jnp.float32),
            eos=jnp.full((s,), -1, jnp.int32),
        )

    def _pick(self, logits, key_data, greedy, temp, top_p):
        """Per-slot next-token pick, the exact arithmetic of
        ``GPTLM.{greedy,sample}_decode``'s pick closures (greedy: argmax of
        the raw logits; sampled: f32/temperature, nucleus keep-mask by
        EXCLUSIVE cumulative probability, categorical) — vmapped per row
        with per-slot knobs. ``top_p=1.0`` keeps every token, making the
        nucleus branch the identity, and the categorical runs at [1, V] so
        its noise bits match the in-process B=1 call exactly (the parity
        contract)."""

        amax = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def row(lg, kd, t, p):
            lt = lg.astype(jnp.float32) / t
            order = jnp.argsort(lt)[::-1]
            sorted_l = lt[order]
            probs = jax.nn.softmax(sorted_l)
            keep_sorted = jnp.cumsum(probs) - probs < p
            keep = jnp.zeros(lt.shape, bool).at[order].set(keep_sorted)
            lt = jnp.where(keep, lt, -jnp.inf)
            return jax.random.categorical(
                jax.random.wrap_key_data(kd), lt[None, :], axis=-1
            )[0].astype(jnp.int32)

        def mixed(_):
            sampled = jax.vmap(row)(logits, key_data, temp, top_p)
            return jnp.where(greedy, amax, sampled)

        # Greedy-only banks (the default config) skip the full-vocab
        # sort/softmax/gumbel machinery entirely — it is O(V log V) per
        # slot per token in the hot chunk graph, and jnp.where alone
        # would still evaluate it.
        return jax.lax.cond(jnp.all(greedy), lambda _: amax, mixed, None)

    def _split_keys(self, key_data):
        """Per-slot ``key, sub = jax.random.split(key)`` on key-data rows —
        the exact chain ``GPTLM._decode_loop`` advances per request."""

        def row(kd):
            nxt = jax.random.split(jax.random.wrap_key_data(kd))
            return (
                jax.random.key_data(nxt[0]),
                jax.random.key_data(nxt[1]),
            )

        carried, sub = jax.vmap(row)(key_data)
        return carried, sub

    def _cache(self, st: _DecodeState):
        from distributed_tensorflow_tpu.models.gpt import SlotKVCache

        return SlotKVCache(k=st.k, v=st.v, lengths=st.lengths)

    def _prefill_graph(
        self, params, st, tokens, plens, admit, key, budget, greedy, temp,
        top_p, eos,
    ):
        """One admission round: ragged batched prefill into admitted slots
        + the first sampled token per admitted request (the pick
        ``_decode_loop`` makes from the prefill logits), all in-graph."""
        logits, cache = self.model.prefill_slots(
            params, self._cache(st), tokens, plens, admit
        )
        keys = jnp.where(admit[:, None], key, st.key)
        carried, sub = self._split_keys(keys)
        first = self._pick(logits, sub, greedy, temp, top_p)
        sel = lambda n, o: jnp.where(admit, n, o)  # noqa: E731
        eos_eff = sel(eos, st.eos)
        fin = sel(
            (first == eos_eff) | (budget <= 1), st.finished
        )
        return st._replace(
            k=cache.k,
            v=cache.v,
            lengths=cache.lengths,
            last_tok=sel(first, st.last_tok),
            key=jnp.where(admit[:, None], carried, st.key),
            emitted=sel(jnp.ones_like(st.emitted), st.emitted),
            budget=sel(budget, st.budget),
            finished=fin,
            greedy=sel(greedy, st.greedy),
            temp=jnp.where(admit, temp, st.temp),
            top_p=jnp.where(admit, top_p, st.top_p),
            eos=eos_eff,
        )

    def _chunk_graph(self, params, st):
        """``chunk`` decode steps as one ``lax.scan``: per step every
        unfinished slot advances one token (decode + in-graph pick),
        finished/vacant slots ride along masked. Returns the new state
        plus the [chunk, S] token block and its validity mask — the only
        per-chunk host traffic."""
        max_len = self.model.max_len

        def body(st, _):
            act = ~st.finished & (st.lengths < max_len)
            logits, cache = self.model.decode_slots(
                params, st.last_tok, self._cache(st), active=act
            )
            carried, sub = self._split_keys(st.key)
            nxt = self._pick(logits, sub, st.greedy, st.temp, st.top_p)
            nxt = jnp.where(act, nxt, st.last_tok)
            emitted = st.emitted + act.astype(jnp.int32)
            fin = st.finished | (
                act
                & (
                    (nxt == st.eos)
                    | (emitted >= st.budget)
                    | (cache.lengths >= max_len)
                )
            )
            st = st._replace(
                k=cache.k,
                v=cache.v,
                lengths=cache.lengths,
                last_tok=nxt,
                key=jnp.where(act[:, None], carried, st.key),
                emitted=emitted,
                finished=fin,
            )
            return st, (nxt, act)

        st, (toks, valid) = jax.lax.scan(
            body, st, None, length=self.chunk
        )
        return st, toks, valid

    # -- the scheduler (host side) -----------------------------------------

    def submit(self, tokens, config: GenerationConfig | None = None) -> int:
        """Queue one request (prompt as a 1-D int token array). Returns a
        request id for :meth:`result`. Validates against the bucket/cache
        geometry up front: the prompt must fit a bucket and
        ``len + max_new`` must fit ``max_len`` (the KV cache is the slot's
        whole memory — vLLM's fixed-slot discipline)."""
        config = config or GenerationConfig()
        config.validate(self.model.vocab_size)
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size < 1:
            raise ValueError("empty prompt")
        if tokens.size > self.buckets[-1]:
            raise ValueError(
                f"prompt length {tokens.size} exceeds the largest bucket "
                f"{self.buckets[-1]}"
            )
        if tokens.size + config.max_new > self.model.max_len:
            raise ValueError(
                f"prompt {tokens.size} + max_new {config.max_new} exceeds "
                f"max_len {self.model.max_len}"
            )
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, tokens, config)
        self._queue.append(req)
        self._results[rid] = req
        self.metrics.counter("requests_submitted_total").inc()
        self.metrics.gauge("queue_depth").set(len(self._queue))
        return rid

    def bucket_for(self, length: int) -> int:
        """Smallest bucket holding a ``length``-token prompt."""
        for b in self.buckets:
            if length <= b:
                return b
        raise ValueError(
            f"prompt length {length} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )

    def _free_slots(self) -> list[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _admit(self) -> None:
        """Move queued requests into free slots; one prefill dispatch per
        length bucket among this round's admissions."""
        free = self._free_slots()
        if not free or not self._queue:
            return
        batch: list[tuple[int, _Request]] = []
        while free and self._queue:
            batch.append((free.pop(0), self._queue.popleft()))
        by_bucket: dict[int, list[tuple[int, _Request]]] = {}
        for slot, req in batch:
            by_bucket.setdefault(
                self.bucket_for(req.tokens.size), []
            ).append((slot, req))
        s = self.slots
        for lb, members in sorted(by_bucket.items()):
            tokens = np.zeros((s, lb), np.int32)
            plens = np.ones((s,), np.int32)  # kv_lens must be >= 1
            admit = np.zeros((s,), bool)
            key = np.array(self._state.key)  # writable host copy
            budget = np.zeros((s,), np.int32)
            greedy = np.ones((s,), bool)
            temp = np.ones((s,), np.float32)
            top_p = np.ones((s,), np.float32)
            eos = np.full((s,), -1, np.int32)
            for slot, req in members:
                c = req.config
                tokens[slot, : req.tokens.size] = req.tokens
                plens[slot] = req.tokens.size
                admit[slot] = True
                key[slot] = np.asarray(
                    jax.random.key_data(jax.random.key(c.seed))
                )
                budget[slot] = c.max_new
                greedy[slot] = c.greedy
                temp[slot] = c.temperature
                top_p[slot] = c.top_p
                eos[slot] = -1 if c.eos_id is None else c.eos_id
                self._slot_req[slot] = req
                req.t_admit = time.perf_counter()
                self.metrics.counter("admissions_total").inc()
                self.journal.emit(
                    "admission",
                    rid=req.rid,
                    slot=int(slot),
                    bucket=int(lb),
                    prompt_len=int(req.tokens.size),
                    queue_wait_s=round(req.t_admit - req.t_submit, 6),
                )
            with self.spans.dispatch(
                "prefill", bucket=int(lb), admitted=len(members)
            ) as sp:
                self._state = self._prefill_jit(
                    self.params,
                    self._state,
                    jnp.asarray(tokens),
                    jnp.asarray(plens),
                    jnp.asarray(admit),
                    jnp.asarray(key),
                    jnp.asarray(budget),
                    jnp.asarray(greedy),
                    jnp.asarray(temp),
                    jnp.asarray(top_p),
                    jnp.asarray(eos),
                )
                # The admission's first tokens come back with this fetch —
                # a real D2H value read, so it is also the execution
                # barrier (and what lets the dispatch span close).
                first = sp.fetch(self._state.last_tok)
            fin = np.asarray(self._state.finished)
            t_first = time.perf_counter()
            for slot, req in members:
                req.t_first = t_first
                self.metrics.histogram("ttft_s").observe(
                    t_first - req.t_submit
                )
                req.out.append(int(first[slot]))
                if fin[slot]:
                    self._finish(slot)
        self.metrics.gauge("queue_depth").set(len(self._queue))

    def _finish(self, slot: int) -> None:
        req = self._slot_req[slot]
        if req is not None:
            req.done = True
            self._slot_req[slot] = None
            now = time.perf_counter()
            latency = now - req.t_submit
            self.metrics.counter("completions_total").inc()
            # A completion IS the slot eviction in this engine (no
            # preemptive eviction yet); counted under both names so the
            # scheduler-side math (admissions - evictions = occupancy)
            # reads naturally.
            self.metrics.counter("slot_evictions_total").inc()
            self.metrics.counter("tokens_generated_total").inc(len(req.out))
            self.metrics.histogram("request_latency_s").observe(latency)
            self.journal.emit(
                "completion",
                rid=req.rid,
                slot=int(slot),
                tokens=len(req.out),
                latency_s=round(latency, 6),
                ttft_s=round(
                    (req.t_first if req.t_first is not None else now)
                    - req.t_submit,
                    6,
                ),
            )

    def step(self) -> bool:
        """One engine tick: admit queued requests into free slots (per-
        bucket prefill dispatches), then — if any slot is mid-generation —
        ONE compiled ``chunk``-token decode dispatch, then collect
        finished requests so their slots free for the next tick's
        admissions. Returns True while there is work left."""
        self._admit()
        occupied = sum(r is not None for r in self._slot_req)
        self.metrics.gauge("slots_busy").set(occupied)
        if occupied:
            with self.spans.dispatch("decode_chunk", chunk=self.chunk) as sp:
                self._state, toks, valid = self._chunk_jit(
                    self.params, self._state
                )
                # D2H fetch = execution barrier (closes the span).
                toks = sp.fetch(toks)
            valid = np.asarray(valid)
            fin = np.asarray(self._state.finished)
            for slot, req in enumerate(self._slot_req):
                if req is None:
                    continue
                req.out.extend(int(t) for t in toks[valid[:, slot], slot])
                if fin[slot]:
                    self._finish(slot)
            # Re-read after _finish frees slots: the tick that completes
            # the last request must leave the gauge at 0 (an idle server
            # must not scrape as busy forever).
            self.metrics.gauge("slots_busy").set(
                sum(r is not None for r in self._slot_req)
            )
        return not self.idle()

    def idle(self) -> bool:
        return not self._queue and all(r is None for r in self._slot_req)

    def result(self, rid: int) -> np.ndarray:
        """Generated tokens of a finished request (prompt excluded).
        Consumes the record — a second read raises — so a long-lived
        server does not accumulate every request it ever served."""
        req = self._results[rid]
        if not req.done:
            raise RuntimeError(f"request {rid} is not finished")
        del self._results[rid]
        return np.asarray(req.out, np.int32)

    # -- convenience entries ----------------------------------------------

    def generate(
        self, prompts, configs: GenerationConfig | list | None = None
    ) -> list[np.ndarray]:
        """Serve a batch of token prompts to completion; returns each
        request's generated tokens in submission order."""
        if configs is None or isinstance(configs, GenerationConfig):
            configs = [configs] * len(prompts)
        rids = [
            self.submit(p, c) for p, c in zip(prompts, configs, strict=True)
        ]
        while self.step():
            pass
        return [self.result(r) for r in rids]

    def serve_text(self, texts: list[str], **gen_kwargs) -> list[str]:
        """Text in → text out: encode with the served tokenizer, generate,
        decode (EOS and padding drop out in ``tokenizer.decode``). By
        default requests stop at the tokenizer's EOS id."""
        if self.tokenizer is None:
            raise ValueError("no tokenizer attached (pass one, or use "
                             "from_checkpoint with a shipped tokenizer.json)")
        gen_kwargs.setdefault("eos_id", self.tokenizer.eos_id)
        cfg = GenerationConfig(**gen_kwargs)
        prompts = [self.tokenizer.encode(t) for t in texts]
        return self.tokenizer.decode_batch(self.generate(prompts, cfg))
