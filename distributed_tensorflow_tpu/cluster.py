"""Cluster bootstrap: the reference's L1 layer, TPU-native (C2, C3, C5).

Reference behavior being reproduced (SURVEY.md §1 L1, §3.2):

- ``tf.app.flags`` ``--job_name={ps,worker} --task_index=N`` select this
  process's role and rank (reference tfdist_between.py:11-13);
- ``tf.train.ClusterSpec({"ps": ..., "worker": ...})`` +
  ``tf.train.Server(...)`` start a per-process gRPC server
  (reference tfdist_between.py:9,17);
- ps processes block forever in ``server.join()``
  (reference tfdist_between.py:27-29).

TPU-native mapping: there is no parameter server and no per-tensor RPC
transport. ``worker_svrs`` entries become processes in a
``jax.distributed`` coordination group (entry 0 is the coordinator), the
global device mesh spans all processes' chips, and all communication is XLA
collectives over ICI/DCN. The ``ps`` role is accepted for CLI compatibility
and resolves to an explanatory no-op: a launcher script that starts
``--job_name=ps`` tasks keeps working, the ps task simply exits cleanly
instead of serving (its function — holding shared parameters — moved onto
the chips).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
from typing import Sequence

import jax

from distributed_tensorflow_tpu.config import ClusterConfig


def define_flags(parser: argparse.ArgumentParser | None = None) -> argparse.ArgumentParser:
    """The reference CLI (C2): ``--job_name`` / ``--task_index``."""
    parser = parser or argparse.ArgumentParser(
        description="distributed_tensorflow_tpu launcher"
    )
    parser.add_argument(
        "--job_name",
        type=str,
        default="worker",
        choices=("ps", "worker"),
        help="Role of this process. 'ps' is accepted for compatibility and "
        "no-ops: parameters live on the chips (no parameter server on TPU).",
    )
    parser.add_argument(
        "--task_index",
        type=int,
        default=0,
        help="Rank of this process within its job (0 = chief/coordinator).",
    )
    return parser


@dataclasses.dataclass(frozen=True)
class ProcessContext:
    """What bootstrap resolves for this process."""

    job_name: str
    task_index: int
    num_processes: int
    is_chief: bool
    is_ps: bool
    heartbeat: object | None = None  # chief: HeartbeatCoordinator; worker: HeartbeatWorker
    # Chief only: its own loopback HeartbeatWorker (the coordinator tracks
    # all n tasks incl. task 0, and never-seen tasks count failed after the
    # grace period — the chief must therefore report too).
    heartbeat_sender: object | None = None

    @property
    def should_exit(self) -> bool:
        return self.is_ps

    def report_progress(self, progress: int) -> None:
        """Advance the monotonic progress counter carried by this process's
        heartbeats (trainers call this at epoch boundaries with the global
        step — train/supervisor.py::report_progress). The counter is what
        lets the detector tell *stalled* from *dead*: a rank hung in a
        collective keeps beating from its native sender thread, but its
        counter freezes. No-op when no sender is armed."""
        for h in (self.heartbeat_sender, self.heartbeat):
            if h is not None and hasattr(h, "set_progress"):
                h.set_progress(progress)
                return

    def close(self) -> None:
        """Stop the native heartbeat threads (coordinator or sender, plus
        the chief's loopback sender). Idempotent; without this a library
        embedding that outlives training would keep UDP threads running and
        hold the port against a later bootstrap."""
        for h in (self.heartbeat, self.heartbeat_sender):
            if h is not None:
                h.stop()


class BootstrapError(RuntimeError):
    """jax.distributed.initialize failed every bounded attempt."""


def bounded_initialize(
    cluster: ClusterConfig,
    task_index: int,
    *,
    timeout_s: int | None = None,
    attempts: int | None = None,
    backoff: float = 1.0,
    initialize_fn=None,
    shutdown_fn=None,
    sleep=None,
    print_fn=print,
) -> None:
    """``jax.distributed.initialize`` under a bounded timeout + bounded
    retry-with-backoff (resilience.retry, jittered so a restarting gang's
    rendezvous attempts de-synchronize).

    The raw call blocks until ``initialization_timeout`` (default 300 s)
    and then dies; a gang relaunched by the elastic agent
    (train/elastic.py) routinely comes up BEFORE its task-0 coordinator
    process does, so the first attempt timing out must cost a retried,
    clearly-logged attempt — not an indefinite hang or an opaque one-shot
    failure. Raises :class:`BootstrapError` naming the coordinator and the
    attempt budget when every attempt fails."""
    import time as _time

    from distributed_tensorflow_tpu.train import resilience

    timeout_s = cluster.connect_timeout_s if timeout_s is None else timeout_s
    attempts = cluster.connect_attempts if attempts is None else attempts
    if initialize_fn is None:
        initialize_fn = jax.distributed.initialize
        if shutdown_fn is None:
            shutdown_fn = jax.distributed.shutdown

    def _attempt():
        initialize_fn(
            coordinator_address=cluster.coordinator_address,
            num_processes=cluster.num_processes,
            process_id=task_index,
            initialization_timeout=int(timeout_s),
        )

    def _on_retry(exc, attempt, delay):
        # jax assigns its global distributed client BEFORE connect(), so a
        # timed-out attempt leaves half-initialized state behind and the
        # bare retry would die instantly with "initialize should only be
        # called once" — tear it down first so the retry is real.
        if shutdown_fn is not None:
            try:
                shutdown_fn()
            except Exception:  # noqa: BLE001 — half-initialized teardown
                pass
        print_fn(
            f"bootstrap: jax.distributed.initialize attempt {attempt + 1}/"
            f"{attempts} failed ({type(exc).__name__}: {exc}); retrying in "
            f"{delay:.1f}s"
        )

    try:
        resilience.retry(
            _attempt,
            attempts=max(1, attempts),
            backoff=backoff,
            jitter=0.25,
            retry_on=(RuntimeError, TimeoutError, OSError),
            describe="jax.distributed.initialize",
            on_retry=_on_retry,
            sleep=sleep or _time.sleep,
        )
    except (RuntimeError, TimeoutError, OSError) as exc:
        # Tear down after the FINAL failure too: a caller that catches
        # BootstrapError and retries bootstrap later in the same process
        # must not inherit the half-initialized global client (its first
        # fresh attempt would die with "initialize should only be called
        # once" and burn budget on a misleading error).
        if shutdown_fn is not None:
            try:
                shutdown_fn()
            except Exception:  # noqa: BLE001 — half-initialized teardown
                pass
        raise BootstrapError(
            f"jax.distributed.initialize to {cluster.coordinator_address} "
            f"(process {task_index}/{cluster.num_processes}) failed after "
            f"{attempts} attempt(s) of {timeout_s}s each: "
            f"{type(exc).__name__}: {exc}"
        ) from exc


def bootstrap(
    cluster: ClusterConfig,
    job_name: str = "worker",
    task_index: int = 0,
    *,
    initialize_distributed: bool | None = None,
    heartbeat_port: int | None = None,
    heartbeat_timeout_ms: int | None = None,
    heartbeat_host: str | None = None,
    print_fn=print,
) -> ProcessContext:
    """Resolve this process's role; join the multi-host group if one exists.

    The reference's ``Server`` + ``ClusterSpec`` bootstrap becomes
    ``jax.distributed.initialize(coordinator, num_processes, process_id)``
    when ``worker_svrs`` lists more than one host (multi-host DCN group) —
    under a bounded timeout + retry (:func:`bounded_initialize`), so a
    restarting gang whose coordinator isn't up yet gets a retried, loud
    error instead of an indefinite hang; single-process runs skip
    initialization entirely.

    ``heartbeat_port`` (optional; defaults from ``cluster.heartbeat_port``)
    arms the native failure detector (runtime/csrc): the chief runs a UDP
    heartbeat coordinator, non-chiefs a sender — explicit worker-liveness
    tracking the reference never had (SURVEY.md §5 "Failure detection").
    With ``heartbeat_host`` set (elastic mode, train/elastic.py) the
    detector is hosted THERE — out-of-band of the job, by the supervising
    agent — and every task including the chief is a plain sender to it.
    Requires the C++ runtime; silently skipped when unavailable.
    """
    if job_name == "ps":
        # Reference: print("ps setting up ...") then server.join() forever
        # (tfdist_between.py:28-29). Here the role is obsolete by design.
        print_fn("ps setting up ...")
        print_fn(
            "ps role is a no-op on TPU: parameters are replicated on chips "
            "and aggregated over ICI; exiting cleanly."
        )
        return ProcessContext(
            job_name="ps",
            task_index=task_index,
            num_processes=cluster.num_processes,
            is_chief=False,
            is_ps=True,
        )

    print_fn("worker setting up ...")
    # Per-rank event journal (round 12): a launcher that exported
    # DTF_JOURNAL_DIR (tools/launch_local.py elastic mode) gets this
    # worker's journal armed with zero worker-side code — under the
    # member's ORIGINAL id across resizes (task_index is the compact
    # rank; DTF_WORKER_RANKS maps it back), so one member keeps one
    # journal across every topology it serves in, mirroring the log-file
    # convention. No env → no-op.
    journal_rank = task_index
    ranks_env = os.environ.get("DTF_WORKER_RANKS")
    if ranks_env:
        from distributed_tensorflow_tpu.launch import parse_worker_ranks

        ranks_list = parse_worker_ranks(ranks_env)
        if 0 <= task_index < len(ranks_list):
            # Out-of-range stays on the compact rank rather than raising:
            # PS-mode tasks bootstrap through here too and are not in the
            # worker roster; cluster_from_env (the resize consumer) is
            # the layer that validates length against the world size.
            journal_rank = ranks_list[task_index]
    from distributed_tensorflow_tpu.observability.journal import (
        configure_from_env,
    )

    configure_from_env(journal_rank)
    n = cluster.num_processes
    if heartbeat_port is None:
        heartbeat_port = cluster.heartbeat_port
    if heartbeat_timeout_ms is None:
        heartbeat_timeout_ms = cluster.heartbeat_timeout_ms
    if heartbeat_host is None:
        heartbeat_host = cluster.heartbeat_host
    if initialize_distributed is None:
        initialize_distributed = n > 1
    if initialize_distributed and n > 1:
        bounded_initialize(cluster, task_index, print_fn=print_fn)
    heartbeat = None
    heartbeat_sender = None
    if heartbeat_port is not None and (n > 1 or heartbeat_host is not None):
        # Beat interval scaled to the silence window: at the old fixed
        # 1000 ms a tight timeout (say 1200 ms) left a 200 ms margin and a
        # loaded host's scheduling jitter read as death (cost a debugging
        # cycle in this round's e2e). >=5 beats per window keeps one
        # dropped datagram + jitter from ever looking like silence — for
        # timeouts >= 500 ms; below that the 100 ms interval floor wins
        # and the margin thins again (sub-500 ms windows are test
        # configs, not production settings).
        interval_ms = min(1000, max(100, heartbeat_timeout_ms // 5))
        try:
            from distributed_tensorflow_tpu.runtime import native

            if heartbeat_host is not None:
                # Elastic mode: the supervising agent (train/elastic.py)
                # hosts the detector out-of-band; every task — chief
                # included — is a plain sender to it. No in-job coordinator:
                # recovery is the agent's job, not the chief's.
                heartbeat = native.HeartbeatWorker(
                    heartbeat_host,
                    heartbeat_port,
                    worker_id=task_index,
                    interval_ms=interval_ms,
                )
            elif cluster.is_chief(task_index):
                heartbeat = native.HeartbeatCoordinator(
                    heartbeat_port, expected_workers=n, timeout_ms=heartbeat_timeout_ms
                )
                # The coordinator tracks task 0 too (a never-seen task counts
                # failed after the grace period), so the chief reports to
                # itself over loopback. If the sender cannot start, tear the
                # coordinator down too — returning it alone would flag the
                # silent chief slot as failed after the grace period and
                # abort a healthy run.
                try:
                    heartbeat_sender = native.HeartbeatWorker(
                        "127.0.0.1",
                        heartbeat_port,
                        worker_id=task_index,
                        interval_ms=interval_ms,
                    )
                except (ImportError, OSError):
                    heartbeat.stop()
                    heartbeat = None
                    raise
            else:
                host = cluster.coordinator_address.rsplit(":", 1)[0]
                heartbeat = native.HeartbeatWorker(
                    host,
                    heartbeat_port,
                    worker_id=task_index,
                    interval_ms=interval_ms,
                )
        except (ImportError, OSError) as e:  # degrade to no liveness tracking
            print_fn(f"heartbeat disabled: {e}")
    return ProcessContext(
        job_name="worker",
        task_index=task_index,
        num_processes=n,
        is_chief=cluster.is_chief(task_index),
        is_ps=False,
        heartbeat=heartbeat,
        heartbeat_sender=heartbeat_sender,
    )


def bootstrap_from_argv(
    cluster: ClusterConfig, argv: Sequence[str] | None = None, **kw
) -> ProcessContext:
    args = define_flags().parse_args(argv if argv is not None else sys.argv[1:])
    return bootstrap(cluster, args.job_name, args.task_index, **kw)
