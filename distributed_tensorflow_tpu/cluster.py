"""Cluster bootstrap: the reference's L1 layer, TPU-native (C2, C3, C5).

Reference behavior being reproduced (SURVEY.md §1 L1, §3.2):

- ``tf.app.flags`` ``--job_name={ps,worker} --task_index=N`` select this
  process's role and rank (reference tfdist_between.py:11-13);
- ``tf.train.ClusterSpec({"ps": ..., "worker": ...})`` +
  ``tf.train.Server(...)`` start a per-process gRPC server
  (reference tfdist_between.py:9,17);
- ps processes block forever in ``server.join()``
  (reference tfdist_between.py:27-29).

TPU-native mapping: there is no parameter server and no per-tensor RPC
transport. ``worker_svrs`` entries become processes in a
``jax.distributed`` coordination group (entry 0 is the coordinator), the
global device mesh spans all processes' chips, and all communication is XLA
collectives over ICI/DCN. The ``ps`` role is accepted for CLI compatibility
and resolves to an explanatory no-op: a launcher script that starts
``--job_name=ps`` tasks keeps working, the ps task simply exits cleanly
instead of serving (its function — holding shared parameters — moved onto
the chips).
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
from typing import Sequence

import jax

from distributed_tensorflow_tpu.config import ClusterConfig


def define_flags(parser: argparse.ArgumentParser | None = None) -> argparse.ArgumentParser:
    """The reference CLI (C2): ``--job_name`` / ``--task_index``."""
    parser = parser or argparse.ArgumentParser(
        description="distributed_tensorflow_tpu launcher"
    )
    parser.add_argument(
        "--job_name",
        type=str,
        default="worker",
        choices=("ps", "worker"),
        help="Role of this process. 'ps' is accepted for compatibility and "
        "no-ops: parameters live on the chips (no parameter server on TPU).",
    )
    parser.add_argument(
        "--task_index",
        type=int,
        default=0,
        help="Rank of this process within its job (0 = chief/coordinator).",
    )
    return parser


@dataclasses.dataclass(frozen=True)
class ProcessContext:
    """What bootstrap resolves for this process."""

    job_name: str
    task_index: int
    num_processes: int
    is_chief: bool
    is_ps: bool
    heartbeat: object | None = None  # chief: HeartbeatCoordinator; worker: HeartbeatWorker
    # Chief only: its own loopback HeartbeatWorker (the coordinator tracks
    # all n tasks incl. task 0, and never-seen tasks count failed after the
    # grace period — the chief must therefore report too).
    heartbeat_sender: object | None = None

    @property
    def should_exit(self) -> bool:
        return self.is_ps

    def close(self) -> None:
        """Stop the native heartbeat threads (coordinator or sender, plus
        the chief's loopback sender). Idempotent; without this a library
        embedding that outlives training would keep UDP threads running and
        hold the port against a later bootstrap."""
        for h in (self.heartbeat, self.heartbeat_sender):
            if h is not None:
                h.stop()


def bootstrap(
    cluster: ClusterConfig,
    job_name: str = "worker",
    task_index: int = 0,
    *,
    initialize_distributed: bool | None = None,
    heartbeat_port: int | None = None,
    heartbeat_timeout_ms: int = 10_000,
    print_fn=print,
) -> ProcessContext:
    """Resolve this process's role; join the multi-host group if one exists.

    The reference's ``Server`` + ``ClusterSpec`` bootstrap becomes
    ``jax.distributed.initialize(coordinator, num_processes, process_id)``
    when ``worker_svrs`` lists more than one host (multi-host DCN group);
    single-process runs skip initialization entirely.

    ``heartbeat_port`` (optional) arms the native failure detector
    (runtime/csrc): the chief runs a UDP heartbeat coordinator, non-chiefs a
    sender — explicit worker-liveness tracking the reference never had
    (SURVEY.md §5 "Failure detection"). Requires the C++ runtime; silently
    skipped when unavailable.
    """
    if job_name == "ps":
        # Reference: print("ps setting up ...") then server.join() forever
        # (tfdist_between.py:28-29). Here the role is obsolete by design.
        print_fn("ps setting up ...")
        print_fn(
            "ps role is a no-op on TPU: parameters are replicated on chips "
            "and aggregated over ICI; exiting cleanly."
        )
        return ProcessContext(
            job_name="ps",
            task_index=task_index,
            num_processes=cluster.num_processes,
            is_chief=False,
            is_ps=True,
        )

    print_fn("worker setting up ...")
    n = cluster.num_processes
    if initialize_distributed is None:
        initialize_distributed = n > 1
    if initialize_distributed and n > 1:
        jax.distributed.initialize(
            coordinator_address=cluster.coordinator_address,
            num_processes=n,
            process_id=task_index,
        )
    heartbeat = None
    heartbeat_sender = None
    if heartbeat_port is not None and n > 1:
        try:
            from distributed_tensorflow_tpu.runtime import native

            if cluster.is_chief(task_index):
                heartbeat = native.HeartbeatCoordinator(
                    heartbeat_port, expected_workers=n, timeout_ms=heartbeat_timeout_ms
                )
                # The coordinator tracks task 0 too (a never-seen task counts
                # failed after the grace period), so the chief reports to
                # itself over loopback. If the sender cannot start, tear the
                # coordinator down too — returning it alone would flag the
                # silent chief slot as failed after the grace period and
                # abort a healthy run.
                try:
                    heartbeat_sender = native.HeartbeatWorker(
                        "127.0.0.1", heartbeat_port, worker_id=task_index
                    )
                except (ImportError, OSError):
                    heartbeat.stop()
                    heartbeat = None
                    raise
            else:
                host = cluster.coordinator_address.rsplit(":", 1)[0]
                heartbeat = native.HeartbeatWorker(
                    host, heartbeat_port, worker_id=task_index
                )
        except (ImportError, OSError) as e:  # degrade to no liveness tracking
            print_fn(f"heartbeat disabled: {e}")
    return ProcessContext(
        job_name="worker",
        task_index=task_index,
        num_processes=n,
        is_chief=cluster.is_chief(task_index),
        is_ps=False,
        heartbeat=heartbeat,
        heartbeat_sender=heartbeat_sender,
    )


def bootstrap_from_argv(
    cluster: ClusterConfig, argv: Sequence[str] | None = None, **kw
) -> ProcessContext:
    args = define_flags().parse_args(argv if argv is not None else sys.argv[1:])
    return bootstrap(cluster, args.job_name, args.task_index, **kw)
