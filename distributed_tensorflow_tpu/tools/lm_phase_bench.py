"""Measured per-phase decomposition of the LM train step (round 5).

Round 4 closed with MFU* at 2.4-5.2% and an *argued* explanation ("toy
widths, bandwidth-bound phases, optimizer traffic") — this tool measures
it. Each phase is a chained-scan region timed with the two-point
discipline (utils/sync.two_point_seconds; CLAUDE.md timing traps), and
the phases nest so differences isolate stages:

- ``blocks-fwd``  — embed + the transformer stack, no logits/loss
- ``fwd``         — + final layernorm, logits matmul, masked CE
- ``fwd+bwd``     — value_and_grad of the same loss (params fixed)
- ``step``        — + adam update (the real train step)

so ``logits+loss = fwd − blocks-fwd``, ``backward = fwd+bwd − fwd``,
``optimizer = step − fwd+bwd``. Two microbenches split the block cost:
``attn`` (the model's attention op at its exact shapes) and ``ffn`` (the
block's two FFN matmuls), each chained output→input.

Every chained region feeds a data-dependent perturbation of the tokens
(derived from the previous iteration's loss) so XLA cannot hoist the
loop-invariant computation out of the scan — without it, a fwd-only
region measures one application plus a scalar loop (cost a debugging
cycle; the training regions chain through params naturally).

Usage::

    python -m distributed_tensorflow_tpu.tools.lm_phase_bench            # default grid
    python -m distributed_tensorflow_tpu.tools.lm_phase_bench --write-docs

Writes docs/benchmarks/lm_phases.md + .json with ``--write-docs``.
"""

from __future__ import annotations

import argparse
import json
import os

import jax
import jax.numpy as jnp
import optax
from jax import lax

from distributed_tensorflow_tpu.models.gpt import GPTLM, _ce_from_logits
from distributed_tensorflow_tpu.utils.sync import timed_fetch, two_point_seconds

_VOCAB = 8192

# (name, model kwargs, batch): one toy row from the round-4 table and the
# MXU-sized rows the round-5 push added. remat=True on the big rows —
# required to fit HBM (the d=2048/L=2048 stash is ~20 GB unremat'd) and
# part of what the measurement must therefore attribute.
CONFIGS = {
    "gpt-s-L512": (
        dict(model_dim=256, num_layers=4, num_heads=8, max_len=512), 32
    ),
    "gpt-l-L1024": (
        dict(
            model_dim=1024, num_layers=8, num_heads=16, max_len=1024,
            attention_impl="flash", flash_min_len=0,
        ),
        8,
    ),
    "gpt-xl-L1024": (
        dict(
            model_dim=2048, num_layers=4, num_heads=16, max_len=1024,
            attention_impl="flash", remat=True,
        ),
        16,
    ),
    "gpt-xl-L2048": (
        dict(
            model_dim=2048, num_layers=4, num_heads=16, max_len=2048,
            attention_impl="flash", remat=True,
        ),
        8,
    ),
    # CPU-runnable flash+remat row (round 13): small enough for the
    # Pallas interpreter, so the remat-policy comparison region has a
    # committed point on an egress-less container; its numbers are
    # interpreter-scale (the row is device-tagged and the table marks
    # it) — the chip rerun replaces them with Mosaic measurements.
    "gpt-tiny-L128-flash-remat": (
        dict(
            model_dim=128, num_layers=2, num_heads=4, max_len=128,
            attention_impl="flash", flash_min_len=0, remat=True,
        ),
        4,
    ),
}


def _perturb(tokens, seed_scalar):
    """Data-dependent token rotation: mixes a scalar derived from the
    previous iteration's output into every position, mod vocab — cheap,
    and makes each iteration's forward depend on the last (no hoisting)."""
    shift = jnp.abs(jnp.nan_to_num(seed_scalar * 1e6)).astype(jnp.int32) % 7
    return (tokens + shift) % _VOCAB


def _chain(body, n):
    """Scan ``body(params, tokens) -> scalar`` n times, tokens perturbed
    by each iteration's scalar result. ``params`` is a RUNTIME argument —
    closing over it would bake the whole parameter tree into the HLO as
    literals, and a 220M-param tree makes an ~880 MB compile payload the
    remote-compile tunnel rejects outright (HTTP 413; cost a debugging
    cycle)."""

    @jax.jit
    def run(params, tokens):
        def step(carry, _):
            toks, acc = carry
            out = body(params, toks)
            return (_perturb(toks, out), acc + out), ()

        (toks, acc), _ = lax.scan(step, (tokens, 0.0), None, length=n)
        return acc

    return run


def _region_seconds(make_run, args, steps, reps):
    r1, r4 = make_run(steps), make_run(4 * steps)
    t1 = lambda: timed_fetch(r1, *args)[0]  # noqa: E731
    t4 = lambda: timed_fetch(r4, *args)[0]  # noqa: E731
    t1(), t4()  # compile + warm
    return two_point_seconds(t1, t4, 3 * steps, reps=reps)


def bench_phases(
    name: str, *, steps: int = 4, reps: int = 3,
    ceiling_tflops: float | None = None, matmul_dtype: str | None = None,
) -> dict:
    mkw, b = CONFIGS[name]
    if matmul_dtype:
        mkw = dict(mkw, matmul_dtype=matmul_dtype)
    model = GPTLM(vocab_size=_VOCAB, **mkw)
    params = model.init(seed=1)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    tokens = jax.random.randint(
        jax.random.key(0), (b, model.max_len), 0, _VOCAB, jnp.int32
    )
    l = model.max_len

    def blocks_fwd(p, toks):
        h = model._embed_tokens(p, toks, jnp.arange(l))

        def body(h, blk):
            h, _, _ = model._block(blk, h, positions=jnp.arange(l))
            return h, ()

        body = model._remat_wrap(body)  # honors the policy knob too
        h, _ = lax.scan(body, h, p.blocks)
        return jnp.sum(h.astype(jnp.float32)) * 1e-9

    def fwd(p, toks):
        return model.loss(p, toks)

    def fwd_bwd(p, toks):
        loss, grads = jax.value_and_grad(model.loss)(p, toks)
        # Fold a hair of every grad into the scalar so the backward is
        # demanded (loss alone depends only on the forward).
        gsum = sum(
            jnp.sum(g.astype(jnp.float32)) for g in jax.tree.leaves(grads)
        )
        return loss + gsum * 1e-30

    def fwd_dgrad(p, toks):
        # The dgrad-only cut (round 9, VERDICT r5 weak #4): differentiate
        # wrt the block-stack INPUT h0 with the params held constant —
        # the backward sweeps the same layer chain (and, under remat,
        # does the same per-layer recompute) but every wgrad matmul is
        # dead code XLA drops. fwd+bwd − this = the wgrad matmuls;
        # this − fwd = dgrad (+ recompute when remat).
        positions = jnp.arange(l)

        def loss_from_h(h):
            def body(h, blk):
                h, _, _ = model._block(blk, h, positions=positions)
                return h, ()

            b2 = model._remat_wrap(body)
            h, _ = lax.scan(b2, h, p.blocks)
            logits = model._logits(p, h)
            return _ce_from_logits(logits, toks)

        h0 = model._embed_tokens(p, toks, positions)
        loss, gh = jax.value_and_grad(loss_from_h)(h0)
        return loss + jnp.sum(gh.astype(jnp.float32)) * 1e-30

    sec = {}
    for key, body in [
        ("blocks-fwd", blocks_fwd),
        ("fwd", fwd),
        ("fwd+bwd", fwd_bwd),
        ("fwd+dgrad", fwd_dgrad),
    ]:
        sec[key] = _region_seconds(
            lambda n, body=body: _chain(body, n),
            (params, tokens),
            steps,
            reps,
        )

    # Remat-policy comparison region (round 13, ROADMAP item 4): the same
    # fwd+bwd region under remat="selective" (flash out+lse saved, only
    # the LN/QKV/MLP half replayed) — measured on remat rows, where the
    # two policies are the actual A/B. Params as runtime args (the
    # HTTP-413 gotcha) ride in through _chain unchanged.
    if model.remat:
        sel_model = GPTLM(
            vocab_size=_VOCAB, **dict(mkw, remat="selective")
        )

        def fwd_bwd_sel(p, toks):
            loss, grads = jax.value_and_grad(sel_model.loss)(p, toks)
            gsum = sum(
                jnp.sum(g.astype(jnp.float32))
                for g in jax.tree.leaves(grads)
            )
            return loss + gsum * 1e-30

        sec["fwd+bwd-selective"] = _region_seconds(
            lambda n: _chain(fwd_bwd_sel, n), (params, tokens), steps, reps
        )

    # Full train step: chained through (params, opt_state) — the same
    # region lm_bench times.
    def make_step_run(n):
        @jax.jit
        def run(params, opt_state, tokens):
            def body(carry, _):
                p, o = carry
                loss, grads = jax.value_and_grad(model.loss)(p, tokens)
                updates, o = opt.update(grads, o, p)
                p = optax.apply_updates(p, updates)
                return (p, o), loss

            (_, _), losses = lax.scan(
                body, (params, opt_state), None, length=n
            )
            return losses[-1]

        return run

    sec["step"] = _region_seconds(
        make_step_run, (params, opt_state, tokens), steps, reps
    )

    # Microbench split of the block interior at the model's exact shapes:
    # attention (the op the blocks call) and the FFN pair, chained
    # output->input so nothing hoists.
    h_dim, kv = model.num_heads, model.num_kv_heads
    d, hd = model.model_dim, model.head_dim
    blk0 = jax.tree.map(lambda x: x[0], params.blocks)
    x0 = jax.random.normal(
        jax.random.key(1), (b, l, d), model.compute_dtype
    )

    def attn_once(blk, x):
        q = model._dot(x, blk.wq).reshape(b, l, h_dim, hd)
        k = model._dot(x, blk.wk).reshape(b, l, kv, hd)
        v = model._dot(x, blk.wv).reshape(b, l, kv, hd)
        o = model._attend(q, k, v)
        return model._dot(o.reshape(b, l, d), blk.wo)

    def ffn_once(blk, x):
        out, _ = model._ffn(blk, x)
        return out.astype(model.compute_dtype)

    def micro(body):
        # blk rides as a runtime arg for the same HLO-size reason as
        # params in _chain.
        def make(n):
            @jax.jit
            def run(blk, x):
                def step(x, _):
                    y = body(blk, x)
                    return y.astype(x.dtype), ()

                y, _ = lax.scan(step, x, None, length=n)
                return jnp.sum(y.astype(jnp.float32))

            return run

        r1, r4 = make(steps), make(4 * steps)
        t1 = lambda: timed_fetch(r1, blk0, x0)[0]  # noqa: E731
        t4 = lambda: timed_fetch(r4, blk0, x0)[0]  # noqa: E731
        t1(), t4()
        return two_point_seconds(t1, t4, 3 * steps, reps=reps)

    per_layer_attn = micro(attn_once)
    per_layer_ffn = micro(ffn_once)

    n_params = sum(p.size for p in jax.tree.leaves(params))
    # 6N model FLOPs with N excluding the embedding/position tables (the
    # Kaplan/Chinchilla convention — lookups pay no per-token matmul
    # FLOPs; the tied head shares the embedding). Round 5 used total
    # params, inflating the toy rows' MFU† by the table's share
    # (ADVICE round 5; lm_bench.py carries the same fix).
    n_nonembed = int(n_params - params.embed.size - params.pos.size)
    toks_per_step = b * l
    model_flops = 6 * n_nonembed * toks_per_step
    row = {
        "config": name,
        "batch": b,
        "seq_len": l,
        "param_count": int(n_params),
        "param_count_nonembed": n_nonembed,
        "remat": bool(model.remat),
        "matmul_dtype": model.matmul_dtype,
        "device": jax.devices()[0].device_kind,
        "phase_ms": {
            "blocks-fwd": round(sec["blocks-fwd"] * 1e3, 2),
            "logits+loss": round((sec["fwd"] - sec["blocks-fwd"]) * 1e3, 2),
            "backward": round((sec["fwd+bwd"] - sec["fwd"]) * 1e3, 2),
            # The round-13 comparison column: the same backward under the
            # selective policy (None on non-remat rows and rows measured
            # before the region existed — rendered as an em-dash).
            "backward-selective": (
                round((sec["fwd+bwd-selective"] - sec["fwd"]) * 1e3, 2)
                if "fwd+bwd-selective" in sec
                else None
            ),
            "bwd-dgrad": round((sec["fwd+dgrad"] - sec["fwd"]) * 1e3, 2),
            "optimizer": round((sec["step"] - sec["fwd+bwd"]) * 1e3, 2),
            "step": round(sec["step"] * 1e3, 2),
        },
        "per_layer_ms": {
            "attention": round(per_layer_attn * 1e3, 3),
            "ffn": round(per_layer_ffn * 1e3, 3),
            "layers": model.num_layers,
        },
        "tokens_per_sec": round(toks_per_step / sec["step"], 1),
        "model_flops_per_step": model_flops,
    }
    row["backward_split"] = _backward_split(row["phase_ms"], model.remat)
    # MFU† against the MEASURED ceiling — read from the committed roofline
    # record (cost_analysis.measured_ceiling_tflops), never hardcoded, so
    # a roofline re-measure propagates here as it does to lm_tpu.md.
    if ceiling_tflops:
        row["ceiling_tflops"] = ceiling_tflops
        row["mfu_model_pct"] = round(
            100 * model_flops / sec["step"] / (ceiling_tflops * 1e12), 2
        )
    else:
        row["ceiling_tflops"] = None
        row["mfu_model_pct"] = None
    return row


def _backward_split(phase_ms: dict, remat: bool) -> dict | None:
    """Decompose the backward lump (VERDICT r5 weak #4):
    ``backward = recompute + dgrad + wgrad``, where recompute (remat rows)
    is one blocks-forward replay — attributed at the measured
    ``blocks-fwd`` time, since jax.checkpoint replays exactly that scan —
    and the measured ``bwd-dgrad`` region is dgrad(+recompute) with the
    wgrad matmuls dead-coded away. None for rows measured before the
    dgrad region existed (they render an em-dash until the next chip
    run)."""
    dg = phase_ms.get("bwd-dgrad")
    if dg is None:
        return None
    rec = phase_ms["blocks-fwd"] if remat else 0.0
    return {
        "recompute": round(rec, 2),
        "dgrad": round(dg - rec, 2),
        "wgrad": round(phase_ms["backward"] - dg, 2),
    }


def _nonembed_param_count(row) -> int | None:
    """Non-embedding N for a committed row (offline migration of records
    written before round 6): total minus the d·(vocab + max_len) tables."""
    if row.get("config") not in CONFIGS or not row.get("param_count"):
        return None
    mkw, _ = CONFIGS[row["config"]]
    return row["param_count"] - mkw["model_dim"] * (_VOCAB + mkw["max_len"])


def refresh_derived(rows, ceiling) -> None:
    """Recompute the derived columns (non-embedding 6N model FLOPs, MFU†
    vs the current ceiling) of committed/carried rows from their measured
    fields — shared by the carry-forward merge and ``--recompute-docs``."""
    for r in rows:
        if "error" in r or not r.get("phase_ms"):
            continue
        r["backward_split"] = _backward_split(
            r["phase_ms"], bool(r.get("remat"))
        )
        if "param_count_nonembed" not in r:
            ne = _nonembed_param_count(r)
            if ne is not None:
                r["param_count_nonembed"] = ne
        n_eff = r.get("param_count_nonembed") or r.get("param_count")
        if n_eff:
            r["model_flops_per_step"] = 6 * n_eff * r["batch"] * r["seq_len"]
        if ceiling and r.get("model_flops_per_step"):
            r["ceiling_tflops"] = ceiling
            r["mfu_model_pct"] = round(
                100
                * r["model_flops_per_step"]
                / (r["phase_ms"]["step"] / 1e3)
                / (ceiling * 1e12),
                2,
            )


def render(rows) -> str:
    cols = [
        "config", "B", "L", "blocks-fwd", "logits+loss", "backward",
        "bwd selective", "bwd rec/dgrad/wgrad", "optimizer", "step (ms)",
        "attn/layer", "ffn/layer", "MFU†",
    ]
    out = ["| " + " | ".join(cols) + " |", "|" + "---|" * len(cols)]
    for r in rows:
        if "error" in r:
            out.append(
                f"| {r['config']} | error: {r['error']} |" + " |" * 11
            )
            continue
        p, pl = r["phase_ms"], r["per_layer_ms"]
        mfu = r.get("mfu_model_pct")
        split = r.get("backward_split")
        split_s = (
            "—"
            if not split
            else f"{split['recompute']}/{split['dgrad']}/{split['wgrad']}"
        )
        # Provenance mark (serving.md convention): rows measured off-chip
        # carry their device; legacy rows without the key are the
        # committed TUNNEL-TPU record.
        dev = r.get("device")
        cfg = r["config"] + (
            "" if dev is None or "TPU" in str(dev) else f" ({dev})"
        )
        sel = p.get("backward-selective")
        out.append(
            "| {config} | {batch} | {seq_len} | {b} | {ll} | {bw} | {sel} "
            "| {sp} | {opt} | {st} | {at} | {ff} | {mfu} |".format(
                config=cfg, batch=r["batch"], seq_len=r["seq_len"],
                b=p["blocks-fwd"], ll=p["logits+loss"], bw=p["backward"],
                sel="—" if sel is None else sel,
                sp=split_s, opt=p["optimizer"], st=p["step"],
                at=pl["attention"], ff=pl["ffn"],
                mfu="—" if mfu is None else mfu,
            )
        )
    return "\n".join(out)


def emit_bench_events(rows, events_path: str) -> list[dict]:
    """THIS RUN's measured rows as ``bench_point`` journal events, so the
    round-12 regression gate covers the phase series — including the new
    plain-vs-selective backward pair. Series identity is
    ``(lm_phase_bench, <config>/<phase>, device)``: a chip rerun starts
    its own series and never collides with a CPU-container point."""
    from distributed_tensorflow_tpu.observability.journal import EventJournal

    j = EventJournal(events_path, run_id="lm_phase_bench")
    try:
        out = []
        for r in rows:
            if "error" in r or not r.get("phase_ms"):
                continue
            pm = r["phase_ms"]
            common = dict(
                tool="lm_phase_bench",
                device=r.get("device") or "",
                config=r["config"],
            )
            out.append(
                j.emit(
                    "bench_point", name=f"{r['config']}/step_ms",
                    value=pm["step"], unit="ms", **common,
                )
            )
            out.append(
                j.emit(
                    "bench_point", name=f"{r['config']}/backward_ms",
                    value=pm["backward"], unit="ms", **common,
                )
            )
            if pm.get("backward-selective") is not None:
                out.append(
                    j.emit(
                        "bench_point",
                        name=f"{r['config']}/backward_selective_ms",
                        value=pm["backward-selective"], unit="ms", **common,
                    )
                )
        return out
    finally:
        j.close()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--configs", nargs="+", default=None, choices=sorted(CONFIGS))
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--write-docs", action="store_true")
    ap.add_argument(
        "--recompute-docs",
        action="store_true",
        help="no measurement: reload docs/benchmarks/lm_phases.json, "
        "recompute the derived columns (non-embedding 6N, MFU† vs the "
        "current ceiling) and rewrite md+json — runs anywhere, no chip",
    )
    ap.add_argument(
        "--matmul-dtype",
        choices=("int8", "fp8"),
        default=None,
        help="run the selected configs with quantized projection matmuls "
        "(GPTLM matmul_dtype) — an ad-hoc A/B probe, refused with "
        "--write-docs so it cannot silently re-anchor the record",
    )
    ap.add_argument(
        "--events",
        default=None,
        help="append the measured rows as bench_point journal events to "
        "this events.jsonl (default with --write-docs: "
        "docs/benchmarks/events.jsonl — the regression-gate series)",
    )
    args = ap.parse_args(argv)
    if args.matmul_dtype and (args.write_docs or args.events):
        # A probe must touch NEITHER committed surface: not the docs, and
        # not the bench_point journal — its series keys carry no override
        # tag, so probe points would contaminate the regression-gate band
        # for the default-precision record.
        ap.error(
            "--matmul-dtype is an ad-hoc probe; the committed record and "
            "the gate's event series track the default precision (drop "
            "--write-docs/--events)"
        )
    from distributed_tensorflow_tpu.tools.cost_analysis import (
        measured_ceiling_tflops,
    )

    ceiling = measured_ceiling_tflops()
    root = os.path.abspath(
        os.path.join(
            os.path.dirname(__file__), "..", "..", "docs", "benchmarks"
        )
    )
    json_path = os.path.join(root, "lm_phases.json")
    if args.recompute_docs:
        with open(json_path) as f:
            payload = json.load(f)
        refresh_derived(payload["rows"], ceiling)
        table = render(payload["rows"])
        print(table)
        with open(json_path, "w") as f:
            json.dump(payload, f, indent=1)
        _write_md(root, table, ceiling)
        print(f"recomputed {root}/lm_phases.md and lm_phases.json")
        return
    rows = []
    for name in args.configs or CONFIGS:
        try:
            rows.append(
                bench_phases(
                    name, steps=args.steps, reps=args.reps,
                    ceiling_tflops=ceiling,
                    matmul_dtype=args.matmul_dtype,
                )
            )
        except Exception as exc:  # noqa: BLE001 — record, keep sweeping
            rows.append(
                {"config": name, "error": f"{type(exc).__name__}: {exc}"[:200]}
            )
        print(json.dumps(rows[-1]))
    measured_rows = list(rows)  # events cover THIS run, not carried rows
    if args.write_docs:
        from distributed_tensorflow_tpu.tools.lm_bench import merge_rows

        prev = None  # the merged prior record, when one was loadable
        if os.path.exists(json_path):
            # Carry-forward merge (lm_bench's --write-docs discipline): a
            # --configs touch-up or a transient tunnel error must not
            # erase previously committed rows; an unreadable record
            # refuses to overwrite.
            try:
                with open(json_path) as f:
                    prev = json.load(f)
            except Exception as exc:
                print(
                    f"REFUSING to write docs: existing {json_path} is "
                    f"unreadable ({type(exc).__name__}: {exc}); move it "
                    "aside to regenerate from scratch"
                )
                return
            rows = merge_rows(rows, prev.get("rows", []), list(CONFIGS))
            # Carried rows track the CURRENT conventions (non-embedding
            # 6N, current ceiling).
            refresh_derived(rows, ceiling)
        table = render(rows)
        print(table)
        # Top-level device describes the LEGACY rows (measured before
        # per-row device tags); preserve it across merges so a CPU
        # touch-up run cannot relabel the carried TUNNEL-TPU rows.
        device = jax.devices()[0].device_kind
        if prev is not None:
            device = prev.get("device", device)
        with open(json_path, "w") as f:
            json.dump({"rows": rows, "device": device}, f, indent=1)
        _write_md(root, table, ceiling)
        print(f"wrote {root}/lm_phases.md and lm_phases.json")
    else:
        print(render(rows))
    events_path = args.events
    if events_path is None and args.write_docs:
        events_path = os.path.join(root, "events.jsonl")
    if events_path:
        n = len(emit_bench_events(measured_rows, events_path))
        print(f"appended {n} bench_point events to {events_path}")


def _write_md(root, table, ceiling) -> None:
    with open(os.path.join(root, "lm_phases.md"), "w") as f:
        f.write(
            "# LM train-step phase decomposition (one TPU v5e chip)\n\n"
            "Generated by `python -m distributed_tensorflow_tpu.tools."
            "lm_phase_bench --write-docs`. Phases nest (see the module "
            "docstring): logits+loss = fwd − blocks-fwd, backward = "
            "fwd+bwd − fwd, optimizer = step − fwd+bwd; attn/ffn are "
            "per-layer forward microbenches at the exact block shapes. "
            "All regions chained scans with data-dependent feeds, "
            "two-point timed. MFU† = 6·N·tokens (the scaling-book "
            "model-FLOPs convention — counts remat recompute as zero; N "
            "EXCLUDES the embedding/position tables, whose lookups pay "
            "no per-token matmul FLOPs — round 6 fixed the denominator, "
            "lm_phases.json keeps both counts) over the MEASURED bf16 "
            f"ceiling ({ceiling} TFLOPS, roofline_tpu.md).\n\n"
            + table
            + "\n\nReading it: the toy rows lose their step time to "
            "phases that are small matmuls and scatters (d=256 tiles "
            "an eighth of the MXU lane width), with the BACKWARD "
            "pass the dominant term. The MXU-sized rows (d=2048, "
            "remat) put ~40% of the measured ceiling into model "
            "FLOPs — the round-3/4 \"MFU gap\" was the WORKLOAD, as "
            "the roofline said, not the environment; their backward "
            "includes one full forward recompute (remat), which "
            "MFU† deliberately does not credit.\n\n"
            "The backward split (round 9): backward = remat RECOMPUTE "
            "(one blocks-forward replay — the measured blocks-fwd "
            "time) + DGRAD (the measured `bwd-dgrad` region minus "
            "recompute; wgrad matmuls dead-coded) + WGRAD (fwd+bwd "
            "minus the dgrad region). On the committed xl rows the "
            "recompute third is 49-58 ms of the 170-189 ms backward "
            "(~30%), leaving ~120-131 ms of dgrad+wgrad — and since "
            "each of recompute/dgrad/wgrad is one forward's worth of "
            "matmul FLOPs (3x blocks-fwd = 147-173 ms, matching the "
            "measured lump), **no single term dominates: the backward "
            "is three near-equal forwards**. The attackable third is "
            "the recompute (a remat policy that stashes cheap "
            "activations), because dgrad+wgrad are irreducible model "
            "FLOPs; the probed dots-saveable policies (CLAUDE.md) "
            "already showed naive stashing LOSES to recompute at these "
            "shapes, so the next step is a selective policy, not less "
            "remat. The rec/dgrad/wgrad column fills from the first "
            "on-chip rerun with the `bwd-dgrad` region (em-dash = "
            "pre-round-9 row).\n\n"
            "The `bwd selective` column (round 13) is that selective "
            "policy, built: the same fwd+bwd region re-measured with "
            "`remat=\"selective\"` — a Pallas-aware jax.checkpoint "
            "policy that SAVES the flash-attention out+lse (O(B·L·d) to "
            "store) so the backward replays only the layernorm/QKV/MLP "
            "half of each block, grad-identical to plain remat "
            "(test_gpt.py) and paired with the fused one-pass dq+dk+dv "
            "backward kernel (ops/pallas_attention, "
            "attention_parity's fused-vs-split rows). Rows tagged with "
            "a device (e.g. `(cpu)`) are off-chip interpreter points "
            "committed so the regression-gate series exists — their "
            "absolute times are NOT comparable to the TUNNEL-TPU rows; "
            "the xl rows' selective column is an em-dash until the chip "
            "rerun regenerates this table (serving.md provenance "
            "convention; no committed MFU† row is re-anchored by the "
            "policy change — `--recompute-docs` migrates derived "
            "columns only).\n"
        )


if __name__ == "__main__":
    main()
