"""Measured attention benchmark: dense XLA vs Pallas flash vs windowed.

The reference validated performance by pasting wall-clocks into its README
(reference README.md:38-40); this framework generates its benchmark records
from tools (same philosophy as ``tools/benchmark_suite.py``). This one
times the attention implementations across sequence lengths with the
correct D2H execution barrier (CLAUDE.md timing trap: through the tunneled
TPU, ``block_until_ready`` measures enqueue, not execution — only a
device-to-host value fetch is trustworthy).

Usage::

    python -m distributed_tensorflow_tpu.tools.attention_bench
    python -m distributed_tensorflow_tpu.tools.attention_bench \
        --lengths 1024 4096 --window 1024 --block 512 --iters 10

Prints a markdown table (one row per L) and a one-line JSON summary.
Dense rows that fail to compile (the O(L²) score matrix at long L) are
reported as ``oom`` rather than aborting the sweep — that boundary is
itself the result.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp


def _timed(fn, args, iters: int) -> float:
    out = fn(*args)
    _ = float(out.reshape(-1)[-1].astype(jnp.float32))  # D2H barrier
    t0 = time.perf_counter()
    for _i in range(iters):
        out = fn(*args)
    _ = float(out.reshape(-1)[-1].astype(jnp.float32))
    return (time.perf_counter() - t0) / iters


def run(
    lengths=(1024, 2048, 4096),
    *,
    batch: int = 2,
    heads: int = 8,
    head_dim: int = 64,
    window: int | None = None,
    block: int | None = None,
    iters: int = 10,
    dtype=jnp.bfloat16,
) -> list[dict]:
    from distributed_tensorflow_tpu.ops.pallas_attention import flash_attention
    from distributed_tensorflow_tpu.ops.ring_attention import dense_attention

    rows = []
    for l in lengths:
        kq, kk, kv = jax.random.split(jax.random.key(0), 3)
        shape = (batch, l, heads, head_dim)
        q = jax.random.normal(kq, shape, dtype)
        k = jax.random.normal(kk, shape, dtype)
        v = jax.random.normal(kv, shape, dtype)
        row = {"L": l}
        try:
            dense = jax.jit(lambda q, k, v: dense_attention(q, k, v, causal=True))
            row["dense_ms"] = _timed(dense, (q, k, v), iters) * 1e3
        except Exception as exc:  # noqa: BLE001 — recorded, not swallowed
            # The expected failure is the O(L²) compile/OOM boundary, but
            # record WHAT failed so a genuine bug can't masquerade as "oom"
            # in a published table.
            row["dense_ms"] = None
            row["dense_error"] = f"{type(exc).__name__}: {exc}"[:200]
        bq = min(block, l) if block else None
        flash = jax.jit(
            lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bq
            )
        )
        row["flash_ms"] = _timed(flash, (q, k, v), iters) * 1e3
        if window is not None and window < l:
            win = jax.jit(
                lambda q, k, v: flash_attention(
                    q, k, v, causal=True, window=window, block_q=bq, block_k=bq
                )
            )
            row["window_ms"] = _timed(win, (q, k, v), iters) * 1e3
        rows.append(row)
    return rows


def render(rows, *, window=None) -> str:
    cols = ["L", "dense XLA (ms)", "flash (ms)", "speedup"]
    if window is not None:
        cols.append(f"window={window} (ms)")
    out = ["| " + " | ".join(cols) + " |", "|" + "---|" * len(cols)]
    for r in rows:
        if r["dense_ms"] is None:
            err = r.get("dense_error", "").lower()
            oomish = any(w in err for w in ("resource", "memory", "oom"))
            dense = "oom" if oomish else "error"
        else:
            dense = f"{r['dense_ms']:.2f}"
        speed = (
            "—"
            if r["dense_ms"] is None
            else f"{r['dense_ms'] / r['flash_ms']:.2f}x"
        )
        cells = [str(r["L"]), dense, f"{r['flash_ms']:.2f}", speed]
        if window is not None:
            cells.append(
                f"{r['window_ms']:.2f}" if "window_ms" in r else "—"
            )
        out.append("| " + " | ".join(cells) + " |")
    return "\n".join(out)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--lengths", type=int, nargs="+", default=[1024, 2048, 4096])
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--window", type=int, default=None)
    ap.add_argument("--block", type=int, default=None)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args(argv)
    rows = run(
        tuple(args.lengths),
        batch=args.batch,
        heads=args.heads,
        head_dim=args.head_dim,
        window=args.window,
        block=args.block,
        iters=args.iters,
    )
    print(f"device: {jax.devices()[0].device_kind}")
    print(render(rows, window=args.window))
    print(json.dumps({"rows": rows, "backend": jax.default_backend()}))


if __name__ == "__main__":
    main()
