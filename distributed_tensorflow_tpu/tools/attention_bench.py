"""Measured attention benchmark: dense XLA vs Pallas flash vs windowed.

The reference validated performance by pasting wall-clocks into its README
(reference README.md:38-40); this framework generates its benchmark records
from tools (same philosophy as ``tools/benchmark_suite.py``). This one
times the attention implementations across sequence lengths with BOTH
measurement disciplines this environment demands (CLAUDE.md):

- **D2H execution barrier**: through the tunneled TPU,
  ``block_until_ready`` measures enqueue, not execution — only a
  device-to-host value fetch is trustworthy;
- **in-graph amortization**: the tunnel's ~12 ms dispatch floor swamps any
  single attention call, so each timing runs ``iters`` applications inside
  ONE dispatch as a ``lax.scan`` whose carry feeds each call's output back
  in as the next query — a genuine sequential dependency, so XLA cannot
  hoist or CSE the loop body — and reports per-call time. (The round-2
  table timed eager calls; three of its five cells were the floor, not the
  kernels — VERDICT round-2 weak #1.)

Usage::

    python -m distributed_tensorflow_tpu.tools.attention_bench
    python -m distributed_tensorflow_tpu.tools.attention_bench \
        --lengths 1024 4096 --window 1024 --block 512 --iters 32 --grad

Prints a markdown table (one row per L) and a one-line JSON summary.
Implementations that fail to compile (the dense O(L²) score matrix at long
L) are reported as ``oom`` rather than aborting the sweep — that boundary
is itself the result.
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp
from jax import lax


def _timed_scanned(fn, q, k, v, iters: int, *, grad: bool = False):
    """Per-call seconds for ``fn(q, k, v) -> [B, L, H, D]``: ``iters``
    applications chained through the carry, TWO-POINT timed
    (``utils/sync.two_point_seconds``) — the round-3 version divided one
    chain's wall time by ``iters``, folding the ~100 ms dispatch+fetch
    roundtrip into every call (at 32 iters that's ~3 ms/call of phantom
    cost, which COMPRESSED every flash-vs-dense ratio toward 1; the
    round-3 'flash 0.92x dense at L=2048' was this artifact — honestly
    measured it is ~3.9x with the round-4 block policy)."""
    if grad:
        # Differentiate w.r.t. ALL of q, k, v (grad over q alone would let
        # dense AD skip the dk/dv backward entirely while flash's custom
        # VJP always computes all three — unequal work). Chain the carry
        # through a mix of the three cotangents so none can be DCE'd.
        g = jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
            argnums=(0, 1, 2),
        )

        def one(q):
            dq, dk, dv = g(q, k, v)
            if dk.shape != dq.shape:  # GQA: fewer KV heads
                rep = dq.shape[2] // dk.shape[2]
                dk = jnp.repeat(dk, rep, axis=2)
                dv = jnp.repeat(dv, rep, axis=2)
            return (dq + 1e-6 * dk + 1e-6 * dv).astype(q.dtype)

    else:
        def one(q):
            return fn(q, k, v).astype(q.dtype)

    from distributed_tensorflow_tpu.utils.sync import (
        timed_fetch,
        two_point_seconds,
    )

    def make(n):
        @jax.jit
        def many(q):
            out, _ = lax.scan(
                lambda c, _: (one(c), None), q, None, length=n
            )
            return out

        return many

    m1, m4 = make(iters), make(4 * iters)
    timed_fetch(m1, q), timed_fetch(m4, q)  # compile both
    return two_point_seconds(
        lambda: timed_fetch(m1, q)[0],
        lambda: timed_fetch(m4, q)[0],
        3 * iters,
        reps=3,
    )


def _record(row, key, fn, q, k, v, iters, grad):
    """Time one implementation, recording failure instead of aborting the
    sweep (a bad (L, block) combination or the dense OOM boundary must not
    kill the table — ADVICE round-2)."""
    try:
        row[f"{key}_ms"] = _timed_scanned(fn, q, k, v, iters, grad=grad) * 1e3
    except Exception as exc:  # noqa: BLE001 — recorded, not swallowed
        row[f"{key}_ms"] = None
        row[f"{key}_error"] = f"{type(exc).__name__}: {exc}"[:200]


def run(
    lengths=(1024, 2048, 4096),
    *,
    batch: int = 2,
    heads: int = 8,
    head_dim: int = 64,
    kv_heads: int | None = None,
    window: int | None = None,
    block: int | None = None,
    iters: int | None = None,
    grad: bool = False,
    dtype=jnp.bfloat16,
) -> list[dict]:
    from distributed_tensorflow_tpu.ops.pallas_attention import flash_attention
    from distributed_tensorflow_tpu.ops.ring_attention import dense_attention

    rows = []
    for l in lengths:
        # Per-length chain sizing: the two-point span (3·iters calls) must
        # dwarf the ~±10 ms dispatch jitter, and short-L calls are tens of
        # µs — a fixed iters that suits L=8192 reports noise at L=1024
        # (two_point_seconds clamps negative medians to 1e-12, which once
        # rendered as a straight-faced "0.000 ms" table cell).
        l_iters = iters if iters else max(8, (1 << 18) // l)
        kq, kk, kv = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(kq, (batch, l, heads, head_dim), dtype)
        kvshape = (batch, l, kv_heads or heads, head_dim)
        k = jax.random.normal(kk, kvshape, dtype)
        v = jax.random.normal(kv, kvshape, dtype)
        row = {"L": l, "iters": l_iters, "grad": grad}
        _record(
            row, "dense",
            lambda q, k, v: dense_attention(q, k, v, causal=True),
            q, k, v, l_iters, grad,
        )
        bq = min(block, l) if block else None
        _record(
            row, "flash",
            lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bq
            ),
            q, k, v, l_iters, grad,
        )
        if window is not None and window < l:
            _record(
                row, "window",
                lambda q, k, v: flash_attention(
                    q, k, v, causal=True, window=window, block_q=bq, block_k=bq
                ),
                q, k, v, l_iters, grad,
            )
            _record(
                row, "window_dense",
                lambda q, k, v: dense_attention(
                    q, k, v, causal=True, window=window
                ),
                q, k, v, l_iters, grad,
            )
        rows.append(row)
    return rows


def render(rows, *, window=None) -> str:
    cols = ["L", "dense XLA (ms)", "flash (ms)", "speedup"]
    if window is not None:
        cols += [f"flash W={window} (ms)", f"dense W={window} (ms)"]
    out = ["| " + " | ".join(cols) + " |", "|" + "---|" * len(cols)]

    def cell(r, key):
        if r.get(f"{key}_ms") is not None:
            return f"{r[f'{key}_ms']:.3f}"
        err = r.get(f"{key}_error", "").lower()
        oomish = any(w in err for w in ("resource", "memory", "oom"))
        return "oom" if oomish else ("—" if not err else "error")

    for r in rows:
        speed = (
            f"{r['dense_ms'] / r['flash_ms']:.2f}x"
            if r.get("dense_ms") and r.get("flash_ms")
            else "—"
        )
        cells = [str(r["L"]), cell(r, "dense"), cell(r, "flash"), speed]
        if window is not None:
            cells += [cell(r, "window"), cell(r, "window_dense")]
        out.append("| " + " | ".join(cells) + " |")
    return "\n".join(out)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--lengths", type=int, nargs="+", default=[1024, 2048, 4096])
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--kv-heads", type=int, default=None)
    ap.add_argument("--window", type=int, default=None)
    ap.add_argument("--block", type=int, default=None)
    ap.add_argument(
        "--iters", type=int, default=None,
        help="chain length (default: auto per L — 2^18/L, min 8)",
    )
    ap.add_argument("--grad", action="store_true", help="time fwd+bwd")
    args = ap.parse_args(argv)
    rows = run(
        tuple(args.lengths),
        batch=args.batch,
        heads=args.heads,
        head_dim=args.head_dim,
        kv_heads=args.kv_heads,
        window=args.window,
        block=args.block,
        iters=args.iters,
        grad=args.grad,
    )
    print(f"device: {jax.devices()[0].device_kind}  iters/dispatch: {args.iters}")
    print(render(rows, window=args.window))
    print(json.dumps({"rows": rows, "backend": jax.default_backend()}))


if __name__ == "__main__":
    main()
