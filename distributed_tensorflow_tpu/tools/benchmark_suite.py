"""Auto-generated benchmark grid — the reference's README-as-benchmark, as a tool.

The reference's performance record is a hand-maintained Markdown table of 9
topology experiments (single GPU, 1ps+1w, 1ps+2w async/sync, 2ps+2w, two-host
runs — reference README.md:13-15,24-40,63-74,141-150,178-206,208-254; rows
reproduced in SURVEY.md §6). Each row was produced by manually launching a
topology, eyeballing the logs, and pasting numbers into the README.

This tool replaces that workflow (SURVEY.md §7 item 7): it runs the same
experiment grid against this framework's strategies on whatever devices are
present and emits the table — Markdown for humans, JSON for machines. The
topology column maps PS-era rows onto their mesh equivalents: worker count →
``data``-axis size; the PS processes have no equivalent (deleted by design,
SURVEY.md §2a).

Usage::

    # 8-virtual-device CPU mesh (the test topology):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m distributed_tensorflow_tpu.tools.benchmark_suite --epochs 3

    # real chip(s): rows needing more devices than exist are skipped.
    python -m distributed_tensorflow_tpu.tools.benchmark_suite --json grid.json

Rows (vs. SURVEY.md §6 table):

- ``single``      — SingleDevice, scanned epoch        (ref row 1: tfsingle.py)
- ``sync-N``      — SyncDataParallel over N chips      (ref rows 5,7: *_sync.py)
- ``async-N``     — AsyncDataParallel, avg_every=50    (ref rows 3,6,8: tfdist_between.py)
- ``zero-N``      — ShardedDataParallel (ZeRO-3)       (no ref row; beyond-parity)
- ``tp-2``        — sync DP × tensor parallel (model=2) (no ref row; beyond-parity)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax

from distributed_tensorflow_tpu.config import TrainConfig
from distributed_tensorflow_tpu.utils.sync import d2h_barrier
from distributed_tensorflow_tpu.models import MLP
from distributed_tensorflow_tpu.parallel.fsdp import ShardedDataParallel
from distributed_tensorflow_tpu.parallel.mesh import make_mesh
from distributed_tensorflow_tpu.parallel.strategy import (
    AsyncDataParallel,
    SingleDevice,
    SyncDataParallel,
)
from distributed_tensorflow_tpu.train import Trainer
from distributed_tensorflow_tpu.utils.logging import StepLogger


def _silent(*a, **k):
    pass


# epochs_per_dispatch sweep points (the middle-tier knob).
K_SWEEP = (5, 10, 25, 50)


def k_sweep_fixed_cost(results: list[dict]) -> dict | None:
    """Decompose the middle tier's cost from the ``single-k*`` rows:
    ``s_per_epoch(k) = t + C/k`` — ``t`` the asymptotic per-epoch compute
    (the whole-run rate) and ``C`` the per-DISPATCH fixed cost (dispatch +
    D2H history fetch + per-chunk checkpoint/eval host work), least-squares
    over the sweep. This is VERDICT r5 weak #7's "undecomposed 4x": at
    k=10 the 4x gap vs whole-run IS C/(10·t). Returns None with fewer than
    two sweep rows."""
    import re as _re

    import numpy as _np

    pts = sorted(
        (int(m.group(1)), r["s_per_epoch"])
        for r in results
        if (m := _re.match(r"^single-k(\d+)$", r["row"]))
    )
    if len(pts) < 2:
        return None
    a = _np.array([[1.0, 1.0 / k] for k, _ in pts])
    y = _np.array([s for _, s in pts])
    (t, c), *_ = _np.linalg.lstsq(a, y, rcond=None)
    return {
        "per_epoch_compute_s": round(float(t), 4),
        "per_dispatch_fixed_s": round(float(c), 4),
        "points": [{"k": k, "s_per_epoch": s} for k, s in pts],
    }


def _row_specs(n_devices: int):
    """The grid, filtered to what the device count allows."""
    rows = [
        ("single", 1, "ref #1 tfsingle.py (~1.3 s/epoch, 0.72)"),
        # Whole-run compilation (train/compiled_run.py): epochs + shuffles +
        # evals in ONE dispatch — the staging/dispatch overhead the eager
        # `single` row pays per epoch is paid once for the whole run.
        ("single-compiled", 1, "ref #1 via whole-run compilation"),
        # Same whole-run contract, inner epoch as ONE Pallas grid kernel
        # launch (TrainConfig.engine="pallas") — bench.py's engine behind
        # the Trainer API.
        ("single-compiled-pallas", 1, "ref #1, Pallas grid-kernel engine"),
        # Middle tier (round 5, config.epochs_per_dispatch): run() through
        # the compiled program k epochs per dispatch — full lifecycle
        # (per-epoch logs + eval + a checkpoint-capable boundary every k
        # epochs) at near-whole-run throughput. The k SWEEP (round 9,
        # VERDICT r5 weak #7) separates the per-dispatch fixed cost from
        # the per-epoch compute: s/epoch(k) = t + C/k, fit by
        # k_sweep_fixed_cost below — the knob users actually turn, with a
        # measured answer for what k buys.
        *(
            (f"single-k{k}", 1, "ref #1, k-epochs-per-dispatch lifecycle")
            for k in K_SWEEP
        ),
    ]
    for n in (2, n_devices):
        if n < 2 or n > n_devices:
            continue
        rows.append(("sync-%d" % n, n, "ref #5/#7 tfdist_between_sync.py (0.72)"))
        rows.append(("async-%d" % n, n, "ref #3/#6/#8 tfdist_between.py (0.80)"))
        rows.append(("zero-%d" % n, n, "beyond parity (ZeRO-3)"))
    if n_devices >= 2:
        rows.append(("tp-2", 2, "beyond parity (tensor parallel)"))
    # Drop duplicate names when n_devices == 2.
    seen, out = set(), []
    for r in rows:
        if r[0] not in seen:
            seen.add(r[0])
            out.append(r)
    return out


def _build(name: str, n: int, model):
    if name == "single":
        return SingleDevice(), True
    kind = name.split("-")[0]
    if kind == "tp":
        mesh = make_mesh((1, 2))
        return SyncDataParallel(mesh, param_specs=model.partition_specs()), True
    mesh = make_mesh((n, 1))
    if kind == "sync":
        return SyncDataParallel(mesh), True
    if kind == "async":
        return AsyncDataParallel(mesh, avg_every=50), True
    if kind == "zero":
        return ShardedDataParallel(mesh), False
    raise ValueError(name)


def run_suite(
    epochs: int = 3,
    batch_size: int = 100,
    datasets=None,
    rows: list[str] | None = None,
    print_fn=print,
    compiled_min_epochs: int = 50,
) -> list[dict]:
    if datasets is None:
        from distributed_tensorflow_tpu.data import read_data_sets

        datasets = read_data_sets("MNIST_data", one_hot=True)
    n_devices = len(jax.devices())
    results = []
    for name, n, ref in _row_specs(n_devices):
        if rows is not None and name not in rows:
            continue
        if name == "single-compiled-pallas" and jax.default_backend() != "tpu":
            # Off-TPU the Pallas kernels run in the interpreter — a
            # correctness device, catastrophically slow as a benchmark
            # (tens of minutes for the 50-epoch leg). Explicit --rows
            # selection overrides.
            if rows is None:
                continue
        model = MLP()
        if name.startswith("single-k"):
            # The chunked middle tier IS run(): time the full lifecycle
            # call (logs silenced, eval + chunk boundaries included).
            k = int(name[len("single-k") :])
            epochs_used = max(epochs, compiled_min_epochs)
            strategy = SingleDevice()
            cfg = TrainConfig(
                epochs=epochs_used, batch_size=batch_size,
                epochs_per_dispatch=k,
            )
            tr = Trainer(model, datasets, cfg, strategy=strategy, print_fn=_silent)
            tr.run()  # warmup: compile the chunk program
            t0 = time.time()
            tr.run()
            s_per_epoch = (time.time() - t0) / epochs_used
            mode = f"chunked-{k}"
        elif name.startswith("single-compiled"):
            # Whole-run path: the first call compiles (the Trainer caches
            # the compiled function, so the second call reuses it); the
            # second is timed end-to-end — staging + dispatch + the D2H
            # history fetch that run_compiled performs (the execution
            # barrier). Amortization is the point of this mode, so it runs
            # at least ``compiled_min_epochs``: at the grid's default 3
            # epochs the one-time staging transfer would dominate and
            # misrepresent the per-epoch cost.
            epochs_used = max(epochs, compiled_min_epochs)
            strategy = SingleDevice()
            engine = "pallas" if name.endswith("pallas") else "xla"
            cfg = TrainConfig(
                epochs=epochs_used, batch_size=batch_size, engine=engine
            )
            tr = Trainer(model, datasets, cfg, strategy=strategy, print_fn=_silent)
            tr.run_compiled(epochs_used)  # warmup: compile
            t0 = time.time()
            tr.run_compiled(epochs_used)
            s_per_epoch = (time.time() - t0) / epochs_used
            mode = "whole-run" if engine == "xla" else "whole-run-pallas"
        else:
            epochs_used = epochs
            strategy, can_scan = _build(name, n, model)
            cfg = TrainConfig(epochs=epochs, batch_size=batch_size, scan_epoch=can_scan)
            tr = Trainer(model, datasets, cfg, strategy=strategy, print_fn=_silent)
            logger = StepLogger(freq=10**9, print_fn=_silent)
            tr.run_epoch(0, logger)  # warmup: compile
            d2h_barrier(tr.state.params)
            times = []
            for e in range(1, epochs + 1):
                t0 = time.time()
                tr.run_epoch(e, logger)
                d2h_barrier(tr.state.params)
                times.append(time.time() - t0)
            times.sort()
            s_per_epoch = times[len(times) // 2]
            mode = "scan" if can_scan else "eager"
        global_batch = batch_size * strategy.num_replicas
        n_examples = (datasets.train.num_examples // global_batch) * global_batch
        row = {
            "row": name,
            "devices": n,
            "mode": mode,
            "epochs_timed": epochs_used,
            "s_per_epoch": round(s_per_epoch, 4),
            "examples_per_sec": round(n_examples / s_per_epoch, 1),
            "final_accuracy": round(tr.evaluate(), 4),
            "reference": ref,
        }
        results.append(row)
        print_fn(f"{name}: {row['s_per_epoch']}s/epoch  {row['examples_per_sec']:.0f} ex/s")
    return results


def markdown_table(results: list[dict]) -> str:
    """Throughput table. Accuracy is deliberately NOT a column: a short
    timed run's accuracy next to the reference's converged number implied a
    (false) parity failure — converged accuracies live in
    docs/benchmarks/parity_converged.md (tools/parity_converged.py), which
    runs the experiment table to completion and asserts the README's
    orderings. The per-run accuracy stays in the JSON as a sanity field."""
    hdr = (
        "| Row | Devices | Mode | s/epoch | examples/sec | Reference counterpart |\n"
        "|---|---|---|---|---|---|"
    )
    lines = [hdr]
    for r in results:
        lines.append(
            "| %s | %d | %s | %.3f | %.0f | %s |"
            % (
                r["row"],
                r["devices"],
                r["mode"],
                r["s_per_epoch"],
                r["examples_per_sec"],
                r["reference"],
            )
        )
    fit = k_sweep_fixed_cost(results)
    if fit is not None:
        t, c = fit["per_epoch_compute_s"], fit["per_dispatch_fixed_s"]
        lines.append("")
        lines.append(
            f"k-sweep fit (`single-k*` rows): s/epoch(k) = {t} + {c}/k — "
            f"per-dispatch fixed cost **{c} s**, asymptotic per-epoch "
            f"compute **{t} s**. Picking k: overhead stays within a "
            "factor f of compute for k >= C/(f·t) ≈ "
            f"{max(1, round(c / max(t, 1e-9)))}/f epochs per dispatch; "
            "k also sets the checkpoint/stop granularity, so take the "
            "smallest k past that knee (TrainConfig.epochs_per_dispatch)."
        )
    lines.append("")
    lines.append(
        "Converged accuracies + reference-finding checks: "
        "see `parity_converged.md` (100/40-epoch runs; this table times "
        "short runs and makes no convergence claims)."
    )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--epochs", type=int, default=3, help="timed epochs per row")
    p.add_argument("--batch_size", type=int, default=100)
    p.add_argument("--rows", type=str, default=None, help="comma-separated row filter")
    p.add_argument("--json", type=str, default=None, help="write JSON results here")
    p.add_argument("--markdown", type=str, default=None, help="write the table here")
    args = p.parse_args(argv)
    rows = args.rows.split(",") if args.rows else None
    results = run_suite(
        epochs=args.epochs,
        batch_size=args.batch_size,
        rows=rows,
        print_fn=lambda *a: print(*a, file=sys.stderr),
    )
    table = markdown_table(results)
    print(table)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
    if args.markdown:
        with open(args.markdown, "w") as f:
            f.write(table + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
