"""Measured-ceiling roofline: what THIS chip actually sustains.

Every MFU number in this repo divides by a peak. ``tools/lm_bench``
divides by the v5e SPEC peak (197 bf16 TFLOPS) and the resulting 1-2.5%
was *attributed* to the tunneled chip's lower effective ceiling without
ever measuring that ceiling (VERDICT round-3 weak #2: "the MFU story
rests on an unmeasured premise"). This tool measures it:

- **compute roof**: square N×N matmul chains (``c ← (c @ W)/N``) in bf16
  and f32 — a genuine sequential dependency through the carry of one
  ``lax.scan`` dispatch, so XLA can neither hoist nor fuse chain steps
  away; per-step FLOPs are exactly 2N³ (the normalize adds O(N²));
- **memory roof**: a streaming kernel (``c ← 0.999·c + a``) over arrays
  far larger than VMEM — 3 array-traversals of HBM traffic per step
  (read c, read a, write c), the classic STREAM triad shape;
- both timed with the ONLY trustworthy barrier through the tunnel (a D2H
  value fetch — CLAUDE.md; ``block_until_ready`` measures enqueue here)
  AND the two-point discipline: each dispatch+fetch carries a ~100 ms
  fixed roundtrip, so per-step time is the DIFFERENCE between a 4k-step
  and a k-step warm dispatch over 3k — naive division by the chain length
  reports the roundtrip, not the kernel (``_timed_chain``).

The reference validated performance by pasting wall-clocks into its
README (reference README.md:38-40); this framework generates measured
records from tools. ``--write-docs`` regenerates
``docs/benchmarks/roofline_tpu.md``, the record ``lm_bench``'s MFU column
is re-expressed against (its ``--ceiling-tflops``).

Usage::

    python -m distributed_tensorflow_tpu.tools.roofline_bench
    python -m distributed_tensorflow_tpu.tools.roofline_bench \
        --sizes 1024 2048 4096 --iters 64 --stream-mb 256 --write-docs
"""

from __future__ import annotations

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_tpu.tools.cost_analysis import _chip_peaks


def _time_once(many, arg):
    from distributed_tensorflow_tpu.utils.sync import timed_fetch

    return timed_fetch(many, arg)[0]


def _timed_chain(make_many, arg, iters: int, reps: int = 5):
    """Seconds per chain step by the TWO-POINT method
    (``utils/sync.two_point_seconds``): time a warm ``iters``-step
    dispatch and a warm ``4·iters``-step dispatch and divide the
    DIFFERENCE by the extra steps. Naive division by iters reports the
    ~100 ms dispatch+fetch roundtrip, not the kernel (measured: the same
    N=2048 bf16 matmul 'improved' from 5.8 to 87 TFLOPS as iters grew
    32→1024 — pure amortization artifact)."""
    from distributed_tensorflow_tpu.utils.sync import two_point_seconds

    many1 = make_many(iters)
    many2 = make_many(4 * iters)
    _time_once(many1, arg), _time_once(many2, arg)  # compile both
    return two_point_seconds(
        lambda: _time_once(many1, arg),
        lambda: _time_once(many2, arg),
        3 * iters,
        reps=reps,
    )


# Extra-work targets for the two-point delta: the differenced span must
# dwarf the tunnel's per-dispatch jitter (~±10 ms on a ~100 ms roundtrip)
# or small shapes report noise (an N=1024 f32 delta measured *negative*).
# 1e14 extra FLOPs ≈ 0.5 s at the ~200 TFLOPS these chains sustain.
_TARGET_FLOPS = 1.0e14
_TARGET_BYTES = 4.0e11
_MAX_ITERS = 16384


def matmul_roof(n: int, dtype, iters: int | None = None) -> dict:
    """Sustained TFLOPS for an N×N·N×N matmul chain in ``dtype``.

    The f32 row uses ``Precision.HIGHEST``: at the DEFAULT precision XLA
    lowers f32 matmuls to single-pass bf16 on the MXU, so an "f32" chain
    measures the bf16 rate (observed: 186 "f32" TFLOPS ≈ the 192 bf16
    roof). HIGHEST forces the multi-pass true-f32 product — the honest
    f32 ceiling, and a sanity check that the two-point method measures
    compute (it must land far below bf16)."""
    if iters is None:
        iters = min(_MAX_ITERS, max(64, int(_TARGET_FLOPS / (6 * n**3))))
    key = jax.random.key(0)
    w = (jax.random.normal(key, (n, n), jnp.float32) / n).astype(dtype)
    c0 = jax.random.normal(jax.random.key(1), (n, n), jnp.float32).astype(
        dtype
    )
    precision = (
        lax.Precision.HIGHEST if dtype == jnp.float32 else None
    )

    def make_many(length):
        @jax.jit
        def many(c):
            def step(c, _):
                acc = jnp.dot(
                    c, w, preferred_element_type=jnp.float32,
                    precision=precision,
                )
                return (acc / n).astype(dtype), None

            c, _ = lax.scan(step, c, None, length=length)
            return c

        return many

    sec = _timed_chain(make_many, c0, iters)
    tflops = 2 * n**3 / sec / 1e12
    return {
        "kind": "matmul",
        "n": n,
        "dtype": str(jnp.dtype(dtype).name),
        "ms_per_step": round(sec * 1e3, 4),
        "tflops": round(tflops, 2),
    }


def stream_roof(mb: int, iters: int | None = None) -> dict:
    """Sustained HBM GB/s for the STREAM-triad-shaped chain
    ``c ← 0.999·c + a`` over ``mb``-MiB f32 arrays (3 traversals/step)."""
    elems = mb * (1 << 20) // 4
    if iters is None:
        iters = min(
            _MAX_ITERS, max(64, int(_TARGET_BYTES / (9 * elems * 4)))
        )
    a = jnp.ones((elems,), jnp.float32) * 1e-3
    c0 = jnp.zeros((elems,), jnp.float32)

    def make_many(length):
        @jax.jit
        def many(c):
            def step(c, _):
                return 0.999 * c + a, None

            c, _ = lax.scan(step, c, None, length=length)
            return c

        return many

    sec = _timed_chain(make_many, c0, iters)
    gbps = 3 * elems * 4 / sec / 1e9
    return {
        "kind": "stream",
        "mb": mb,
        "dtype": "float32",
        "ms_per_step": round(sec * 1e3, 4),
        "gbps": round(gbps, 1),
    }


def run(sizes, iters, stream_mb):
    rows = []
    for n in sizes:
        for dtype in (jnp.bfloat16, jnp.float32):
            rows.append(matmul_roof(n, dtype, iters))
            print(
                f"matmul N={n} {rows[-1]['dtype']}: "
                f"{rows[-1]['ms_per_step']} ms/step, "
                f"{rows[-1]['tflops']} TFLOPS"
            )
    rows.append(stream_roof(stream_mb, iters))
    print(
        f"stream {stream_mb} MiB: {rows[-1]['ms_per_step']} ms/step, "
        f"{rows[-1]['gbps']} GB/s"
    )
    return rows


def summarize(rows) -> dict:
    peaks = _chip_peaks(jax.devices()[0]) or {}
    best_bf16 = max(
        (r["tflops"] for r in rows if r["kind"] == "matmul"
         and r["dtype"] == "bfloat16"),
        default=None,
    )
    best_f32 = max(
        (r["tflops"] for r in rows if r["kind"] == "matmul"
         and r["dtype"] == "float32"),
        default=None,
    )
    best_gbps = max(
        (r["gbps"] for r in rows if r["kind"] == "stream"), default=None
    )
    out = {
        "device": str(jax.devices()[0].device_kind),
        "ceiling_bf16_tflops": best_bf16,
        "ceiling_f32_tflops": best_f32,
        "ceiling_hbm_gbps": best_gbps,
        "rows": rows,
    }
    if peaks.get("flops") and best_bf16:
        out["spec_bf16_tflops"] = round(peaks["flops"] / 1e12, 1)
        out["ceiling_vs_spec_pct"] = round(
            100 * best_bf16 * 1e12 / peaks["flops"], 1
        )
    return out


def _markdown(summary) -> str:
    lines = [
        "| kind | shape | dtype | ms/step | achieved |",
        "|---|---|---|---|---|",
    ]
    for r in summary["rows"]:
        if r["kind"] == "matmul":
            shape, val = f"{r['n']}×{r['n']}", f"{r['tflops']} TFLOPS"
        else:
            shape, val = f"{r['mb']} MiB", f"{r['gbps']} GB/s"
        lines.append(
            f"| {r['kind']} | {shape} | {r['dtype']} | {r['ms_per_step']} "
            f"| {val} |"
        )
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", type=int, nargs="+", default=[1024, 2048, 4096])
    ap.add_argument(
        "--iters", type=int, default=None,
        help="chain length (default: auto from the extra-work targets)",
    )
    ap.add_argument("--stream-mb", type=int, default=256)
    ap.add_argument("--write-docs", action="store_true")
    args = ap.parse_args(argv)

    rows = run(args.sizes, args.iters, args.stream_mb)
    summary = summarize(rows)
    print(json.dumps({k: v for k, v in summary.items() if k != "rows"}))

    if args.write_docs:
        docs = os.path.join(
            os.path.dirname(__file__), "..", "..", "docs", "benchmarks"
        )
        os.makedirs(docs, exist_ok=True)
        spec = (
            f"{summary['spec_bf16_tflops']} TFLOPS spec peak → the "
            f"measured ceiling is **{summary['ceiling_vs_spec_pct']}% of "
            f"spec**"
            if "spec_bf16_tflops" in summary
            else "spec peak unknown for this device kind"
        )
        with open(os.path.join(docs, "roofline_tpu.md"), "w") as f:
            f.write(
                "# Measured roofline — tunneled "
                f"{summary['device']}\n\n"
                "Generated by `python -m distributed_tensorflow_tpu."
                "tools.roofline_bench --write-docs` (scan-chained "
                "dispatches, D2H-fetch barrier — CLAUDE.md measurement "
                "discipline).\n\n" + _markdown(summary) + "\n\n"
                f"**Ceilings**: bf16 matmul "
                f"{summary['ceiling_bf16_tflops']} TFLOPS, f32 matmul "
                f"{summary['ceiling_f32_tflops']} TFLOPS, HBM stream "
                f"{summary['ceiling_hbm_gbps']} GB/s. {spec}.\n\n"
                "These are the *achieved* roofs every other record here "
                "should be read against: `lm_bench --ceiling-tflops "
                f"{summary['ceiling_bf16_tflops']}` re-expresses the LM "
                "MFU column against the bf16 ceiling (an 'MFU*' of 100% "
                "means the training step saturates what the chip+tunnel "
                "actually delivers to ANY workload, spec be damned).\n"
            )
        with open(os.path.join(docs, "roofline_tpu.json"), "w") as f:
            json.dump(summary, f, indent=1)
        print(f"wrote {os.path.join(docs, 'roofline_tpu.md')}")
    return summary


if __name__ == "__main__":
    main()
