"""Save-boundary stall: synchronous vs async checkpoint pipeline.

The round-22 tentpole claim (docs/resilience.md §async-checkpoint) in
measured form: with ``async_checkpoint=True`` the training loop's pause
at a save boundary is the device→host snapshot cost, not the full
serialize+CRC+manifest+GC write — the writer thread pays that off the
hot path. This bench times exactly the boundary pause (the ``save()``
call itself) for the SAME state pytree under both modes and emits the
``ckpt_stall_ms_{sync,async}`` bench_point series (unit ``ms`` — the
regression gate fails HIGH, so an async path that quietly starts
blocking on the writer again fails the fast tier).

Methodology notes, in the repo's bench discipline:

- Each timed save is drained (``wait_pending``) BEFORE the next timing
  window opens, so every async point measures the snapshot handoff and
  never a queue-supersede fast path (which would flatter the number).
- The state is plain host-backed jax arrays on CPU — the honest
  BASELINE. On a real TPU the device→host snapshot crosses the tunnel
  while the sync write crosses it AND hits storage, so the win grows
  with state size and storage latency; CPU rows carry ``device: cpu``
  per the round-13 provenance convention.
- Median over ``--reps`` (default 5) after one warm save per mode (the
  warm save absorbs orbax's first-write setup and the directory
  creation).

Usage::

    python -m distributed_tensorflow_tpu.tools.ckpt_bench --events \
        docs/benchmarks/events.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import sys
import tempfile
import time


def _make_state(nparams: int):
    import jax
    import jax.numpy as jnp

    # A dict-of-arrays pytree shaped like a small trainer state: a few
    # large leaves (params-like) and a couple of scalars (step/opt
    # hyper-state) so the manifest walks a realistic file mix.
    keys = jax.random.split(jax.random.key(0), 4)
    quarter = nparams // 4
    return {
        f"w{i}": jax.random.normal(k, (quarter,), dtype=jnp.float32)
        for i, k in enumerate(keys)
    } | {
        "global_step": jnp.asarray(0, dtype=jnp.int32),
        "scale": jnp.asarray(1.0, dtype=jnp.float32),
    }


def _time_mode(state, *, async_checkpoint: bool, reps: int) -> dict:
    from distributed_tensorflow_tpu.train.supervisor import Supervisor

    tmp = tempfile.mkdtemp(prefix="ckpt_bench_")
    try:
        sup = Supervisor(
            checkpoint_dir=tmp, async_checkpoint=async_checkpoint
        )
        sup.save(state, 0)  # warm: orbax setup + dir creation
        sup.wait_pending()
        stalls_ms = []
        for r in range(reps):
            t0 = time.perf_counter()
            sup.save(state, r + 1)
            stalls_ms.append((time.perf_counter() - t0) * 1e3)
            # Drain OUTSIDE the timing window: each point measures a
            # boundary pause with an idle writer, never the supersede
            # fast path.
            sup.wait_pending()
        return {
            "mode": "async" if async_checkpoint else "sync",
            "stall_ms": round(statistics.median(stalls_ms), 3),
            "stalls_ms": [round(s, 3) for s in stalls_ms],
            "reps": reps,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run(nparams: int = 2_000_000, reps: int = 5) -> list[dict]:
    import jax

    state = jax.tree.map(
        lambda x: jax.device_put(x).block_until_ready(),
        _make_state(nparams),
    )
    return [
        _time_mode(state, async_checkpoint=False, reps=reps),
        _time_mode(state, async_checkpoint=True, reps=reps),
    ]


def emit_bench_events(results: list[dict], events_path: str) -> int:
    from distributed_tensorflow_tpu.observability.journal import (
        EventJournal,
    )

    j = EventJournal(events_path)
    n = 0
    for r in results:
        j.emit(
            "bench_point",
            run="ckpt_bench",
            name=f"ckpt_stall_ms_{r['mode']}",
            value=float(r["stall_ms"]),
            unit="ms",
            tool="ckpt_bench",
            device="cpu",
            reps=r["reps"],
        )
        n += 1
    return n


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--nparams", type=int, default=2_000_000)
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--json", default=None, help="also write results here")
    p.add_argument(
        "--events",
        default=None,
        help="append ckpt_stall_ms_{sync,async} bench_point events to "
        "this events.jsonl (the gate-covered series)",
    )
    args = p.parse_args(argv)
    results = run(nparams=args.nparams, reps=args.reps)
    sync = next(r for r in results if r["mode"] == "sync")
    a = next(r for r in results if r["mode"] == "async")
    ratio = sync["stall_ms"] / max(a["stall_ms"], 1e-9)
    # The acceptance claim: async's boundary pause is MEASURABLY below
    # sync's — we assert a conservative 2x so tunnel-class jitter on a
    # loaded container never flakes the check (measured ~10-40x on CPU).
    check = "PASS" if ratio >= 2.0 else "FAIL"
    for r in results:
        print(json.dumps(r))
    print(
        f"{check}: async save-boundary stall {a['stall_ms']} ms vs sync "
        f"{sync['stall_ms']} ms ({ratio:.1f}x)",
        file=sys.stderr,
    )
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
    if args.events:
        n = emit_bench_events(results, args.events)
        print(
            f"appended {n} bench_point events to {args.events}",
            file=sys.stderr,
        )
    return 0 if check == "PASS" else 1


if __name__ == "__main__":
    sys.exit(main())
