"""Run report from the event journal: replay ``events.jsonl`` into a
human summary + a Perfetto-loadable trace.

The reader half of the telemetry layer (docs/observability.md):
everything the framework journals — Step/Cost lines, epoch metrics,
lifecycle events (restart/resize/rollback/preemption/restore), checkpoint
saves, serving admissions/completions, metrics snapshots, host spans —
reconstructs here WITHOUT grep'ing stdout::

    python -m distributed_tensorflow_tpu.tools.obs_report <logdir|events.jsonl>
    python -m distributed_tensorflow_tpu.tools.obs_report run/ --json
    python -m distributed_tensorflow_tpu.tools.obs_report run/ --trace t.json
    python -m distributed_tensorflow_tpu.tools.obs_report run/ --requests
    python -m distributed_tensorflow_tpu.tools.obs_report gang_logdir/ --gang
    python -m distributed_tensorflow_tpu.tools.obs_report fleet_dir/ --fleet

``--trace`` exports the journal's ``span`` events in the chrome trace
event format (load in Perfetto / chrome://tracing). ``--json`` prints the
summary dict instead of the rendered report. ``--requests`` (round 12)
joins a TextServer journal's trace ids back into per-request timelines —
queue wait, prefill, decode chunks, TTFT, latency, all from the journal
alone. ``--gang`` treats the path as a GANG logdir: every rank's journal
is merged into one skew-aligned fleet timeline
(observability/aggregate.py); with ``--trace`` the export has one track
per rank, restarts/resizes visible on all of them. ``--fleet`` (round
16) is the serving twin: the router's journal + every replica's merge,
and per-request timelines join on TRACE ids — submit on the router,
admission on replica A, completion on replica B after a failover, one
id throughout (serve_fleet.py; docs/serving.md §fleet).

jax-free (lean-import convention): runs anywhere the journal was written,
including degraded containers and machines with no accelerator stack.
"""

from __future__ import annotations

import argparse
import json
import sys

from distributed_tensorflow_tpu.observability import aggregate
from distributed_tensorflow_tpu.observability import format as obs_format
from distributed_tensorflow_tpu.observability.journal import read_events
from distributed_tensorflow_tpu.observability.spans import chrome_trace

LIFECYCLE_KINDS = (
    "restart",
    "restart_exhausted",
    "resize",
    "resize_denied",
    "rollback",
    "rollback_compiled",
    "preemption",
    "restore",
)


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile over raw per-event values (the journal
    keeps every completion, so no bucket estimation is needed here)."""
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def summarize(events: list[dict]) -> dict:
    """Fold a journal into the run summary dict (the ``--json`` payload)."""
    by_kind: dict = {}
    for ev in events:
        by_kind.setdefault(ev.get("kind", "?"), []).append(ev)

    out: dict = {
        "events": len(events),
        "kinds": {k: len(v) for k, v in sorted(by_kind.items())},
    }
    span = [e.get("ts") for e in events if isinstance(e.get("ts"), (int, float))]
    if span:
        out["wall_span_s"] = round(max(span) - min(span), 3)

    # -- training ---------------------------------------------------------
    steps = by_kind.get("step", [])
    if steps:
        out["training"] = {
            "step_lines": len(steps),
            "first_step": steps[0].get("step"),
            "last_step": steps[-1].get("step"),
            "last_cost": steps[-1].get("cost"),
            "last_avg_ms": steps[-1].get("avg_ms"),
        }
    epochs = by_kind.get("epoch", [])
    if epochs:
        out["epochs"] = [
            {
                "metric": e.get("metric"),
                "value": e.get("value"),
                "total_time_s": e.get("total_time_s"),
            }
            for e in epochs
        ]
    finals = by_kind.get("final", [])
    if finals:
        out["final_cost"] = finals[-1].get("cost")

    # -- lifecycle history (the Restart/Resize/Rollback/... replay) -------
    history = []
    for ev in events:
        kind = ev.get("kind")
        if kind in LIFECYCLE_KINDS:
            try:
                lines = obs_format.render(kind, ev)
            except KeyError:
                lines = [f"{kind}: {ev}"]  # unrenderable: still replayed
            history.append({"ts": ev.get("ts"), "kind": kind, "line": lines[0]})
    if history:
        out["lifecycle"] = history

    saves = by_kind.get("checkpoint_save", [])
    if saves:
        out["checkpoints"] = {
            "saves": len(saves),
            "bytes_total": sum(int(e.get("bytes", 0)) for e in saves),
            "last_step": saves[-1].get("step"),
            "mean_duration_s": round(
                sum(float(e.get("duration_s", 0.0)) for e in saves)
                / len(saves),
                4,
            ),
        }

    # -- serving ----------------------------------------------------------
    admissions = by_kind.get("admission", [])
    completions = by_kind.get("completion", [])
    if admissions or completions:
        serving: dict = {
            "admissions": len(admissions),
            "completions": len(completions),
        }
        if completions:
            lat = sorted(float(e.get("latency_s", 0.0)) for e in completions)
            ttft = sorted(float(e.get("ttft_s", 0.0)) for e in completions)
            tokens = sum(int(e.get("tokens", 0)) for e in completions)
            t0 = min(e["ts"] for e in completions + admissions)
            t1 = max(e["ts"] for e in completions)
            serving.update(
                tokens=tokens,
                tokens_per_s=round(tokens / max(t1 - t0, 1e-9), 2),
                latency_s={
                    "p50": round(_percentile(lat, 0.50), 4),
                    "p90": round(_percentile(lat, 0.90), 4),
                    "p99": round(_percentile(lat, 0.99), 4),
                },
                ttft_s={
                    "p50": round(_percentile(ttft, 0.50), 4),
                    "p90": round(_percentile(ttft, 0.90), 4),
                    "p99": round(_percentile(ttft, 0.99), 4),
                },
            )
        out["serving"] = serving

    # -- serving cache: prefix hits, speculation, pool occupancy ----------
    cache_sec: dict = {}
    pref = [e for e in admissions if "prefix_hit_blocks" in e]
    if pref:
        hits = sum(int(e.get("prefix_hit_blocks", 0)) for e in pref)
        miss = sum(int(e.get("prefix_miss_blocks", 0)) for e in pref)
        cache_sec["prefix"] = {
            "hit_blocks": hits,
            "miss_blocks": miss,
            "hit_rate": round(hits / max(hits + miss, 1), 3),
        }
    spec = by_kind.get("spec_verify", [])
    if spec:
        prop = sum(int(e.get("proposed", 0)) for e in spec)
        acc = sum(int(e.get("accepted", 0)) for e in spec)
        emitted = sum(int(e.get("emitted", 0)) for e in spec)
        cache_sec["speculation"] = {
            "verify_dispatches": len(spec),
            "proposed": prop,
            "accepted": acc,
            "acceptance_rate": round(acc / max(prop, 1), 3),
            "tokens_per_dispatch": round(emitted / len(spec), 2),
        }
    snaps_for_pool = by_kind.get("metrics", [])
    if snaps_for_pool:
        mm = snaps_for_pool[-1].get("metrics", {})
        used, total = mm.get("kv_blocks_used"), mm.get("kv_blocks_total")
        if used and total and total[0].get("value"):
            cache_sec["kv_blocks"] = {
                "used": used[0].get("value"),
                "total": total[0].get("value"),
                "occupancy": round(
                    used[0]["value"] / total[0]["value"], 3
                ),
            }
    # Cache geometry (round 15): dtype + honest byte accounting from the
    # server's construction-time serving_cache_config event — a quantized
    # pool must read as "int8, half the bytes/slot", not silently as a
    # bigger chip. Last event wins (one journal can span several server
    # incarnations; the newest geometry is the live one).
    cfgs = by_kind.get("serving_cache_config", [])
    if cfgs:
        cfg = cfgs[-1]
        cache_sec["geometry"] = {
            "kv_dtype": cfg.get("kv_dtype"),
            "decode_matmul_dtype": cfg.get("decode_matmul_dtype"),
            "paged": cfg.get("paged"),
            "position_bytes": cfg.get("position_bytes"),
            "slot_bytes": cfg.get("slot_bytes"),
            "pool_bytes": cfg.get("pool_bytes"),
        }
    if cache_sec:
        out["serving_cache"] = cache_sec

    # -- comm/compute (round 14: dp vs diloco sync-round accounting) ------
    # Grouped per (mode, sync_every, delta_dtype): one journal can span a
    # mode change (cross-topology resume), a sync_every change (a POLICY
    # key — a resume under a new H is explicitly allowed), or a
    # delta-compression change, and a blended ratio would misstate the
    # H× / compression headlines each segment exists to show.
    comm = by_kind.get("comm_stats", [])
    if comm:
        segs: dict = {}
        for e in comm:
            key = (e.get("mode"), e.get("sync_every"), e.get("delta_dtype"))
            s = segs.setdefault(
                key,
                {
                    "mode": key[0],
                    "sync_every": key[1],
                    "delta_dtype": key[2],
                    "steps": 0,
                    "sync_rounds": 0,
                    "allreduce_bytes": 0,
                    "payload_bytes": 0,
                },
            )
            s["steps"] += int(e.get("steps", 0))
            s["sync_rounds"] += int(e.get("sync_rounds", 0))
            s["allreduce_bytes"] += int(e.get("allreduce_bytes", 0))
            # Round-14 journals predate the payload field: the wire
            # payload WAS the dense all-reduce.
            s["payload_bytes"] += int(
                e.get("payload_bytes", e.get("allreduce_bytes", 0))
            )
        for s in segs.values():
            # Steps of compute per gang sync round — dp is 1.0 by
            # construction; diloco's value IS the H× comm-reduction
            # headline (measured from the journal, not asserted).
            s["steps_per_round"] = round(
                s["steps"] / max(s["sync_rounds"], 1), 2
            )
            # Round 17: bytes actually on the wire per round, and the
            # effective compression vs the dense payload (1.0 = full
            # precision).
            s["bytes_per_round"] = round(
                s["payload_bytes"] / max(s["sync_rounds"], 1), 1
            )
            s["compression_x"] = round(
                s["allreduce_bytes"] / max(s["payload_bytes"], 1), 2
            )
        out["comm"] = list(segs.values())

    # -- bench points (serve_bench / lm_bench emitters) -------------------
    bench = by_kind.get("bench_point", [])
    if bench:
        out["bench_points"] = [
            {k: e.get(k) for k in ("tool", "name", "value", "unit")}
            for e in bench
        ]

    # -- metrics snapshots (last one wins) --------------------------------
    snaps = by_kind.get("metrics", [])
    if snaps:
        out["metrics"] = snaps[-1].get("metrics", {})

    spans = by_kind.get("span", [])
    if spans:
        out["spans"] = {"count": len(spans)}
        # The dispatch p50 is a DISPATCH statistic — checkpoint/profiler
        # spans (seconds) would otherwise dominate the median.
        disp = sorted(
            float(e.get("dur_us", 0.0))
            for e in spans
            if e.get("cat") == "dispatch"
        )
        if disp:
            out["spans"]["p50_dispatch_ms"] = round(
                _percentile(disp, 0.5) / 1000, 3
            )
    return out


def render_report(summary: dict) -> str:
    lines = [
        f"events: {summary['events']}"
        + (
            f"  (wall span {summary['wall_span_s']}s)"
            if "wall_span_s" in summary
            else ""
        ),
        "by kind: "
        + ", ".join(f"{k}={n}" for k, n in summary["kinds"].items()),
    ]
    tr = summary.get("training")
    if tr:
        lines.append(
            f"training: steps {tr['first_step']}..{tr['last_step']} "
            f"({tr['step_lines']} step lines), last cost "
            f"{tr['last_cost']:.4f}, last AvgTime {tr['last_avg_ms']:.2f}ms"
        )
    for e in summary.get("epochs", []):
        lines.append(
            f"  epoch: {e['metric']}={e['value']:.4f} "
            f"(total {e['total_time_s']:.2f}s)"
        )
    if "final_cost" in summary:
        lines.append(f"final cost: {summary['final_cost']:.4f}")
    ck = summary.get("checkpoints")
    if ck:
        lines.append(
            f"checkpoints: {ck['saves']} saves, {ck['bytes_total']} bytes, "
            f"last step {ck['last_step']}, mean {ck['mean_duration_s']}s"
        )
    if summary.get("lifecycle"):
        lines.append("lifecycle history:")
        for h in summary["lifecycle"]:
            lines.append(f"  [{h['ts']:.3f}] {h['line']}")
    sv = summary.get("serving")
    if sv:
        lines.append(
            f"serving: {sv['admissions']} admissions, "
            f"{sv['completions']} completions"
            + (
                f", {sv['tokens']} tokens @ {sv['tokens_per_s']} tok/s; "
                f"latency p50/p90/p99 = {sv['latency_s']['p50']}/"
                f"{sv['latency_s']['p90']}/{sv['latency_s']['p99']}s; "
                f"TTFT p50 = {sv['ttft_s']['p50']}s"
                if "tokens" in sv
                else ""
            )
        )
    sc = summary.get("serving_cache")
    if sc:
        parts = []
        p = sc.get("prefix")
        if p:
            parts.append(
                f"prefix {p['hit_blocks']}/{p['hit_blocks'] + p['miss_blocks']}"
                f" blocks cached (hit rate {p['hit_rate']})"
            )
        s2 = sc.get("speculation")
        if s2:
            parts.append(
                f"speculation acceptance {s2['acceptance_rate']} "
                f"({s2['accepted']}/{s2['proposed']}), "
                f"{s2['tokens_per_dispatch']} tokens/dispatch over "
                f"{s2['verify_dispatches']} verifies"
            )
        kb = sc.get("kv_blocks")
        if kb:
            parts.append(
                f"kv pool {kb['used']:.0f}/{kb['total']:.0f} blocks "
                f"({kb['occupancy']})"
            )
        g = sc.get("geometry")
        if g:
            wo = (
                f", weights {g['decode_matmul_dtype']}"
                if g.get("decode_matmul_dtype")
                else ""
            )
            parts.append(
                f"cache {g.get('kv_dtype')}{wo}: "
                f"{g.get('slot_bytes')} bytes/slot, "
                f"{g.get('pool_bytes')} bytes pool"
            )
        lines.append("serving cache: " + "; ".join(parts))
    for cm in summary.get("comm", []):
        lines.append(
            f"comm: mode={cm['mode']} sync_every={cm['sync_every']} — "
            f"{cm['sync_rounds']} sync rounds over {cm['steps']} steps "
            f"({cm['steps_per_round']} steps/round), "
            f"{cm['allreduce_bytes']} bytes all-reduced"
        )
        # Round 17: wire payload beside the dense accounting — only when
        # the journal carries the compressed-delta fields (old journals
        # and full-precision runs render exactly the round-14 line).
        if cm.get("delta_dtype"):
            lines.append(
                f"comm payload: {cm['delta_dtype']} deltas — "
                f"{cm['payload_bytes']} bytes on the wire "
                f"({cm['bytes_per_round']} bytes/round, "
                f"{cm['compression_x']}x compressed)"
            )
    for b in summary.get("bench_points", []):
        lines.append(
            f"bench: {b.get('tool')}/{b.get('name')} = {b.get('value')} "
            f"{b.get('unit') or ''}".rstrip()
        )
    sp = summary.get("spans")
    if sp:
        p50 = (
            f" (dispatch p50 {sp['p50_dispatch_ms']}ms)"
            if "p50_dispatch_ms" in sp
            else ""
        )
        lines.append(
            f"spans: {sp['count']} recorded{p50} — export with --trace"
        )
    return "\n".join(lines)


def reconstruct_requests(events: list[dict]) -> list[dict]:
    """Per-request serving timelines from the journal alone (round 12):
    join ``request_submit`` → ``admission`` → prefill/decode/spec_verify
    spans (by the ``rids`` each dispatch span carries) → ``completion``
    on rid + trace id. Returns one record per request, submission order::

        {rid, trace, prompt_len, max_new, queue_wait_s, prefill_ms,
         decode_chunks, decode_ms, ttft_s, latency_s, tokens, done}

    Decode attribution is wall-clock per resident request: a chunk
    dispatch's duration counts toward EVERY request resident in it (they
    all waited on it) — the sum across requests exceeds wall time by
    design, exactly like CPU time on a multicore host. Pre-round-12
    journals (no request_submit, no span rids) still reconstruct the
    admission/completion half."""
    reqs: dict = {}

    def rec(rid) -> dict:
        return reqs.setdefault(
            rid,
            {
                "rid": rid,
                "trace": None,
                "prompt_len": None,
                "max_new": None,
                "queue_wait_s": None,
                "prefill_ms": 0.0,
                "decode_chunks": 0,
                "decode_ms": 0.0,
                "ttft_s": None,
                "latency_s": None,
                "tokens": None,
                "done": False,
                "priority": 0,
                "shed": False,
            },
        )

    for ev in events:
        kind = ev.get("kind")
        if kind == "request_submit":
            r = rec(ev.get("rid"))
            r["trace"] = ev.get("trace")
            r["prompt_len"] = ev.get("prompt_len")
            r["max_new"] = ev.get("max_new")
            # Round 21: absent on default-path journals (byte parity).
            r["priority"] = int(ev.get("priority", 0))
        elif kind == "request_shed":
            r = rec(ev.get("rid"))
            r["trace"] = r["trace"] or ev.get("trace")
            r["shed"] = True
        elif kind == "admission":
            r = rec(ev.get("rid"))
            r["trace"] = r["trace"] or ev.get("trace")
            if r["prompt_len"] is None:
                r["prompt_len"] = ev.get("prompt_len")
            r["queue_wait_s"] = ev.get("queue_wait_s")
        elif kind == "span":
            args = ev.get("args") or {}
            rids = args.get("rids")
            if not rids:
                continue
            dur_ms = float(ev.get("dur_us", 0.0)) / 1000.0
            if ev.get("name") == "prefill":
                for rid in rids:
                    rec(rid)["prefill_ms"] = round(
                        rec(rid)["prefill_ms"] + dur_ms, 3
                    )
            elif ev.get("name") in ("decode_chunk", "spec_verify"):
                for rid in rids:
                    r = rec(rid)
                    r["decode_chunks"] += 1
                    r["decode_ms"] = round(r["decode_ms"] + dur_ms, 3)
        elif kind == "completion":
            r = rec(ev.get("rid"))
            r["trace"] = r["trace"] or ev.get("trace")
            r["ttft_s"] = ev.get("ttft_s")
            r["latency_s"] = ev.get("latency_s")
            r["tokens"] = ev.get("tokens")
            r["done"] = True
    return [reqs[k] for k in sorted(reqs)]


def request_percentiles(records: list[dict]) -> dict | None:
    """p50/p95/p99 of TTFT and end-to-end latency over completed request
    records (the serve_bench SLO rows). None when nothing completed."""
    done = [r for r in records if r["done"] and r["latency_s"] is not None]
    if not done:
        return None
    out = {"requests": len(done)}
    for key in ("ttft_s", "latency_s"):
        vals = sorted(float(r[key]) for r in done if r[key] is not None)
        out[key] = {
            "p50": round(_percentile(vals, 0.50), 4),
            "p95": round(_percentile(vals, 0.95), 4),
            "p99": round(_percentile(vals, 0.99), 4),
        }
    return out


def render_requests(records: list[dict]) -> str:
    lines = [
        "rid  trace             queue(s)  prefill(ms)  decode(ms)/chunks  "
        "ttft(s)  latency(s)  tokens",
    ]
    for r in records:
        fmt = lambda v, spec: ("-" if v is None else format(v, spec))  # noqa: E731
        lines.append(
            f"{r['rid']:<4} {str(r['trace'] or '-'):<17} "
            f"{fmt(r['queue_wait_s'], '.4f'):>8}  {r['prefill_ms']:>11.3f}  "
            f"{r['decode_ms']:>10.3f}/{r['decode_chunks']:<6} "
            f"{fmt(r['ttft_s'], '.4f'):>7}  {fmt(r['latency_s'], '.4f'):>10}  "
            f"{fmt(r['tokens'], 'd'):>6}"
            + (
                "  (shed)"
                if r.get("shed")
                else ("" if r["done"] else "  (in flight)")
            )
        )
    # Round 21 per-class rollup: rendered only when the workload used
    # priority classes or shed anything — default journals keep the
    # round-12 output byte-identical.
    if any(r.get("priority") or r.get("shed") for r in records):
        classes: dict = {}
        for r in records:
            c = classes.setdefault(
                int(r.get("priority") or 0), {"n": 0, "done": 0, "shed": 0,
                                              "ttft": []}
            )
            c["n"] += 1
            c["done"] += bool(r["done"] and not r.get("shed"))
            c["shed"] += bool(r.get("shed"))
            if r.get("ttft_s") is not None:
                c["ttft"].append(float(r["ttft_s"]))
        for prio, c in sorted(classes.items(), reverse=True):
            p95 = (
                round(_percentile(sorted(c["ttft"]), 0.95), 4)
                if c["ttft"]
                else "-"
            )
            lines.append(
                f"class p{prio}: {c['n']} requests, {c['done']} done, "
                f"{c['shed']} shed (rate "
                f"{round(c['shed'] / max(c['n'], 1), 4)}), TTFT p95 {p95}s"
            )
    pct = request_percentiles(records)
    if pct:
        lines.append(
            f"TTFT p50/p95/p99 = {pct['ttft_s']['p50']}/"
            f"{pct['ttft_s']['p95']}/{pct['ttft_s']['p99']}s; latency "
            f"p50/p95/p99 = {pct['latency_s']['p50']}/"
            f"{pct['latency_s']['p95']}/{pct['latency_s']['p99']}s "
            f"over {pct['requests']} requests"
        )
    return "\n".join(lines)


def reconstruct_fleet_requests(merged: dict) -> list[dict]:
    """Fleet-wide per-request timelines (round 16): the router journal
    and every replica journal merged (observability/aggregate.py), then
    joined on the TRACE id — the one identity a request keeps across
    replicas. A failover shows as one trace submitted on the router,
    admitted on replica A, re-routed, and completed on replica B::

        {rid, trace, prompt_len, replicas: [admission hosts in order],
         completed_on, failovers, reroutes, tokens, ttft_s, latency_s,
         done, cancelled}

    ``latency_s``/``ttft_s`` are FLEET quantities on the merged (skew-
    adjusted) clock: router submit → the serving replica's completion /
    first token — queue wait, routing, any failover latency included.
    Requests with no terminal event render as in flight (a fleet that
    lost one would show it here — the zero-loss proof's observable)."""
    driver = "driver" if "driver" in merged["ranks"] else (
        merged["ranks"][0] if merged["ranks"] else None
    )
    recs: dict = {}
    order: list = []

    def rec(trace) -> dict:
        if trace not in recs:
            order.append(trace)
            recs[trace] = {
                "rid": None,
                "trace": trace,
                "prompt_len": None,
                "replicas": [],
                "completed_on": None,
                "failovers": 0,
                "reroutes": 0,
                "tokens": None,
                "ttft_s": None,
                "latency_s": None,
                "submit_ts": None,
                "done": False,
                "cancelled": False,
                "rejected": False,
                "migrated": False,
                "migration": None,
            }
        return recs[trace]

    def migration(trace) -> dict:
        r = rec(trace)
        if r["migration"] is None:
            r["migration"] = {
                "from": None, "to": None, "blocks": None, "nbytes": None,
                "post_ms": None, "import_ms": None, "fallback": None,
            }
        return r["migration"]

    for ev in merged["events"]:
        trace = ev.get("trace")
        if not trace:
            continue
        kind = ev.get("kind")
        src = ev.get("_src")
        if kind == "request_submit":
            r = rec(trace)
            if src == driver or r["submit_ts"] is None:
                r["submit_ts"] = ev.get("ts")
                r["prompt_len"] = ev.get("prompt_len", r["prompt_len"])
            if src == driver:
                r["rid"] = ev.get("rid")
        elif kind == "request_reroute" and src == driver:
            r = rec(trace)
            r["reroutes"] += 1
            if ev.get("reason") == "replica_dead":
                r["failovers"] += 1
        elif kind == "admission" and src != driver:
            rec(trace)["replicas"].append(src)
        elif kind == "completion" and src != driver:
            r = rec(trace)
            r["completed_on"] = src
            r["tokens"] = ev.get("tokens")
            r["done"] = True
            ts, lat, ttft = ev.get("ts"), ev.get("latency_s"), ev.get("ttft_s")
            if r["submit_ts"] is not None and isinstance(ts, (int, float)):
                r["latency_s"] = round(ts - r["submit_ts"], 6)
                if isinstance(lat, (int, float)) and isinstance(
                    ttft, (int, float)
                ):
                    # The replica's first-token instant on the wall clock
                    # (completion ts − replica latency + replica TTFT),
                    # re-anchored to the ROUTER's submit.
                    r["ttft_s"] = round(
                        (ts - lat + ttft) - r["submit_ts"], 6
                    )
        elif kind == "request_migrated" and src == driver:
            # Round 23: the prefill→decode handoff, one trace across
            # both legs — the join this function exists to render.
            r = rec(trace)
            r["migrated"] = True
            m = migration(trace)
            m["from"] = ev.get("from_replica")
            m["blocks"] = ev.get("blocks")
            m["nbytes"] = ev.get("nbytes")
        elif kind == "kv_migration":
            m = migration(trace)
            ph = ev.get("phase")
            if ph == "post":
                m["post_ms"] = ev.get("wall_ms")
            elif ph == "import":
                m["to"] = src
                m["import_ms"] = ev.get("wall_ms")
            elif ph in ("fallback", "post_failed"):
                m["fallback"] = ev.get("reason", ph)
        elif kind == "request_cancelled":
            rec(trace)["cancelled"] = True
        elif kind == "fleet_result" and ev.get("status") == "rejected":
            # A terminal router-side rejection (replica validation or
            # re-route budget) is a deliberate, journaled outcome — it
            # must not render as a LOST request.
            rec(trace)["rejected"] = True
    out = [recs[t] for t in order]
    out.sort(key=lambda r: (r["rid"] is None, r["rid"], r["trace"]))
    for r in out:
        del r["submit_ts"]
    return out


def render_fleet_requests(records: list[dict]) -> str:
    lines = [
        "rid  trace             path                    failover  ttft(s)"
        "  latency(s)  tokens  status",
    ]
    fmt = lambda v, spec: ("-" if v is None else format(v, spec))  # noqa: E731
    for r in records:
        path = "->".join(r["replicas"]) or "-"
        if r["cancelled"]:
            status = "cancelled"
        elif r["done"]:
            status = "done+migr" if r.get("migrated") else "done"
        elif r.get("rejected"):
            status = "rejected"
        else:
            status = "IN FLIGHT"
        lines.append(
            f"{fmt(r['rid'], 'd'):<4} {str(r['trace'] or '-'):<17} "
            f"{path:<23} {r['failovers']:>8}  {fmt(r['ttft_s'], '.4f'):>7}"
            f"  {fmt(r['latency_s'], '.4f'):>10}  {fmt(r['tokens'], 'd'):>6}"
            f"  {status}"
        )
    # rid None = replica-LOCAL traffic (warmup requests a replica served
    # before joining the fleet): rendered above for completeness, but the
    # fleet summary must not fold multi-second compile warmups into the
    # percentiles the readiness gate exists to exclude.
    fleet = [r for r in records if r["rid"] is not None]
    local = len(records) - len(fleet)
    done = [r for r in fleet if r["done"]]
    lost = [
        r
        for r in fleet
        if not r["done"] and not r["cancelled"] and not r.get("rejected")
    ]
    failovers = sum(r["failovers"] for r in fleet)
    migrated = [r for r in fleet if r.get("migrated")]
    tail = (
        f"{len(fleet)} requests: {len(done)} done, "
        f"{sum(r['cancelled'] for r in fleet)} cancelled, "
        f"{sum(bool(r.get('rejected')) for r in fleet)} rejected, "
        f"{len(lost)} in flight/lost; {failovers} failover(s)"
        + (f"; {len(migrated)} migrated" if migrated else "")
        + (f" (+{local} replica-local)" if local else "")
    )
    if migrated:
        ms = [r["migration"] or {} for r in migrated]
        bytes_ = [m["nbytes"] for m in ms if m.get("nbytes")]
        blocks = [m["blocks"] for m in ms if m.get("blocks")]
        fallbacks = sum(1 for m in ms if m.get("fallback"))
        line = "kv migration:"
        if blocks:
            line += f" avg blocks {sum(blocks) / len(blocks):.1f}"
        if bytes_:
            line += f", avg {sum(bytes_) / len(bytes_) / 1024:.1f} KiB/req"
        post = sorted(m["post_ms"] for m in ms if m.get("post_ms") is not None)
        imp = sorted(
            m["import_ms"] for m in ms if m.get("import_ms") is not None
        )
        if post:
            line += f", post p50 {_percentile(post, 0.50):.2f} ms"
        if imp:
            line += f", import p50 {_percentile(imp, 0.50):.2f} ms"
        line += f", {fallbacks} fallback(s)"
        lines.append(line)
    pct = request_percentiles(
        [
            {"done": True, "ttft_s": r["ttft_s"], "latency_s": r["latency_s"]}
            for r in done
        ]
    )
    if pct:
        tail += (
            f"; fleet TTFT p50/p95 = {pct['ttft_s']['p50']}/"
            f"{pct['ttft_s']['p95']}s, latency p50/p95 = "
            f"{pct['latency_s']['p50']}/{pct['latency_s']['p95']}s"
        )
    lines.append(tail)
    return "\n".join(lines)


def render_gang(summary: dict) -> str:
    lines = [
        f"fleet: {len(summary['ranks'])} journals, "
        f"{summary['events']} events, wall span {summary['wall_span_s']}s"
    ]
    for label, r in summary["ranks"].items():
        skew = summary["skew_s"].get(label, 0.0)
        starts = summary["worker_starts"].get(label, 0)
        prog = r.get("last_progress")
        role = f" [{r['role']}]" if r.get("role") else ""
        lines.append(
            f"  {label}{role}: {r['events']} events over {r['wall_span_s']}s"
            + (f", skew {skew}s" if skew else "")
            + (f", {starts} incarnation(s)" if starts else "")
            + (
                f", last progress step {prog['step']} "
                f"({prog['age_s']}s ago)"
                if prog
                else ""
            )
        )
    if summary["lifecycle"]:
        lines.append("gang lifecycle:")
        for h in summary["lifecycle"]:
            lines.append(f"  [{h['ts']:.3f}] ({h['src']}) {h['line']}")
    return "\n".join(lines)


def export_trace(events: list[dict], path: str) -> int:
    """Write the journal's span events as a chrome trace; returns the
    span count (0 is legal — an empty trace still loads)."""
    spans = [e for e in events if e.get("kind") == "span"]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(chrome_trace(spans), f)
    return len(spans)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="events.jsonl or a logdir containing one")
    ap.add_argument("--json", action="store_true", help="print the summary dict")
    ap.add_argument("--trace", metavar="OUT", help="export chrome-trace JSON")
    ap.add_argument(
        "--requests",
        action="store_true",
        help="per-request serving timelines (queue/prefill/decode/TTFT) "
        "reconstructed from trace ids",
    )
    ap.add_argument(
        "--gang",
        action="store_true",
        help="treat PATH as a gang logdir: merge every rank's journal "
        "into one fleet timeline (--trace then exports one track per "
        "rank)",
    )
    ap.add_argument(
        "--fleet",
        action="store_true",
        help="treat PATH as a serving-fleet logdir (serve_fleet.py): "
        "merge the router + per-replica journals and render per-request "
        "timelines joined on trace ids — a failover shows as one trace "
        "admitted on replica A and completed on replica B",
    )
    args = ap.parse_args(argv)
    if args.fleet:
        merged = aggregate.merge(args.path)
        records = reconstruct_fleet_requests(merged)
        if args.json:
            print(json.dumps(records))
        else:
            print(render_gang(aggregate.fleet_summary(merged)))
            print(render_fleet_requests(records))
        if args.trace:
            with open(args.trace, "w", encoding="utf-8") as f:
                json.dump(aggregate.gang_chrome_trace(merged), f)
            print(
                f"wrote fleet trace ({len(merged['ranks'])} tracks) to "
                f"{args.trace}"
            )
        return 0
    if args.gang:
        merged = aggregate.merge(args.path)
        summary = aggregate.fleet_summary(merged)
        if args.json:
            print(json.dumps(summary))
        else:
            print(render_gang(summary))
        if args.trace:
            with open(args.trace, "w", encoding="utf-8") as f:
                json.dump(aggregate.gang_chrome_trace(merged), f)
            print(
                f"wrote gang trace ({len(merged['ranks'])} tracks) to "
                f"{args.trace}"
            )
        return 0
    events = read_events(args.path)
    if args.requests:
        records = reconstruct_requests(events)
        if args.json:
            print(json.dumps(records))
        else:
            print(render_requests(records))
        return 0
    summary = summarize(events)
    if args.json:
        print(json.dumps(summary))
    else:
        print(render_report(summary))
    if args.trace:
        n = export_trace(events, args.trace)
        print(f"wrote {n} spans to {args.trace}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
