"""Perf regression gate: the measured record becomes CI-able.

Until now a perf regression was caught by a HUMAN eyeballing the newest
``BENCH_r*.json`` against its predecessors (the "band rule" in
BASELINE.md was prose, not code) — and the bench_point journal the
round-10 emitters write was only ever read back for display. This tool
turns both records into a gate::

    python -m distributed_tensorflow_tpu.tools.regression_gate            # check
    python -m distributed_tensorflow_tpu.tools.regression_gate --json     # dict
    python -m distributed_tensorflow_tpu.tools.regression_gate \
        --journal docs/benchmarks/events.jsonl --tolerance 0.4

For every series it can find —

- ``bench_point`` journal events grouped by ``(tool, name, device)``
  (the serve_bench / lm_bench emitters, ``docs/benchmarks/events.jsonl``
  by default — device is part of the identity, so a tunnel-TPU rerun
  starts its own series instead of colliding with the CPU band), and
- the driver trajectory ``BENCH_r*.json`` at the repo root as the series
  ``(driver, <metric>)``

— the LATEST point is compared against the band of every PRIOR point:
``[min·(1−tol), max·(1+tol)]``. Direction matters: for lower-is-better
units (``ms``, ``s``) only the high side fails; for everything else
(tokens/s, examples/sec, speedup ``x``) only the low side fails — an
improvement is never a regression. A series with no prior points has no
band and is skipped (you cannot regress against nothing), so the gate is
safe to run on a fresh repo.

Exit is nonzero with the offending ``(tool, name)`` named — the contract
``tests/test_fleet_observability.py::test_gate_passes_on_committed_artifacts``
wires into the fast tier, so a BENCH artifact landing outside the
recorded band fails loudly instead of silently re-anchoring the record.

The default tolerance (0.5) is deliberately wide: the measured record
itself documents 1.7× run-to-run tunnel variance on the whole-epoch
kernel (docs/performance.md) — the gate exists to catch
order-of-methodology breakage (a broken barrier, a silently serialized
path), not to flag noise. Tighten per-call once a series is stable.

jax-free (lean-import convention): reads JSON files only.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Units where smaller is better: only an INCREASE past the band fails.
# ``requests`` counts FAILED requests (serve_bench fleet row): the whole
# point of that series is catching the count going UP from 0. ``bytes``/
# ``bytes/token`` are comm payloads (diloco_bench's comm_bytes_per_token,
# round 17): traffic creeping back UP past the compressed record is the
# regression. ``us``/``µs`` variants (round 18): the decode-latency
# series (serve_bench's decode_us_per_token) are microsecond-scale —
# before this entry a us-unit latency series silently gated FAIL-LOW,
# i.e. it would have flagged an IMPROVEMENT and waved regressions
# through (direction pinned in tests/test_fleet_observability.py).
# ``dispatches/token`` (round 20): the decode megakernel's structural
# launch count — more launches per token is the regression (the whole
# point of the tier is O(1)); fails HIGH, direction pinned alongside
# the us variants. ``shed_rate`` (round 21): the per-class load-shed
# fraction under the fixed overload scenario — MORE shedding at the
# same offered load is a scheduling/capacity regression; fails HIGH.
LOWER_IS_BETTER_UNITS = (
    "ms", "s", "ms/token", "ms/dispatch", "requests", "bytes",
    "bytes/token", "us", "µs", "us/token", "µs/token",
    "dispatches/token", "shed_rate", "bytes/req",
)

DEFAULT_TOLERANCE = 0.5


def bench_series(root: str | None = None) -> dict:
    """The driver trajectory as gate series: ``(("driver", metric)) →
    [(ordinal, value, unit), ...]`` ordered oldest→newest, from every
    parseable ``BENCH_r*.json`` at the repo root."""
    from distributed_tensorflow_tpu.tools.perf_record import _BENCH, repo_root

    root = root or repo_root()
    rows = []
    for name in os.listdir(root):
        m = _BENCH.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(root, name)) as f:
                parsed = json.load(f).get("parsed") or {}
        except (OSError, ValueError):
            continue
        if "value" not in parsed:
            continue
        rows.append(
            (
                int(m.group(1)),
                parsed.get("metric", "value"),
                float(parsed["value"]),
                parsed.get("unit", ""),
            )
        )
    series: dict = {}
    for n, metric, value, unit in sorted(rows):
        series.setdefault(("driver", metric), []).append((n, value, unit))
    return series


def journal_series(path: str) -> dict:
    """``bench_point`` journal events as gate series, grouped by
    ``(tool, name, device)`` in emission order (the journal IS the
    trajectory: every ``--write-docs`` run appends, so history
    accumulates). Device is part of the identity: the committed record
    mixes CPU-container and tunnel-TPU reruns of the same metric whose
    values differ by orders of magnitude — one band over both would fail
    every legitimate device switch and mask real same-device
    regressions. A device's first point starts a fresh series (skipped,
    nothing prior), so a chip rerun never trips the gate by existing."""
    from distributed_tensorflow_tpu.observability.journal import read_events

    series: dict = {}
    for i, ev in enumerate(read_events(path, kind="bench_point")):
        if ev.get("value") is None:
            continue
        key = (
            str(ev.get("tool")),
            str(ev.get("name")),
            str(ev.get("device") or ""),
        )
        series.setdefault(key, []).append(
            (i, float(ev["value"]), str(ev.get("unit") or ""))
        )
    return series


def check_series(series: dict, tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Gate every series: latest vs the band of its prior points. Keys
    are ``(tool, name)`` or ``(tool, name, device)`` — the optional
    device member rides into the records untouched. Returns
    ``{"checked": n, "skipped": [...], "failures": [...]}`` — each
    failure names tool/name(/device), the latest value, and the violated
    band edge."""
    checked, skipped, failures = 0, [], []
    for key, points in sorted(series.items()):
        tool, name = key[0], key[1]
        device = key[2] if len(key) > 2 and key[2] else None
        ident = {"tool": tool, "name": name}
        if device:
            ident["device"] = device
        if len(points) < 2:
            skipped.append({**ident, "reason": "no prior points"})
            continue
        checked += 1
        *prior, (_, latest, unit) = points
        values = [v for _, v, _ in prior]
        lo, hi = min(values), max(values)
        lower_better = unit in LOWER_IS_BETTER_UNITS
        if lower_better and latest > hi * (1.0 + tolerance):
            failures.append(
                {
                    **ident,
                    "value": latest,
                    "unit": unit,
                    "band_max": hi,
                    "allowed": round(hi * (1.0 + tolerance), 6),
                    "direction": "above",
                }
            )
        elif not lower_better and latest < lo * (1.0 - tolerance):
            failures.append(
                {
                    **ident,
                    "value": latest,
                    "unit": unit,
                    "band_min": lo,
                    "allowed": round(lo * (1.0 - tolerance), 6),
                    "direction": "below",
                }
            )
    return {"checked": checked, "skipped": skipped, "failures": failures}


def gate(
    *,
    journal: str | None = None,
    bench_root: str | None = None,
    tolerance: float = DEFAULT_TOLERANCE,
) -> dict:
    """Collect every available series (journal + driver trajectory) and
    check them. Missing sources are skipped cleanly — no journal and no
    artifacts means 0 checked, exit 0 (nothing to regress against)."""
    series: dict = {}
    if journal and os.path.exists(journal):
        series.update(journal_series(journal))
    series.update(bench_series(bench_root))
    result = check_series(series, tolerance)
    result["tolerance"] = tolerance
    return result


def default_journal() -> str:
    from distributed_tensorflow_tpu.tools.perf_record import repo_root

    return os.path.join(repo_root(), "docs", "benchmarks", "events.jsonl")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--journal",
        default=default_journal(),
        help="bench_point events.jsonl (default: docs/benchmarks/"
        "events.jsonl; missing file = journal series skipped)",
    )
    ap.add_argument(
        "--bench-root",
        default=None,
        help="directory holding BENCH_r*.json (default: the repo root)",
    )
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE)
    ap.add_argument("--json", action="store_true", help="print the result dict")
    args = ap.parse_args(argv)
    result = gate(
        journal=args.journal,
        bench_root=args.bench_root,
        tolerance=args.tolerance,
    )
    if args.json:
        print(json.dumps(result))
    else:
        print(
            f"regression gate: {result['checked']} series checked, "
            f"{len(result['skipped'])} skipped (single point), "
            f"{len(result['failures'])} outside the band "
            f"(tolerance {result['tolerance']})"
        )
        for f in result["failures"]:
            edge = (
                f"> {f['allowed']} (band max {f['band_max']})"
                if f["direction"] == "above"
                else f"< {f['allowed']} (band min {f['band_min']})"
            )
            dev = f" [{f['device']}]" if f.get("device") else ""
            print(
                f"REGRESSION {f['tool']}/{f['name']}{dev}: {f['value']} "
                f"{f['unit']} {edge}"
            )
    return 1 if result["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
